"""Query DSL: JSON -> typed query tree.

Mirrors the role of the reference's 48 QueryBuilders (index/query/*.java,
registered in SearchModule.java:265) — each DSL object parses into a typed
node that the executor compiles to device score/mask programs. The set here
covers the core retrieval surface plus the BASELINE capabilities (knn,
text_expansion, rank_feature) the reference snapshot lacks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from elasticsearch_tpu.utils.errors import QueryParsingError


class Query:
    """Base query node."""
    boost: float = 1.0


@dataclass
class MatchAll(Query):
    boost: float = 1.0


@dataclass
class MatchNone(Query):
    boost: float = 1.0


@dataclass
class Match(Query):
    field: str
    text: str
    operator: str = "or"            # or | and
    minimum_should_match: Optional[int] = None
    boost: float = 1.0


@dataclass
class MatchPhrase(Query):
    field: str
    text: str
    slop: int = 0
    boost: float = 1.0


@dataclass
class MultiMatch(Query):
    fields: List[str]
    text: str
    type: str = "best_fields"       # best_fields | most_fields
    operator: str = "or"
    boost: float = 1.0


@dataclass
class Term(Query):
    field: str
    value: Any
    boost: float = 1.0


@dataclass
class Terms(Query):
    field: str
    values: List[Any]
    boost: float = 1.0


@dataclass
class Range(Query):
    field: str
    gt: Optional[Any] = None
    gte: Optional[Any] = None
    lt: Optional[Any] = None
    lte: Optional[Any] = None
    # interval relation against RANGE fields (RangeFieldMapper):
    # intersects (default) | within | contains
    relation: str = "intersects"
    boost: float = 1.0


@dataclass
class Exists(Query):
    field: str
    boost: float = 1.0


@dataclass
class Ids(Query):
    values: List[str]
    boost: float = 1.0


@dataclass
class Prefix(Query):
    field: str
    value: str
    boost: float = 1.0


@dataclass
class Wildcard(Query):
    field: str
    value: str
    boost: float = 1.0


@dataclass
class Regexp(Query):
    field: str
    value: str
    boost: float = 1.0


@dataclass
class Fuzzy(Query):
    field: str
    value: str
    fuzziness: Any = "AUTO"
    boost: float = 1.0


@dataclass
class Bool(Query):
    must: List[Query] = field(default_factory=list)
    should: List[Query] = field(default_factory=list)
    must_not: List[Query] = field(default_factory=list)
    filter: List[Query] = field(default_factory=list)
    minimum_should_match: Optional[int] = None
    boost: float = 1.0


@dataclass
class ConstantScore(Query):
    filter: Query = None
    boost: float = 1.0


@dataclass
class DisMax(Query):
    queries: List[Query] = field(default_factory=list)
    tie_breaker: float = 0.0
    boost: float = 1.0


@dataclass
class Boosting(Query):
    positive: Query = None
    negative: Query = None
    negative_boost: float = 0.5
    boost: float = 1.0


@dataclass
class Knn(Query):
    field: str
    query_vector: List[float]
    k: int = 10
    num_candidates: int = 100
    filter: Optional[Query] = None
    boost: float = 1.0


@dataclass
class RankFeature(Query):
    field: str
    function: str = "saturation"     # saturation | log | sigmoid | linear
    pivot: float = 1.0
    exponent: float = 1.0
    scaling_factor: float = 1.0
    boost: float = 1.0


@dataclass
class TextExpansion(Query):
    """Learned-sparse query over a rank_features field (ELSER analog).

    Either ``tokens`` carries precomputed inference output, or
    ``model_text`` triggers on-device expansion through the registered
    model at query time (TextExpansionQueryBuilder's inference rewrite,
    re-done as a local jitted program — ml/text_expansion.py)."""
    field: str
    tokens: Optional[Dict[str, float]] = None
    model_id: Optional[str] = None
    model_text: Optional[str] = None
    boost: float = 1.0


@dataclass
class ScriptScore(Query):
    """script_score with the reference's vector-function surface
    (cosineSimilarity / dotProduct / l2norm — ScoreScriptUtils.java:132,151)."""
    query: Query = None
    source: str = ""
    params: Dict[str, Any] = field(default_factory=dict)
    boost: float = 1.0


@dataclass
class FunctionScore(Query):
    query: Query = None
    functions: List[Dict[str, Any]] = field(default_factory=list)
    boost_mode: str = "multiply"
    score_mode: str = "sum"
    boost: float = 1.0


@dataclass
class HasChild(Query):
    """Parents with at least one matching child
    (modules/parent-join HasChildQueryBuilder analog)."""
    child_type: str = ""
    query: Query = None
    min_children: int = 1
    boost: float = 1.0


@dataclass
class HasParent(Query):
    """Children whose parent matches
    (modules/parent-join HasParentQueryBuilder analog)."""
    parent_type: str = ""
    query: Query = None
    boost: float = 1.0


@dataclass
class ParentId(Query):
    """Children of one specific parent (ParentIdQueryBuilder analog)."""
    child_type: str = ""
    id: str = ""
    boost: float = 1.0


@dataclass
class MatchPhrasePrefix(Query):
    """Phrase with the LAST term as a prefix (search-as-you-type;
    index/query/MatchPhrasePrefixQueryBuilder analog)."""
    field: str = ""
    text: str = ""
    max_expansions: int = 50
    boost: float = 1.0


@dataclass
class MoreLikeThis(Query):
    """Find docs similar to free text: top tf-idf terms become a should
    query (index/query/MoreLikeThisQueryBuilder analog)."""
    fields: List[str] = field(default_factory=list)
    like: List[str] = field(default_factory=list)
    max_query_terms: int = 25
    min_term_freq: int = 2      # MoreLikeThisQueryBuilder defaults
    min_doc_freq: int = 5
    boost: float = 1.0


@dataclass
class GeoDistance(Query):
    """Docs whose geo_point lies within ``distance`` meters of a center
    (index/query/GeoDistanceQueryBuilder analog)."""
    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    distance_m: float = 0.0
    boost: float = 1.0


@dataclass
class GeoBoundingBox(Query):
    """Docs whose geo_point lies inside the box
    (index/query/GeoBoundingBoxQueryBuilder analog)."""
    field: str = ""
    top: float = 0.0
    left: float = 0.0
    bottom: float = 0.0
    right: float = 0.0
    boost: float = 1.0


_DISTANCE_UNITS = (   # longest suffix first so 'nmi' wins over 'mi'/'m'
    ("nmi", 1852.0), ("km", 1000.0), ("cm", 0.01), ("mm", 0.001),
    ("mi", 1609.344), ("yd", 0.9144), ("ft", 0.3048), ("in", 0.0254),
    ("nm", 1852.0), ("m", 1.0),
)


def parse_distance_m(raw: Any) -> float:
    """ES distance expression -> meters ('10km', '3mi', '500ft', number)."""
    if isinstance(raw, (int, float)):
        return float(raw)
    s = str(raw).strip().lower()
    try:
        for suffix, mult in _DISTANCE_UNITS:
            if s.endswith(suffix):
                return float(s[: -len(suffix)]) * mult
        return float(s)
    except (TypeError, ValueError):
        raise QueryParsingError(f"failed to parse distance [{raw!r}]")


def _parse_geo_point(spec: Any) -> Tuple[float, float]:
    if isinstance(spec, dict):
        return float(spec["lat"]), float(spec["lon"])
    if isinstance(spec, (list, tuple)) and len(spec) == 2:
        return float(spec[1]), float(spec[0])     # [lon, lat] GeoJSON order
    if isinstance(spec, str):
        lat, _, lon = spec.partition(",")
        return float(lat), float(lon)
    raise QueryParsingError(f"cannot parse geo point [{spec!r}]")


def _parse_geo_distance(spec: Dict[str, Any]) -> GeoDistance:
    opts = {k: v for k, v in spec.items()
            if k not in ("distance", "boost", "distance_type",
                         "validation_method")}
    if len(opts) != 1 or "distance" not in spec:
        raise QueryParsingError(
            "geo_distance requires [distance] and exactly one field")
    (fname, point), = opts.items()
    try:
        lat, lon = _parse_geo_point(point)
    except (KeyError, TypeError, ValueError) as e:
        raise QueryParsingError(
            f"failed to parse geo point for [{fname}]: {e}")
    return GeoDistance(field=fname, lat=lat, lon=lon,
                       distance_m=parse_distance_m(spec["distance"]),
                       boost=float(spec.get("boost", 1.0)))


def _parse_geo_bounding_box(spec: Dict[str, Any]) -> GeoBoundingBox:
    opts = {k: v for k, v in spec.items()
            if k not in ("boost", "validation_method", "type")}
    if len(opts) != 1:
        raise QueryParsingError(
            "geo_bounding_box requires exactly one field")
    (fname, box), = opts.items()
    try:
        if "top_left" in box and "bottom_right" in box:
            top, left = _parse_geo_point(box["top_left"])
            bottom, right = _parse_geo_point(box["bottom_right"])
        elif "top_right" in box and "bottom_left" in box:
            top, right = _parse_geo_point(box["top_right"])
            bottom, left = _parse_geo_point(box["bottom_left"])
        elif {"top", "left", "bottom", "right"} <= set(box):
            top, left = float(box["top"]), float(box["left"])
            bottom, right = float(box["bottom"]), float(box["right"])
        else:
            raise QueryParsingError(
                "geo_bounding_box requires corner points "
                "(top_left/bottom_right, top_right/bottom_left, or "
                "top/left/bottom/right)")
    except (KeyError, TypeError, ValueError) as e:
        raise QueryParsingError(
            f"failed to parse geo_bounding_box [{fname}]: {e}")
    return GeoBoundingBox(field=fname, top=top, left=left, bottom=bottom,
                          right=right,
                          boost=float(spec.get("boost", 1.0)))


def _parse_match_phrase_prefix(spec: Dict[str, Any]) -> MatchPhrasePrefix:
    fname, opts = _field_spec(spec, "query")
    return MatchPhrasePrefix(
        field=fname, text=str(opts.get("query", "")),
        max_expansions=int(opts.get("max_expansions", 50)),
        boost=float(opts.get("boost", 1.0)))


def _parse_more_like_this(spec: Dict[str, Any]) -> MoreLikeThis:
    like = spec.get("like")
    if like is None:
        raise QueryParsingError("more_like_this requires [like]")
    likes = like if isinstance(like, list) else [like]
    texts = [x for x in likes if isinstance(x, str)]
    if len(texts) != len(likes):
        # silently narrowing {"_index","_id"} doc references to only the
        # text likes would return different hits with no signal
        raise QueryParsingError(
            "more_like_this supports free-text [like] values only; "
            "document references are not supported")
    if not texts:
        raise QueryParsingError(
            "more_like_this requires at least one [like] text")
    return MoreLikeThis(
        fields=list(spec.get("fields", [])),
        like=texts,
        max_query_terms=int(spec.get("max_query_terms", 25)),
        min_term_freq=int(spec.get("min_term_freq", 2)),
        min_doc_freq=int(spec.get("min_doc_freq", 5)),
        boost=float(spec.get("boost", 1.0)))


@dataclass
class Percolate(Query):
    """Reverse search: which stored queries match this document
    (modules/percolator PercolateQueryBuilder analog)."""
    # NOTE: ``documents`` must precede ``field`` — the attribute named
    # "field" shadows dataclasses.field for the rest of the class body
    documents: List[Dict[str, Any]] = field(default_factory=list)
    field: str = "query"
    boost: float = 1.0


@dataclass
class Nested(Query):
    path: str = ""
    query: Query = None
    score_mode: str = "avg"
    # inner_hits spec ({} = defaults): the fetch phase returns the matching
    # nested objects per hit (InnerHitsPhase analog)
    inner_hits: Optional[Dict[str, Any]] = None
    boost: float = 1.0


# ---------------------------------------------------------------------------
# span family (index/query/Span*QueryBuilder analogs) — position-based
# matching evaluated by search/spans.py
# ---------------------------------------------------------------------------

class SpanQuery(Query):
    """Base for span nodes; every span node names exactly one field."""


@dataclass
class SpanTerm(SpanQuery):
    field: str = ""
    value: str = ""
    boost: float = 1.0


@dataclass
class SpanNear(SpanQuery):
    clauses: List[SpanQuery] = field(default_factory=list)
    slop: int = 0
    in_order: bool = True
    boost: float = 1.0


@dataclass
class SpanOr(SpanQuery):
    clauses: List[SpanQuery] = field(default_factory=list)
    boost: float = 1.0


@dataclass
class SpanNot(SpanQuery):
    include: SpanQuery = None
    exclude: SpanQuery = None
    pre: int = 0
    post: int = 0
    boost: float = 1.0


@dataclass
class SpanFirst(SpanQuery):
    match: SpanQuery = None
    end: int = 0
    boost: float = 1.0


@dataclass
class SpanContaining(SpanQuery):
    big: SpanQuery = None
    little: SpanQuery = None
    boost: float = 1.0


@dataclass
class SpanWithin(SpanQuery):
    big: SpanQuery = None
    little: SpanQuery = None
    boost: float = 1.0


@dataclass
class SpanMulti(SpanQuery):
    """Wraps a multi-term query (prefix/wildcard/regexp/fuzzy) as spans
    (SpanMultiTermQueryWrapper analog)."""
    match: Query = None
    boost: float = 1.0


@dataclass
class Intervals(Query):
    """Minimal-interval matching (index/query/IntervalQueryBuilder analog).
    ``rule`` is the raw source tree (match/any_of/all_of/prefix/wildcard
    with max_gaps/ordered/filter), interpreted by search/spans.py."""
    # NOTE: rule must precede the attribute named "field" (it shadows
    # dataclasses.field for the rest of the class body)
    rule: Dict[str, Any] = field(default_factory=dict)
    field: str = ""
    boost: float = 1.0


@dataclass
class QueryString(Query):
    """Lucene-syntax query string (QueryStringQueryBuilder analog). Parsed
    into a Query tree at rewrite time by search/querystring.py."""
    query: str = ""
    default_field: Optional[str] = None
    fields: List[str] = field(default_factory=list)
    default_operator: str = "or"
    boost: float = 1.0


@dataclass
class SimpleQueryString(Query):
    """Fault-tolerant simplified syntax (SimpleQueryStringBuilder analog)."""
    query: str = ""
    fields: List[str] = field(default_factory=list)
    default_operator: str = "or"
    boost: float = 1.0


@dataclass
class TermsSet(Query):
    """Docs matching >= N of the terms, N read per-doc from
    minimum_should_match_field or computed by a script
    (TermsSetQueryBuilder analog)."""
    # terms must precede the "field" attribute (dataclasses.field shadow)
    terms: List[Any] = field(default_factory=list)
    field: str = ""
    minimum_should_match_field: Optional[str] = None
    minimum_should_match_script: Optional[str] = None
    boost: float = 1.0


@dataclass
class DistanceFeature(Query):
    """Score decays with distance from an origin on a date or geo_point
    field: boost * pivot / (pivot + distance)
    (DistanceFeatureQueryBuilder analog)."""
    field: str = ""
    origin: Any = None
    pivot: Any = None
    boost: float = 1.0


@dataclass
class Pinned(Query):
    """Promoted ids rank first, organic results after
    (x-pack search-business-rules PinnedQueryBuilder analog)."""
    ids: List[str] = field(default_factory=list)
    organic: Query = None
    boost: float = 1.0


@dataclass
class ScriptQuery(Query):
    """Filter context scripted per document over doc values
    (index/query/ScriptQueryBuilder analog)."""
    source: str = ""
    params: Dict[str, Any] = field(default_factory=dict)
    boost: float = 1.0


@dataclass
class GeoShape(Query):
    """Docs whose geo_shape relates to the query geometry
    (GeoShapeQueryBuilder analog)."""
    # shape must precede the "field" attribute (dataclasses.field shadow)
    shape: Dict[str, Any] = field(default_factory=dict)
    field: str = ""
    relation: str = "intersects"
    boost: float = 1.0


@dataclass
class GeoPolygon(Query):
    """Docs whose geo_point lies inside the closed polygon
    (GeoPolygonQueryBuilder analog)."""
    # points must precede the "field" attribute (dataclasses.field shadow)
    points: List[Tuple[float, float]] = field(default_factory=list)  # (lat, lon)
    field: str = ""
    boost: float = 1.0


def parse_query(body: Any) -> Query:
    """Parse the object under "query" into a Query tree."""
    if body is None:
        return MatchAll()
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError(
            f"query must be an object with exactly one key, got {body!r}")
    (kind, spec), = body.items()
    parser = _PARSERS.get(kind)
    if parser is None:
        raise QueryParsingError(f"unknown query type [{kind}]")
    if isinstance(spec, dict) and "_name" in spec:
        # clause-level _name (named queries) is metadata for the fetch
        # phase's matched_queries, never part of the clause body — strip
        # it HERE so single-field parsers don't count it as a field
        spec = {k: v for k, v in spec.items() if k != "_name"}
    return parser(spec)


def _field_spec(spec: Dict[str, Any], value_key: str) -> tuple:
    """Unpack {"field": <value-or-options>} into (field, options-dict)."""
    if not isinstance(spec, dict) or len(spec) != 1:
        raise QueryParsingError(f"expected single-field object, got {spec!r}")
    (fname, opts), = spec.items()
    if not isinstance(opts, dict):
        opts = {value_key: opts}
    return fname, opts


def _parse_match(spec):
    fname, opts = _field_spec(spec, "query")
    return Match(field=fname, text=str(opts.get("query", "")),
                 operator=str(opts.get("operator", "or")).lower(),
                 minimum_should_match=opts.get("minimum_should_match"),
                 boost=float(opts.get("boost", 1.0)))


def _parse_match_phrase(spec):
    fname, opts = _field_spec(spec, "query")
    return MatchPhrase(field=fname, text=str(opts.get("query", "")),
                       slop=int(opts.get("slop", 0)),
                       boost=float(opts.get("boost", 1.0)))


def _parse_multi_match(spec):
    if "fields" not in spec:
        raise QueryParsingError("multi_match requires [fields]")
    return MultiMatch(fields=list(spec["fields"]), text=str(spec.get("query", "")),
                      type=spec.get("type", "best_fields"),
                      operator=str(spec.get("operator", "or")).lower(),
                      boost=float(spec.get("boost", 1.0)))


def _parse_term(spec):
    fname, opts = _field_spec(spec, "value")
    return Term(field=fname, value=opts.get("value"),
                boost=float(opts.get("boost", 1.0)))


def _parse_terms(spec):
    spec = dict(spec)
    boost = float(spec.pop("boost", 1.0))
    if len(spec) != 1:
        raise QueryParsingError("terms query requires exactly one field")
    (fname, values), = spec.items()
    if not isinstance(values, list):
        raise QueryParsingError("terms query values must be an array")
    return Terms(field=fname, values=values, boost=boost)


def _parse_range(spec):
    fname, opts = _field_spec(spec, "gte")
    relation = str(opts.get("relation", "intersects")).lower()
    if relation not in ("intersects", "within", "contains"):
        raise QueryParsingError(
            f"unknown range relation [{relation}]")
    return Range(field=fname, gt=opts.get("gt"), gte=opts.get("gte"),
                 lt=opts.get("lt"), lte=opts.get("lte"),
                 relation=relation,
                 boost=float(opts.get("boost", 1.0)))


def _parse_bool(spec):
    def clause(name):
        v = spec.get(name, [])
        if isinstance(v, dict):
            v = [v]
        return [parse_query(q) for q in v]
    return Bool(must=clause("must"), should=clause("should"),
                must_not=clause("must_not"), filter=clause("filter"),
                minimum_should_match=spec.get("minimum_should_match"),
                boost=float(spec.get("boost", 1.0)))


def _parse_knn(spec):
    return Knn(field=spec["field"], query_vector=list(spec["query_vector"]),
               k=int(spec.get("k", 10)),
               num_candidates=int(spec.get("num_candidates", 100)),
               filter=parse_query(spec["filter"]) if spec.get("filter") else None,
               boost=float(spec.get("boost", 1.0)))


def _parse_rank_feature(spec):
    fname = spec.get("field")
    if fname is None:
        raise QueryParsingError("rank_feature requires [field]")
    function, pivot, exponent, scaling = "saturation", 1.0, 1.0, 1.0
    if "saturation" in spec:
        function = "saturation"
        pivot = float((spec["saturation"] or {}).get("pivot", 1.0))
    elif "log" in spec:
        function = "log"
        scaling = float((spec["log"] or {}).get("scaling_factor", 1.0))
    elif "sigmoid" in spec:
        function = "sigmoid"
        sig = spec["sigmoid"] or {}
        pivot = float(sig.get("pivot", 1.0))
        exponent = float(sig.get("exponent", 1.0))
    elif "linear" in spec:
        function = "linear"
    return RankFeature(field=fname, function=function, pivot=pivot,
                       exponent=exponent, scaling_factor=scaling,
                       boost=float(spec.get("boost", 1.0)))


def _parse_text_expansion(spec):
    fname, opts = _field_spec(spec, "model_text")
    tokens = opts.get("tokens")
    model_text = opts.get("model_text")
    if tokens is None and model_text is None:
        raise QueryParsingError(
            "text_expansion requires [tokens] (precomputed inference "
            "output) or [model_text] (on-device expansion)")
    return TextExpansion(
        field=fname,
        tokens=({str(k): float(v) for k, v in tokens.items()}
                if tokens is not None else None),
        model_id=opts.get("model_id"),
        model_text=model_text,
        boost=float(opts.get("boost", 1.0)))


def _parse_script_score(spec):
    script = spec.get("script", {})
    return ScriptScore(query=parse_query(spec.get("query")),
                       source=script.get("source", ""),
                       params=script.get("params", {}),
                       boost=float(spec.get("boost", 1.0)))


def _parse_function_score(spec):
    return FunctionScore(query=parse_query(spec.get("query")),
                         functions=list(spec.get("functions", [])),
                         boost_mode=spec.get("boost_mode", "multiply"),
                         score_mode=spec.get("score_mode", "sum"),
                         boost=float(spec.get("boost", 1.0)))


_PARSERS = {
    "match_all": lambda spec: MatchAll(boost=float((spec or {}).get("boost", 1.0))),
    "match_none": lambda spec: MatchNone(),
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "match_phrase_prefix": _parse_match_phrase_prefix,
    "more_like_this": _parse_more_like_this,
    "geo_distance": _parse_geo_distance,
    "geo_bounding_box": _parse_geo_bounding_box,
    "multi_match": _parse_multi_match,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "exists": lambda spec: Exists(field=spec["field"],
                                  boost=float(spec.get("boost", 1.0))),
    "ids": lambda spec: Ids(values=[str(v) for v in spec.get("values", [])]),
    "prefix": lambda spec: Prefix(*_field_value(spec, "value")),
    "wildcard": lambda spec: Wildcard(*_field_value(spec, "value")),
    "regexp": lambda spec: Regexp(*_field_value(spec, "value")),
    "fuzzy": lambda spec: _parse_fuzzy(spec),
    "bool": _parse_bool,
    "constant_score": lambda spec: ConstantScore(
        filter=parse_query(spec.get("filter")), boost=float(spec.get("boost", 1.0))),
    "dis_max": lambda spec: DisMax(
        queries=[parse_query(q) for q in spec.get("queries", [])],
        tie_breaker=float(spec.get("tie_breaker", 0.0)),
        boost=float(spec.get("boost", 1.0))),
    "boosting": lambda spec: Boosting(
        positive=parse_query(spec.get("positive")),
        negative=parse_query(spec.get("negative")),
        negative_boost=float(spec.get("negative_boost", 0.5)),
        boost=float(spec.get("boost", 1.0))),
    "knn": _parse_knn,
    "has_child": lambda spec: HasChild(
        child_type=spec["type"], query=parse_query(spec.get("query")),
        min_children=int(spec.get("min_children", 1)),
        boost=float(spec.get("boost", 1.0))),
    "has_parent": lambda spec: HasParent(
        parent_type=spec["parent_type"],
        query=parse_query(spec.get("query")),
        boost=float(spec.get("boost", 1.0))),
    "parent_id": lambda spec: ParentId(
        child_type=spec["type"], id=str(spec["id"]),
        boost=float(spec.get("boost", 1.0))),
    "percolate": lambda spec: Percolate(
        field=spec.get("field", "query"),
        documents=(spec.get("documents")
                   or ([spec["document"]] if "document" in spec else [])),
        boost=float(spec.get("boost", 1.0))),
    "nested": lambda spec: Nested(
        path=spec["path"], query=parse_query(spec.get("query")),
        score_mode=spec.get("score_mode", "avg"),
        inner_hits=spec.get("inner_hits"),
        boost=float(spec.get("boost", 1.0))),
    "rank_feature": _parse_rank_feature,
    "text_expansion": _parse_text_expansion,
    "script_score": _parse_script_score,
    "function_score": _parse_function_score,
    "span_term": lambda spec: _parse_span_term(spec),
    "span_near": lambda spec: SpanNear(
        clauses=[_parse_span(c) for c in spec.get("clauses", [])],
        slop=int(spec.get("slop", 0)),
        in_order=bool(spec.get("in_order", True)),
        boost=float(spec.get("boost", 1.0))),
    "span_or": lambda spec: SpanOr(
        clauses=[_parse_span(c) for c in spec.get("clauses", [])],
        boost=float(spec.get("boost", 1.0))),
    "span_not": lambda spec: SpanNot(
        include=_parse_span(spec["include"]),
        exclude=_parse_span(spec["exclude"]),
        pre=int(spec.get("pre", spec.get("dist", 0))),
        post=int(spec.get("post", spec.get("dist", 0))),
        boost=float(spec.get("boost", 1.0))),
    "span_first": lambda spec: SpanFirst(
        match=_parse_span(spec["match"]),
        end=int(spec.get("end", 0)),
        boost=float(spec.get("boost", 1.0))),
    "span_containing": lambda spec: SpanContaining(
        big=_parse_span(spec["big"]), little=_parse_span(spec["little"]),
        boost=float(spec.get("boost", 1.0))),
    "span_within": lambda spec: SpanWithin(
        big=_parse_span(spec["big"]), little=_parse_span(spec["little"]),
        boost=float(spec.get("boost", 1.0))),
    "span_multi": lambda spec: SpanMulti(
        match=parse_query(spec["match"]),
        boost=float(spec.get("boost", 1.0))),
    "intervals": lambda spec: _parse_intervals(spec),
    "query_string": lambda spec: QueryString(
        query=str(spec.get("query", "")),
        default_field=spec.get("default_field"),
        fields=list(spec.get("fields", [])),
        default_operator=str(spec.get("default_operator", "or")).lower(),
        boost=float(spec.get("boost", 1.0))),
    "simple_query_string": lambda spec: SimpleQueryString(
        query=str(spec.get("query", "")),
        fields=list(spec.get("fields", [])),
        default_operator=str(spec.get("default_operator", "or")).lower(),
        boost=float(spec.get("boost", 1.0))),
    "terms_set": lambda spec: _parse_terms_set(spec),
    "distance_feature": lambda spec: DistanceFeature(
        field=spec["field"], origin=spec.get("origin"),
        pivot=spec.get("pivot"),
        boost=float(spec.get("boost", 1.0))),
    "pinned": lambda spec: Pinned(
        ids=[str(i) for i in spec.get("ids", [])],
        organic=parse_query(spec.get("organic")),
        boost=float(spec.get("boost", 1.0))),
    "script": lambda spec: ScriptQuery(
        source=(spec.get("script") or {}).get("source", "")
        if isinstance(spec.get("script"), dict) else str(spec.get("script", "")),
        params=((spec.get("script") or {}).get("params", {})
                if isinstance(spec.get("script"), dict) else {}),
        boost=float(spec.get("boost", 1.0))),
    "wrapper": lambda spec: _parse_wrapper(spec),
    "geo_polygon": lambda spec: _parse_geo_polygon(spec),
    "geo_shape": lambda spec: _parse_geo_shape(spec),
    # match_bool_prefix: every term matches normally, the last as a
    # prefix (MatchBoolPrefixQueryBuilder) — the single-field form of
    # multi_match type bool_prefix
    "match_bool_prefix": lambda spec: _parse_match_bool_prefix(spec),
}


def _parse_match_bool_prefix(spec) -> MultiMatch:
    fname, opts = _field_spec(spec, "query")
    return MultiMatch(fields=[fname], text=str(opts.get("query", "")),
                      type="bool_prefix",
                      operator=str(opts.get("operator", "or")).lower(),
                      boost=float(opts.get("boost", 1.0)))


def _parse_geo_shape(spec) -> GeoShape:
    opts = {k: v for k, v in spec.items()
            if k not in ("boost", "ignore_unmapped")}
    if len(opts) != 1:
        raise QueryParsingError("geo_shape requires exactly one field")
    (fname, body), = opts.items()
    if not isinstance(body, dict) or "shape" not in body:
        raise QueryParsingError("geo_shape requires [shape]")
    relation = str(body.get("relation", "intersects")).lower()
    if relation not in ("intersects", "disjoint", "within", "contains"):
        raise QueryParsingError(
            f"unknown geo_shape relation [{relation}]")
    return GeoShape(field=fname, shape=body["shape"], relation=relation,
                    boost=float(spec.get("boost", 1.0)))


def _parse_span_term(spec) -> SpanTerm:
    fname, opts = _field_spec(spec, "value")
    return SpanTerm(field=fname, value=str(opts.get("value", "")),
                    boost=float(opts.get("boost", 1.0)))


def _parse_span(body: Any) -> SpanQuery:
    q = parse_query(body)
    if not isinstance(q, (SpanQuery,)):
        raise QueryParsingError(
            f"expected a span query, got [{type(q).__name__}]")
    return q


def _parse_intervals(spec) -> Intervals:
    fname, rule = _field_spec(spec, "match")
    boost = float(rule.pop("boost", 1.0)) if isinstance(rule, dict) else 1.0
    if not isinstance(rule, dict) or len(rule) != 1:
        raise QueryParsingError(
            "intervals requires exactly one rule (match/any_of/all_of/"
            "prefix/wildcard)")
    return Intervals(field=fname, rule=rule, boost=boost)


def _parse_terms_set(spec) -> TermsSet:
    fname, opts = _field_spec(spec, "terms")
    script = opts.get("minimum_should_match_script")
    if isinstance(script, dict):
        script = script.get("source", "")
    return TermsSet(
        field=fname, terms=list(opts.get("terms", [])),
        minimum_should_match_field=opts.get("minimum_should_match_field"),
        minimum_should_match_script=script,
        boost=float(opts.get("boost", 1.0)))


def _parse_wrapper(spec) -> Query:
    import base64
    import json as _json
    raw = spec.get("query")
    if raw is None:
        raise QueryParsingError("wrapper requires [query]")
    try:
        body = _json.loads(base64.b64decode(raw))
    except Exception as e:  # noqa: BLE001 — surface as a parse error
        raise QueryParsingError(f"failed to decode wrapper query: {e}")
    return parse_query(body)


def _parse_geo_polygon(spec) -> GeoPolygon:
    opts = {k: v for k, v in spec.items()
            if k not in ("boost", "validation_method")}
    if len(opts) != 1:
        raise QueryParsingError("geo_polygon requires exactly one field")
    (fname, poly), = opts.items()
    pts = [_parse_geo_point(p) for p in (poly or {}).get("points", [])]
    if len(pts) < 3:
        raise QueryParsingError("geo_polygon requires at least 3 points")
    return GeoPolygon(field=fname, points=pts,
                      boost=float(spec.get("boost", 1.0)))


def _field_value(spec, key):
    fname, opts = _field_spec(spec, key)
    return fname, str(opts.get(key, "")), float(opts.get("boost", 1.0))


def collect_named_queries(body_query: Any
                          ) -> List[Tuple[str, Dict[str, Any]]]:
    """[(name, clause_json)] for every ``_name``-tagged clause in a raw
    request query (search/fetch/subphase/MatchedQueriesPhase.java:43's
    named-weight registry, gathered at the JSON level so every query type
    participates without per-parser changes). The name may sit at the
    clause level ({"bool": {..., "_name": n}}) or inside field options
    ({"match": {"f": {"query": ..., "_name": n}}})."""
    out: List[Tuple[str, Dict[str, Any]]] = []

    def walk(node: Any) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                if k in _PARSERS and isinstance(v, dict):
                    name = v.get("_name")
                    if name is None:
                        for fv in v.values():
                            if isinstance(fv, dict) and "_name" in fv:
                                name = fv["_name"]
                                break
                    if name is not None:
                        out.append((str(name), {k: v}))
                walk(v)
        elif isinstance(node, list):
            for item in node:
                walk(item)

    walk(body_query)
    return out


def disjunctive_clauses(q: Query
                        ) -> Optional[Tuple[str, List[Tuple[str, float]]]]:
    """(field, [(text, boost)]) when the query is a pure disjunctive
    text-scoring shape — a Match with OR semantics, or a Bool of ONLY
    should Match clauses (default/1 minimum_should_match) on one field.
    Returns None otherwise.

    ONE definition shared by the shard WAND collector
    (search/phase.py wand_clauses) and the mesh one-program path
    (parallel/mesh_plane.py mesh_eligible) so their eligibility rules
    cannot drift. Field-type checks stay with the callers (they own the
    mappers)."""
    if isinstance(q, Match):
        if q.operator == "and" or q.minimum_should_match is not None:
            return None
        return q.field, [(q.text, q.boost)]
    if isinstance(q, Bool):
        if q.must or q.must_not or q.filter or not q.should:
            return None
        if q.minimum_should_match not in (None, 0, 1, "1"):
            return None
        field: Optional[str] = None
        clauses: List[Tuple[str, float]] = []
        for c in q.should:
            if not isinstance(c, Match) or c.operator == "and" \
                    or c.minimum_should_match is not None:
                return None
            if field is None:
                field = c.field
            elif field != c.field:
                return None   # one postings executor per (segment, field)
            clauses.append((c.text, c.boost * q.boost))
        if field is None:
            return None
        return field, clauses
    return None


def resolve_minimum_should_match(msm: Any, n_clauses: int) -> int:
    """ES minimum_should_match forms: 3, "3", "-1", "75%", "-25%"."""
    if msm is None:
        return 0
    if isinstance(msm, int):
        value = msm
    else:
        s = str(msm).strip()
        if s.endswith("%"):
            pct = float(s[:-1])
            if pct < 0:
                value = n_clauses - int(n_clauses * (-pct) / 100.0)
            else:
                value = int(n_clauses * pct / 100.0)
        else:
            value = int(s)
    if value < 0:
        value = n_clauses + value
    return max(0, min(value, n_clauses))


def _parse_fuzzy(spec):
    fname, opts = _field_spec(spec, "value")
    return Fuzzy(field=fname, value=str(opts.get("value", "")),
                 fuzziness=opts.get("fuzziness", "AUTO"),
                 boost=float(opts.get("boost", 1.0)))
