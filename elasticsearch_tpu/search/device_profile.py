"""Device observatory: XLA compile/recompile tracking, per-kernel cost
attribution, and the profiled-jit wrapper every device kernel routes
through.

PR 8 made every *request* observable; the layer that actually determines
TPU throughput — XLA compilation and per-kernel execution — stayed
invisible. Every ops file hand-tunes pow2 shape bucketing ("one distinct
gather shape costs a full XLA compile (~seconds)", ops/bm25.py
``qb_bucket``; "pow-2 shapes keep the compile cache to ~9 entries",
ops/ivf.py ``search``) yet nothing measured whether those invariants
held: a padding-policy regression would surface only as an unexplained
p99 cliff. This module is the measurement:

- :func:`profiled_jit` / :func:`profiled_callable` — THE way a kernel
  under ``ops/``, ``search/`` or ``parallel/mesh.py`` gets staged. The
  wrapper jits the function and, per concrete call, detects whether the
  call compiled (the jitted function's own executable-cache size is the
  authoritative signal; a host-side shape-bucket mirror is the fallback
  when that private surface moves) and reports to the process-global
  :class:`DeviceProfile` registry. A grep-guard test pins raw-jit call
  sites at zero, the PR 8 "unknown fallback reason pinned at zero"
  precedent — an uninstrumented new kernel fails CI.
- :class:`DeviceProfile` (process-global ``DEVICE_PROFILE``, the PLANES /
  TELEMETRY one-accelerator-per-process precedent): per kernel-family
  compile counts vs cache hits, compile wall-time (total / max), live
  shape-bucket cardinality, a **recompile-storm detector** (a counter +
  slow-compile log line when a family crosses a configurable
  distinct-compile rate), a measured execute-time EWMA per
  (family, shape bucket), and guarded ``lowered.cost_analysis()``
  FLOPs / bytes estimates where the backend exposes them.
- Request attribution rides the PR 8 contextvar trace: jitted functions
  cannot self-report, so the host-side wrapper is the dispatch seam —
  a compile inside an active :class:`~.telemetry.SearchTrace` adds a
  ``compile`` span (``profile: true`` responses show ``compile_ms``) and
  flags the trace so slow logs mark first-compile requests. Profile-off
  responses stay byte-identical: nothing here ever touches a response.

Timing semantics (honest by construction, documented so nobody reads
more into them): JAX dispatch is asynchronous, and telemetry never pays
a device sync — so the execute EWMA measures host-observed call wall
time (dispatch + any internal syncs), and compile wall time is the
first-call wall time for a shape bucket (trace + XLA compile dominate
it). The bench ``--device-profile`` gate, which DOES block on results,
is where true device-side steady-state numbers come from.

Import discipline: this module imports only the stdlib and its sibling
``telemetry`` at load time (``jax`` lazily, at first wrap) so the ops
modules can import it at module top without cycling through the search
package's serving stack.
"""

from __future__ import annotations

import inspect
import logging
import time
from typing import Any, Callable, Dict, Optional, Tuple

from elasticsearch_tpu.search import telemetry

logger = logging.getLogger(__name__)

# execute-time EWMA smoothing (the NodePressure / C3 alpha family)
EWMA_ALPHA = 0.2

# per-family bound on the (shape bucket -> EWMA / cost) maps: bucket
# labels derive from call shapes, so a pathological caller must not grow
# node memory forever; compiles themselves stay exactly counted
MAX_BUCKETS_PER_FAMILY = 256

_TRACER_TYPE: Any = None


def _tracer_type():
    """jax's Tracer type, resolved lazily (public path first)."""
    global _TRACER_TYPE
    if _TRACER_TYPE is None:
        try:
            from jax.core import Tracer
        except Exception:  # noqa: BLE001 — moved in newer jax
            from jax._src.core import Tracer
        _TRACER_TYPE = Tracer
    return _TRACER_TYPE


def _describe_dynamic(v: Any) -> str:
    """Shape-bucket component for one traced argument: dtype[shape] for
    arrays, the bare type name for weakly-typed scalars (jax caches by
    dtype, not value — a per-value label would explode the bucket map
    without any recompile behind it)."""
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(int(d)) for d in shape)}]"
    if v is None:
        return "None"
    return type(v).__name__


class FamilyProfile:
    """One kernel family's observatory record."""

    __slots__ = ("name", "compiles", "cache_hits", "compile_ns_total",
                 "compile_ns_max", "shapes", "execute", "execute_device",
                 "cost", "compile_marks", "storms")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.cache_hits = 0
        self.compile_ns_total = 0
        self.compile_ns_max = 0
        # shape-bucket label -> compile count (cardinality == the live
        # compile-cache size the pow2 bucketing invariants promise)
        self.shapes: Dict[str, int] = {}
        # shape-bucket label -> [ewma_ms, observations]
        self.execute: Dict[str, list] = {}
        # shape-bucket label -> [ewma_ms, observations] from DEVICE
        # execution events, populated only where the backend exposes a
        # per-dispatch duration on result buffers (no device sync ever);
        # empty on backends without the surface — the host EWMA above
        # stays the authoritative fallback
        self.execute_device: Dict[str, list] = {}
        # shape-bucket label -> {"flops": ..., "bytes_accessed": ...}
        self.cost: Dict[str, Dict[str, float]] = {}
        # recent compile times (monotonic seconds) for the storm window
        self.compile_marks: list = []
        self.storms = 0


class DeviceProfile:
    """Process-global compile/execute registry (one accelerator per
    process — the PLANES / BREAKERS / TELEMETRY precedent). Surfaced as
    the ``_nodes/stats`` ``"device_profile"`` section and merged
    fleet-wide into ``_cluster/stats``."""

    def __init__(self):
        self._families: Dict[str, FamilyProfile] = {}
        self.enabled = True
        # storm detector: more than ``storm_threshold`` compiles of a
        # family inside ``storm_window_s`` is a recompile storm — the
        # bucketing invariant broke (or a workload churns shapes) and
        # every compile costs seconds of serving capacity
        self.storm_threshold = 8
        self.storm_window_s = 60.0
        # individual compiles slower than this also log (a single
        # multi-second XLA compile mid-serving deserves a line even
        # without a storm)
        self.slow_compile_ms = 1000.0
        # guarded lowered.cost_analysis() estimates (one extra trace per
        # new shape bucket; off when even that is unwanted)
        self.cost_analysis = True

    def configure(self, storm_threshold: Optional[int] = None,
                  storm_window_s: Optional[float] = None,
                  slow_compile_ms: Optional[float] = None) -> None:
        if storm_threshold is not None:
            self.storm_threshold = int(storm_threshold)
        if storm_window_s is not None:
            self.storm_window_s = float(storm_window_s)
        if slow_compile_ms is not None:
            self.slow_compile_ms = float(slow_compile_ms)

    def configure_from_state(self, state) -> None:
        """Refresh the storm/slow-compile knobs from committed cluster
        settings (``search.device_profile.storm_*``), memoized on the
        state version like the plane registries — the parse must not tax
        the per-search hot path it observes."""
        version = getattr(state, "version", None)
        if version is not None and \
                version == getattr(self, "_cfg_version", None):
            return
        self._cfg_version = version
        from elasticsearch_tpu.utils.settings import (
            SEARCH_DEVICE_PROFILE_SLOW_COMPILE,
            SEARCH_DEVICE_PROFILE_STORM_THRESHOLD,
            SEARCH_DEVICE_PROFILE_STORM_WINDOW, setting_from_state,
        )
        self.configure(
            storm_threshold=setting_from_state(
                state, SEARCH_DEVICE_PROFILE_STORM_THRESHOLD),
            storm_window_s=setting_from_state(
                state, SEARCH_DEVICE_PROFILE_STORM_WINDOW),
            slow_compile_ms=1000.0 * setting_from_state(
                state, SEARCH_DEVICE_PROFILE_SLOW_COMPILE))

    def family(self, name: str) -> FamilyProfile:
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = FamilyProfile(name)
        return fam

    # -- recording --------------------------------------------------------

    def on_compile(self, family: str, label: str, dur_ns: int,
                   cost: Optional[Dict[str, float]] = None) -> None:
        fam = self.family(family)
        fam.compiles += 1
        fam.compile_ns_total += int(dur_ns)
        fam.compile_ns_max = max(fam.compile_ns_max, int(dur_ns))
        fam.shapes[label] = fam.shapes.get(label, 0) + 1
        while len(fam.shapes) > MAX_BUCKETS_PER_FAMILY:
            fam.shapes.pop(next(iter(fam.shapes)))
        if cost:
            fam.cost[label] = cost
            while len(fam.cost) > MAX_BUCKETS_PER_FAMILY:
                fam.cost.pop(next(iter(fam.cost)))
        # storm detection over a sliding window of compile marks
        now = time.monotonic()
        marks = fam.compile_marks
        marks.append(now)
        horizon = now - self.storm_window_s
        while marks and marks[0] < horizon:
            marks.pop(0)
        stormed = len(marks) > self.storm_threshold
        if stormed:
            fam.storms += 1
            # reset the window so one sustained churn counts as one
            # storm per threshold-crossing, not one per extra compile
            del marks[:]
        if stormed or dur_ns / 1e6 >= self.slow_compile_ms:
            logger.warning(
                "slow-compile: family [%s] shape [%s] compiled in "
                "%.1fms (%d distinct shape buckets, %d compiles total%s)",
                family, label, dur_ns / 1e6, len(fam.shapes),
                fam.compiles,
                ", RECOMPILE STORM" if stormed else "")
        # request attribution: the active trace (if any) gains a compile
        # span and the first-compile flag slow logs print
        telemetry.record_compile(family, dur_ns)

    def on_execute(self, family: str, label: str, dur_ns: int) -> None:
        fam = self.family(family)
        fam.cache_hits += 1
        got = fam.execute.get(label)
        ms = dur_ns / 1e6
        if got is None:
            fam.execute[label] = [ms, 1]
            while len(fam.execute) > MAX_BUCKETS_PER_FAMILY:
                fam.execute.pop(next(iter(fam.execute)))
        else:
            got[0] = EWMA_ALPHA * ms + (1 - EWMA_ALPHA) * got[0]
            got[1] += 1

    def on_execute_device(self, family: str, label: str,
                          dur_ns: int) -> None:
        """A device-event execution duration (backend-reported, not
        host-observed) for an already-compiled dispatch. Recorded beside
        the host EWMA, never instead of it: the host figure keeps its
        dispatch-cost meaning on every backend, the device figure only
        exists where the runtime hands it over for free."""
        fam = self.family(family)
        got = fam.execute_device.get(label)
        ms = dur_ns / 1e6
        if got is None:
            fam.execute_device[label] = [ms, 1]
            while len(fam.execute_device) > MAX_BUCKETS_PER_FAMILY:
                fam.execute_device.pop(next(iter(fam.execute_device)))
        else:
            got[0] = EWMA_ALPHA * ms + (1 - EWMA_ALPHA) * got[0]
            got[1] += 1

    # -- surfaces ---------------------------------------------------------

    def total_compiles(self) -> int:
        return sum(f.compiles for f in self._families.values())

    def compiles_by_family(self) -> Dict[str, int]:
        return {name: fam.compiles
                for name, fam in sorted(self._families.items())}

    def snapshot(self) -> Dict[str, Any]:
        families: Dict[str, Any] = {}
        for name, fam in sorted(self._families.items()):
            families[name] = {
                "compiles": fam.compiles,
                "cache_hits": fam.cache_hits,
                "compile_ms_total": round(fam.compile_ns_total / 1e6, 3),
                "compile_ms_max": round(fam.compile_ns_max / 1e6, 3),
                "shape_buckets": len(fam.shapes),
                "recompile_storms": fam.storms,
                "execute_ewma_ms": {
                    label: {"ewma_ms": round(ewma, 4), "calls": count}
                    for label, (ewma, count)
                    in sorted(fam.execute.items())},
            }
            if fam.execute_device:
                families[name]["execute_device_ewma_ms"] = {
                    label: {"ewma_ms": round(ewma, 4), "calls": count}
                    for label, (ewma, count)
                    in sorted(fam.execute_device.items())}
            if fam.cost:
                families[name]["cost"] = {
                    label: {k: round(v, 1) for k, v in entry.items()}
                    for label, entry in sorted(fam.cost.items())}
        return {
            "families": families,
            "total_compiles": self.total_compiles(),
            "total_cache_hits": sum(
                f.cache_hits for f in self._families.values()),
            "recompile_storms": sum(
                f.storms for f in self._families.values()),
            "storm_threshold": self.storm_threshold,
            "storm_window_s": self.storm_window_s,
            # True once any family recorded a backend-reported duration
            # (operators read which timing semantics the EWMAs carry)
            "device_events": any(f.execute_device
                                 for f in self._families.values()),
        }

    def reset(self) -> None:
        self._families.clear()


DEVICE_PROFILE = DeviceProfile()


class ProfiledJit:
    """A jitted kernel routed through the device observatory.

    Call-compatible with the jitted function it wraps (``lower`` passes
    through). Tracer arguments (this kernel inlined inside another traced
    program) bypass profiling entirely — only concrete dispatches are
    device programs worth attributing."""

    def __init__(self, family: str, fn: Optional[Callable] = None,
                 static_argnames: Tuple[str, ...] = (),
                 jit_kwargs: Optional[Dict[str, Any]] = None,
                 jitted: Optional[Callable] = None):
        if not family:
            raise ValueError("profiled kernels must name their family")
        import jax
        self.family = family
        self._static = frozenset(
            (static_argnames,) if isinstance(static_argnames, str)
            else static_argnames)
        if jitted is None:
            jitted = jax.jit(fn, static_argnames=static_argnames,
                             **(jit_kwargs or {}))
        self._jitted = jitted
        self.__name__ = getattr(fn, "__name__", family) \
            if fn is not None else family
        self.__qualname__ = getattr(fn, "__qualname__", family) \
            if fn is not None else family
        self.__doc__ = fn.__doc__ if fn is not None else None
        self.__wrapped__ = fn if fn is not None else jitted
        # per-INSTANCE shape mirror for the fallback compile detector:
        # several wrappers can share one family (the masked/unmasked
        # mesh variants, re-created factory kernels), but each has its
        # own jit cache — a family-shared mirror would mask their
        # first compiles from each other when _cache_size is absent.
        # Populated ONLY on the fallback path (dead weight otherwise)
        # and FIFO-bounded like the family maps.
        self._seen_labels: Dict[str, None] = {}
        # device-event probe state: None = unprobed, False = surface
        # absent on this backend (probe once, never again), True =
        # result buffers carry per-dispatch durations
        self._device_events: Optional[bool] = None
        params: Tuple[str, ...] = ()
        if fn is not None:
            try:
                params = tuple(inspect.signature(fn).parameters)
            except (TypeError, ValueError):
                params = ()
        self._params = params

    # -- passthrough ------------------------------------------------------

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def _cache_size(self) -> Optional[int]:
        """The jitted function's executable-cache size — the
        authoritative compiled-or-not signal. Private jax surface, so
        None (fall back to the shape mirror) when it moves."""
        try:
            return int(self._jitted._cache_size())
        except Exception:  # noqa: BLE001 — private API moved
            return None

    # -- the profiled call ------------------------------------------------

    def _label(self, args, kwargs) -> str:
        parts = []
        params = self._params
        for i, a in enumerate(args):
            name = params[i] if i < len(params) else None
            if name is not None and name in self._static:
                parts.append(f"{name}={a!r}")
            else:
                parts.append(_describe_dynamic(a))
        for k in sorted(kwargs):
            v = kwargs[k]
            if k in self._static:
                parts.append(f"{k}={v!r}")
            else:
                parts.append(f"{k}={_describe_dynamic(v)}")
        return "/".join(parts)

    def _cost_of(self, args, kwargs) -> Optional[Dict[str, float]]:
        """Guarded FLOPs/bytes estimate for a freshly-compiled shape:
        one extra trace per new bucket (compiles are rare by contract),
        None whenever the backend doesn't expose the analysis."""
        if not DEVICE_PROFILE.cost_analysis:
            return None
        try:
            analysis = self._jitted.lower(*args, **kwargs).cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else None
            if not isinstance(analysis, dict):
                return None
            out: Dict[str, float] = {}
            flops = analysis.get("flops")
            if flops is not None:
                out["flops"] = float(flops)
            acc = analysis.get("bytes accessed")
            if acc is not None:
                out["bytes_accessed"] = float(acc)
            return out or None
        except Exception:  # noqa: BLE001 — estimates are best-effort
            return None

    # candidate private surfaces for a backend-reported per-dispatch
    # duration on result buffers (some accelerator runtimes attach one;
    # CPU does not). Attribute reads only — the probe must NEVER
    # block_until_ready or otherwise device-sync.
    _DEVICE_EVENT_ATTRS = ("execution_duration_ns",
                           "_execution_duration_ns")

    def _device_event_ns(self, out) -> Optional[int]:
        """Backend-reported device duration for this dispatch, or None.
        Probes the first output leaf once: a backend without the surface
        caches False and every later call costs a single flag check, so
        the host-EWMA fallback path stays exactly as cheap as before."""
        if self._device_events is False:
            return None
        leaf = out
        while isinstance(leaf, (tuple, list)) and leaf:
            leaf = leaf[0]
        for name in self._DEVICE_EVENT_ATTRS:
            try:
                v = getattr(leaf, name)
                v = v() if callable(v) else v
                v = int(v)
            except Exception:  # noqa: BLE001 — absent/moved surface
                continue
            if v > 0:
                self._device_events = True
                return v
        self._device_events = False
        return None

    def __call__(self, *args, **kwargs):
        reg = DEVICE_PROFILE
        if not reg.enabled:
            return self._jitted(*args, **kwargs)
        tracer = _tracer_type()
        if any(isinstance(a, tracer) for a in args) or \
                any(isinstance(v, tracer) for v in kwargs.values()):
            # inlined inside an outer traced program: the OUTER profiled
            # kernel owns the compile attribution
            return self._jitted(*args, **kwargs)
        label = self._label(args, kwargs)
        before = self._cache_size()
        t0 = time.monotonic_ns()
        out = self._jitted(*args, **kwargs)
        dur_ns = time.monotonic_ns() - t0
        after = self._cache_size()
        if before is not None and after is not None:
            compiled = after > before
        else:
            # bounded mirror: past the cap an evicted-then-recurring
            # shape reads as a fresh compile (overcount, the safe
            # direction for a DETECTOR — bounded memory outranks exact
            # counts on a fallback path that only exists when the
            # private cache-size surface is gone)
            compiled = label not in self._seen_labels
            self._seen_labels[label] = None
            while len(self._seen_labels) > 4 * MAX_BUCKETS_PER_FAMILY:
                self._seen_labels.pop(next(iter(self._seen_labels)))
        if compiled:
            reg.on_compile(self.family, label, dur_ns,
                           self._cost_of(args, kwargs))
        else:
            reg.on_execute(self.family, label, dur_ns)
            dev_ns = self._device_event_ns(out)
            if dev_ns is not None:
                reg.on_execute_device(self.family, label, dev_ns)
        return out


def profiled_jit(family: str, *, static_argnames: Tuple[str, ...] = (),
                 **jit_kwargs):
    """Decorator: stage ``fn`` with jax.jit AND route every concrete
    call through the device observatory. THE replacement for a bare
    ``partial(jax.jit, ...)`` under ``ops/`` and ``search/`` — the
    grep-guard test pins raw jit call sites there at zero."""
    def wrap(fn: Callable) -> ProfiledJit:
        return ProfiledJit(family, fn, static_argnames=static_argnames,
                           jit_kwargs=jit_kwargs)
    return wrap


def profiled_callable(family: str, stageable: Callable,
                      **jit_kwargs) -> ProfiledJit:
    """Jit + profile an already-staged callable (the shard_map kernel
    factories in parallel/mesh.py): the jit happens HERE so factory call
    sites never spell a raw jit themselves."""
    import jax
    return ProfiledJit(family,
                       jitted=jax.jit(stageable, **(jit_kwargs or {})))


def merge_device_profile_sections(sections) -> Dict[str, Any]:
    """Coordinator-side fleet merge of per-node ``device_profile``
    sections (``_cluster/stats``'s section-filtered fan-out): counters
    sum, compile-time maxima take the max, per-bucket EWMA detail stays
    node-local (averaging EWMAs across nodes would mean nothing)."""
    families: Dict[str, Dict[str, Any]] = {}
    totals = {"total_compiles": 0, "total_cache_hits": 0,
              "recompile_storms": 0}
    for section in sections:
        if not section:
            continue
        for key in totals:
            totals[key] += int(section.get(key) or 0)
        for name, entry in (section.get("families") or {}).items():
            agg = families.get(name)
            if agg is None:
                agg = families[name] = {
                    "compiles": 0, "cache_hits": 0,
                    "compile_ms_total": 0.0, "compile_ms_max": 0.0,
                    "shape_buckets": 0, "recompile_storms": 0}
            agg["compiles"] += int(entry.get("compiles") or 0)
            agg["cache_hits"] += int(entry.get("cache_hits") or 0)
            agg["compile_ms_total"] = round(
                agg["compile_ms_total"]
                + float(entry.get("compile_ms_total") or 0.0), 3)
            agg["compile_ms_max"] = max(
                agg["compile_ms_max"],
                float(entry.get("compile_ms_max") or 0.0))
            agg["shape_buckets"] += int(entry.get("shape_buckets") or 0)
            agg["recompile_storms"] += int(
                entry.get("recompile_storms") or 0)
    return {"families": dict(sorted(families.items())), **totals}
