"""Shard allocation: decide which node gets each shard copy.

Reference: cluster/routing/allocation/AllocationService.java:70 (reroute on
every membership/metadata change), BalancedShardsAllocator.java:82 (weighted
least-loaded placement) and the pluggable decider chain (decider/ — same-
shard, filters, throttling). Pure functions ClusterState -> ClusterState;
the master runs them inside state updates.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, Iterable, List, Optional, Sequence

from elasticsearch_tpu.cluster.routing import (
    IndexRoutingTable, RoutingTable, ShardRouting, ShardState,
)
from elasticsearch_tpu.cluster.state import ClusterState, DiscoveryNode


class Decision:
    YES = "YES"
    NO = "NO"
    THROTTLE = "THROTTLE"


class AllocationDecider:
    def can_allocate(self, shard: ShardRouting, node: DiscoveryNode,
                     state: ClusterState) -> str:
        return Decision.YES


class SameShardDecider(AllocationDecider):
    """No two copies of the same shard on one node
    (decider/SameShardAllocationDecider.java)."""

    def can_allocate(self, shard, node, state):
        for sr in state.routing_table.shards_on_node(node.node_id):
            if sr.index == shard.index and sr.shard_id == shard.shard_id:
                return Decision.NO
        return Decision.YES


class FilterDecider(AllocationDecider):
    """index.routing.allocation.{require,include,exclude}._name
    (decider/FilterAllocationDecider.java), matched on node names."""

    def can_allocate(self, shard, node, state):
        try:
            settings = state.metadata.index(shard.index).settings
        except Exception:  # noqa: BLE001 — index gone: no constraint
            return Decision.YES
        name = node.name or node.node_id
        req = settings.get("index.routing.allocation.require._name")
        if req and name != req:
            return Decision.NO
        inc = settings.get("index.routing.allocation.include._name")
        if inc and name not in str(inc).split(","):
            return Decision.NO
        exc = settings.get("index.routing.allocation.exclude._name")
        if exc and name in str(exc).split(","):
            return Decision.NO
        return Decision.YES


class ThrottlingDecider(AllocationDecider):
    """Bound concurrent recoveries per node
    (decider/ThrottlingAllocationDecider.java)."""

    def __init__(self, max_initializing_per_node: int = 4) -> None:
        self.max_initializing = max_initializing_per_node

    def can_allocate(self, shard, node, state):
        initializing = sum(
            1 for sr in state.routing_table.shards_on_node(node.node_id)
            if sr.state == ShardState.INITIALIZING)
        if initializing >= self.max_initializing:
            return Decision.THROTTLE
        return Decision.YES


class MaxRetryDecider(AllocationDecider):
    """Stop retry storms: a shard that failed allocation too many times
    stays unassigned until an explicit reroute with retry_failed
    (decider/MaxRetryAllocationDecider.java)."""

    def __init__(self, max_retries: int = 5) -> None:
        self.max_retries = max_retries

    def can_allocate(self, shard, node, state):
        if shard.failed_attempts >= self.max_retries:
            return Decision.NO
        return Decision.YES


class AwarenessDecider(AllocationDecider):
    """Spread copies of a shard across values of the awareness attributes
    (decider/AwarenessAllocationDecider.java): a node whose attribute
    value already holds its fair share of this shard's copies is
    rejected. Attributes come from the dynamic cluster setting
    cluster.routing.allocation.awareness.attributes."""

    def can_allocate(self, shard, node, state):
        attrs_setting = state.metadata.persistent_settings.get(
            "cluster.routing.allocation.awareness.attributes")
        if not attrs_setting:
            return Decision.YES
        group = state.routing_table.index(shard.index) \
            .shard_group(shard.shard_id) \
            if state.routing_table.has_index(shard.index) else ()
        n_copies = max(len(group), 1)
        for attr in str(attrs_setting).split(","):
            attr = attr.strip()
            if not attr:
                continue
            values = {n.attr(attr) for n in state.data_nodes().values()
                      if n.attr(attr) is not None}
            if not values:
                continue
            my_value = node.attr(attr)
            per_value_cap = -(-n_copies // len(values))  # ceil
            assigned_here = sum(
                1 for sr in group
                if sr.assigned and sr.node_id in state.nodes
                and state.nodes[sr.node_id].attr(attr) == my_value)
            if assigned_here >= per_value_cap:
                return Decision.NO
        return Decision.YES


class DiskThresholdDecider(AllocationDecider):
    """Keep shards off nodes past the low watermark
    (decider/DiskThresholdDecider.java). Usage comes from the cluster
    info the master refreshes from node stats
    (InternalClusterInfoService analog); absent info allows."""

    def __init__(self, low_watermark: float = 0.85) -> None:
        self.low_watermark = low_watermark
        # node_id -> (used_bytes, total_bytes)
        self.usages: Dict[str, tuple] = {}

    def can_allocate(self, shard, node, state):
        got = self.usages.get(node.node_id)
        if not got:
            return Decision.YES
        used, total = got
        if total > 0 and used / total >= self.low_watermark:
            return Decision.NO
        return Decision.YES


def default_deciders() -> Sequence[AllocationDecider]:
    """Fresh decider instances per service: DiskThresholdDecider carries
    mutable usage state, so sharing one module-level tuple would leak
    decisions across nodes (and across tests)."""
    return (SameShardDecider(), FilterDecider(), ThrottlingDecider(),
            MaxRetryDecider(), AwarenessDecider(), DiskThresholdDecider())


class AllocationService:
    def __init__(self,
                 deciders: Optional[Sequence[AllocationDecider]] = None):
        self.deciders = list(deciders if deciders is not None
                             else default_deciders())
        # GatewayAllocator (gateway.py), attached by the node: when set,
        # unassigned shards with a prior identity are placed on the node
        # holding the freshest non-corrupted on-disk copy instead of by
        # balance alone. None (the default) keeps reroute pure balance.
        self.gateway_allocator = None

    def disk_threshold(self) -> Optional["DiskThresholdDecider"]:
        """The service's disk decider, for cluster-info refreshes."""
        for d in self.deciders:
            if isinstance(d, DiskThresholdDecider):
                return d
        return None

    # -- decision ------------------------------------------------------------

    def decide(self, shard: ShardRouting, node: DiscoveryNode,
               state: ClusterState) -> str:
        worst = Decision.YES
        for d in self.deciders:
            verdict = d.can_allocate(shard, node, state)
            if verdict == Decision.NO:
                return Decision.NO
            if verdict == Decision.THROTTLE:
                worst = Decision.THROTTLE
        return worst

    # -- reroute -------------------------------------------------------------

    # BalancedShardsAllocator weight factors
    # (cluster.routing.allocation.balance.shard / .index defaults)
    SHARD_BALANCE = 0.45
    INDEX_BALANCE = 0.55
    REBALANCE_THRESHOLD = 1.0

    def _weight(self, loads: Dict[str, int],
                index_loads: Dict[str, Dict[str, int]],
                nid: str, index: str, n_nodes: int,
                total_shards: int, index_total: int) -> float:
        """BalancedShardsAllocator.WeightFunction: a node is attractive
        for a shard of [index] when it holds fewer shards overall AND
        fewer shards of that index than its fair share."""
        avg_shards = total_shards / n_nodes
        avg_index = index_total / n_nodes
        return (self.SHARD_BALANCE * (loads[nid] - avg_shards)
                + self.INDEX_BALANCE *
                (index_loads[nid].get(index, 0) - avg_index))

    def reroute(self, state: ClusterState,
                rebalance: bool = True) -> ClusterState:
        """Assign unassigned shards (primaries first) to the
        minimum-weight eligible node, then move replicas off overloaded
        nodes when the weight spread exceeds the threshold. Idempotent;
        no-op returns the same state."""
        data_nodes = state.data_nodes()
        if not data_nodes:
            return state
        routing = state.routing_table
        changed = False
        gateway = self.gateway_allocator
        if gateway is not None:
            # ReplicaShardAllocator cancel pass: an in-flight empty-store
            # replica build yields when a node holding the copy's real
            # data rejoins (the cancelled entry re-enters the unassigned
            # pool below and lands on the copy-holder)
            routing, n_cancelled = gateway.cancel_replaceable_recoveries(
                state, routing, self)
            if n_cancelled:
                changed = True
        loads: Dict[str, int] = {
            nid: len(routing.shards_on_node(nid)) for nid in data_nodes}
        index_loads: Dict[str, Dict[str, int]] = {
            nid: {} for nid in data_nodes}
        for nid in data_nodes:
            for sr in routing.shards_on_node(nid):
                index_loads[nid][sr.index] = \
                    index_loads[nid].get(sr.index, 0) + 1
        index_totals: Dict[str, int] = {}
        for sr in routing.all_shards():
            if sr.assigned:
                index_totals[sr.index] = index_totals.get(sr.index, 0) + 1
        n_nodes = len(data_nodes)

        def place(shard: ShardRouting, target: str) -> None:
            nonlocal routing, changed
            new_shard = shard.initialize(target)
            routing = routing.put_index(
                routing.index(shard.index).replace_shard(shard, new_shard))
            loads[target] += 1
            index_loads[target][shard.index] = \
                index_loads[target].get(shard.index, 0) + 1
            index_totals[shard.index] = \
                index_totals.get(shard.index, 0) + 1
            changed = True

        unassigned = sorted(
            (sr for sr in routing.all_shards()
             if sr.state == ShardState.UNASSIGNED),
            key=lambda sr: (not sr.primary, sr.index, sr.shard_id))
        if gateway is not None:
            # batch the shard-state fetches this pass will want into one
            # request per node before walking the shards
            gateway.prefetch(unassigned, state)
        for shard in unassigned:
            # replicas wait for an active primary to recover from
            if not shard.primary:
                primary = routing.index(shard.index).primary(shard.shard_id)
                if not primary.active:
                    continue
            st = state.next_version(routing_table=routing) if changed else state
            if gateway is not None and shard.last_allocation_id is not None:
                # this copy existed before: consult the gateway fetch
                # (Primary/ReplicaShardAllocator) before balance
                action, detail = gateway.decide_unassigned(shard, st, self)
                if action == "wait":
                    continue   # fetch in flight / throttled: next reroute
                if action == "allocate":
                    place(shard, detail)
                    continue
                if action in ("refuse", "fallback") and detail and \
                        shard.unassigned_reason != detail:
                    # surface the fetch-derived reason on the routing
                    # entry (health / _cat/shards / allocation explain)
                    noted = replace(shard, unassigned_reason=detail)
                    routing = routing.put_index(
                        routing.index(shard.index).replace_shard(
                            shard, noted))
                    shard = noted
                    changed = True
                if action == "refuse":
                    continue   # stays unassigned, loudly
            candidates = [
                nid for nid, node in data_nodes.items()
                if self.decide(shard, node, st) == Decision.YES]
            if not candidates:
                continue
            total = sum(loads.values())
            target = min(candidates, key=lambda nid: (
                self._weight(loads, index_loads, nid, shard.index, n_nodes,
                             total, index_totals.get(shard.index, 0)), nid))
            place(shard, target)

        if rebalance:
            rebalanced = self._rebalance(
                state, routing, data_nodes, loads, index_loads,
                index_totals)
            if rebalanced is not None:
                routing = rebalanced
                changed = True

        if not changed:
            return state
        return state.next_version(routing_table=routing)

    def _rebalance(self, state, routing, data_nodes, loads, index_loads,
                   index_totals) -> Optional[RoutingTable]:
        """Move STARTED replicas from max-weight to min-weight nodes while
        the spread exceeds the threshold (BalancedShardsAllocator.balance).
        Replica moves are drop-and-recover — the copy rebuilds from the
        primary on the target (a documented divergence from the
        reference's live relocation handoff; primaries never move).
        Returns the rebalanced routing table, or None for no change."""
        if len(data_nodes) < 2:
            return None
        # only rebalance a green cluster (ClusterRebalanceAllocationDecider
        # indices_all_active default)
        if any(not sr.active for sr in routing.all_shards()):
            return None
        changed = False
        for _round in range(8):            # bounded passes per reroute
            heavy = max(data_nodes, key=lambda nid: (loads[nid], nid))
            light = min(data_nodes, key=lambda nid: (loads[nid], nid))
            # move while the shard-count spread exceeds the threshold
            # (one move per pass converges to a <=1 spread)
            if loads[heavy] - loads[light] <= self.REBALANCE_THRESHOLD:
                break
            movable = [
                sr for sr in routing.shards_on_node(heavy)
                if not sr.primary and sr.state == ShardState.STARTED]
            moved = False
            for sr in movable:
                target_node = data_nodes[light]
                probe = sr.fail()
                st = state.next_version(routing_table=routing)
                if self.decide(replace(probe, failed_attempts=0),
                               target_node, st) != Decision.YES:
                    continue
                # drop the copy on the heavy node; allocate on the light
                irt = routing.index(sr.index)
                irt = irt.replace_shard(
                    sr, ShardRouting(index=sr.index, shard_id=sr.shard_id,
                                     primary=False))
                fresh = next(s for s in irt.shard_group(sr.shard_id)
                             if s.state == ShardState.UNASSIGNED)
                irt = irt.replace_shard(fresh, fresh.initialize(light))
                routing = routing.put_index(irt)
                loads[heavy] -= 1
                loads[light] += 1
                index_loads[heavy][sr.index] = \
                    index_loads[heavy].get(sr.index, 1) - 1
                index_loads[light][sr.index] = \
                    index_loads[light].get(sr.index, 0) + 1
                moved = True
                changed = True
                break
            if not moved:
                break
        return routing if changed else None

    # -- lifecycle events ----------------------------------------------------

    def apply_started_shards(self, state: ClusterState,
                             started: Iterable[ShardRouting]) -> ClusterState:
        routing = state.routing_table
        changed = False
        for shard in started:
            irt = routing.index(shard.index)
            current = next((sr for sr in irt.shard_group(shard.shard_id)
                            if sr.allocation_id == shard.allocation_id), None)
            if current is None or current.state != ShardState.INITIALIZING:
                continue
            routing = routing.put_index(
                irt.replace_shard(current, current.start()))
            changed = True
        if not changed:
            return state
        return self.reroute(state.next_version(routing_table=routing))

    def apply_failed_shard(self, state: ClusterState,
                           failed: ShardRouting,
                           count_failure: bool = True,
                           reason: Optional[str] = None) -> ClusterState:
        """Failed primary: promote an active replica, then schedule a new
        replica copy; failed replica: back to unassigned (reference:
        NodeRemovalClusterStateTaskExecutor → AllocationService.reroute).
        ``count_failure=False`` for operator-initiated cancels, which must
        not consume the MaxRetryDecider budget. ``reason`` is recorded on
        the unassigned copy (UnassignedInfo details) so allocation
        explain can answer *why* — e.g. a corrupted store."""
        routing = state.routing_table
        irt = routing.index(failed.index)
        current = next((sr for sr in irt.shard_group(failed.shard_id)
                        if sr.allocation_id == failed.allocation_id and
                        sr.allocation_id is not None), None)
        if current is None:
            return state
        if self.gateway_allocator is not None:
            # whatever the fetch cache said about this node's copy is
            # stale now (a corruption marker may have just appeared)
            self.gateway_allocator.invalidate_node_entry(
                failed.index, failed.shard_id, current.node_id)
        dropped = current.fail(reason)
        if not count_failure:
            dropped = replace(dropped,
                              failed_attempts=current.failed_attempts)
        irt = irt.replace_shard(current, dropped)
        metadata = state.metadata
        if current.primary:
            # every primary failure bumps the shard's primary term so stale
            # primaries can be fenced (IndexMetadata primaryTerms semantics)
            metadata = metadata.update_index(
                metadata.index(failed.index)
                .with_primary_term_bump(failed.shard_id))
            replicas = [sr for sr in irt.shard_group(failed.shard_id)
                        if not sr.primary and sr.active]
            if replicas:
                promoted = replicas[0]
                irt = irt.replace_shard(promoted, promoted.promote_to_primary())
                demoted = next(sr for sr in irt.shard_group(failed.shard_id)
                               if sr.primary and sr.state == ShardState.UNASSIGNED)
                # the replacement replica slot keeps the failed copy's
                # identity + reason: the gateway fetch can still match
                # whatever data outlived the failure, and explain keeps
                # answering WHY the copy died
                irt = irt.replace_shard(
                    demoted, ShardRouting(
                        index=failed.index, shard_id=failed.shard_id,
                        primary=False,
                        unassigned_reason=demoted.unassigned_reason,
                        last_allocation_id=demoted.last_allocation_id))
        routing = routing.put_index(irt)
        return self.reroute(state.next_version(routing_table=routing,
                                               metadata=metadata))

    def disassociate_dead_nodes(self, state: ClusterState,
                                dead: Iterable[str]) -> ClusterState:
        dead_set = set(dead)
        out = state
        for nid in dead_set:
            for shard in list(out.routing_table.shards_on_node(nid)):
                if shard.node_id in dead_set:
                    out = self.apply_failed_shard(
                        out, shard, reason=f"node [{nid}] left the cluster")
        return out
