"""Shard allocation: decide which node gets each shard copy.

Reference: cluster/routing/allocation/AllocationService.java:70 (reroute on
every membership/metadata change), BalancedShardsAllocator.java:82 (weighted
least-loaded placement) and the pluggable decider chain (decider/ — same-
shard, filters, throttling). Pure functions ClusterState -> ClusterState;
the master runs them inside state updates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

from elasticsearch_tpu.cluster.routing import (
    IndexRoutingTable, RoutingTable, ShardRouting, ShardState,
)
from elasticsearch_tpu.cluster.state import ClusterState, DiscoveryNode


class Decision:
    YES = "YES"
    NO = "NO"
    THROTTLE = "THROTTLE"


class AllocationDecider:
    def can_allocate(self, shard: ShardRouting, node: DiscoveryNode,
                     state: ClusterState) -> str:
        return Decision.YES


class SameShardDecider(AllocationDecider):
    """No two copies of the same shard on one node
    (decider/SameShardAllocationDecider.java)."""

    def can_allocate(self, shard, node, state):
        for sr in state.routing_table.shards_on_node(node.node_id):
            if sr.index == shard.index and sr.shard_id == shard.shard_id:
                return Decision.NO
        return Decision.YES


class FilterDecider(AllocationDecider):
    """index.routing.allocation.{require,include,exclude}._name
    (decider/FilterAllocationDecider.java), matched on node names."""

    def can_allocate(self, shard, node, state):
        try:
            settings = state.metadata.index(shard.index).settings
        except Exception:  # noqa: BLE001 — index gone: no constraint
            return Decision.YES
        name = node.name or node.node_id
        req = settings.get("index.routing.allocation.require._name")
        if req and name != req:
            return Decision.NO
        inc = settings.get("index.routing.allocation.include._name")
        if inc and name not in str(inc).split(","):
            return Decision.NO
        exc = settings.get("index.routing.allocation.exclude._name")
        if exc and name in str(exc).split(","):
            return Decision.NO
        return Decision.YES


class ThrottlingDecider(AllocationDecider):
    """Bound concurrent recoveries per node
    (decider/ThrottlingAllocationDecider.java)."""

    def __init__(self, max_initializing_per_node: int = 4) -> None:
        self.max_initializing = max_initializing_per_node

    def can_allocate(self, shard, node, state):
        initializing = sum(
            1 for sr in state.routing_table.shards_on_node(node.node_id)
            if sr.state == ShardState.INITIALIZING)
        if initializing >= self.max_initializing:
            return Decision.THROTTLE
        return Decision.YES


DEFAULT_DECIDERS: Sequence[AllocationDecider] = (
    SameShardDecider(), FilterDecider(), ThrottlingDecider(),
)


class AllocationService:
    def __init__(self, deciders: Sequence[AllocationDecider] = DEFAULT_DECIDERS):
        self.deciders = list(deciders)

    # -- decision ------------------------------------------------------------

    def decide(self, shard: ShardRouting, node: DiscoveryNode,
               state: ClusterState) -> str:
        worst = Decision.YES
        for d in self.deciders:
            verdict = d.can_allocate(shard, node, state)
            if verdict == Decision.NO:
                return Decision.NO
            if verdict == Decision.THROTTLE:
                worst = Decision.THROTTLE
        return worst

    # -- reroute -------------------------------------------------------------

    def reroute(self, state: ClusterState) -> ClusterState:
        """Assign unassigned shards (primaries first) to the least-loaded
        eligible data node. Idempotent; no-op returns the same state."""
        data_nodes = state.data_nodes()
        if not data_nodes:
            return state
        loads: Dict[str, int] = {
            nid: len(state.routing_table.shards_on_node(nid))
            for nid in data_nodes}
        routing = state.routing_table
        changed = False
        unassigned = sorted(
            (sr for sr in routing.all_shards()
             if sr.state == ShardState.UNASSIGNED),
            key=lambda sr: (not sr.primary, sr.index, sr.shard_id))
        for shard in unassigned:
            # replicas wait for an active primary to recover from
            if not shard.primary:
                primary = routing.index(shard.index).primary(shard.shard_id)
                if not primary.active:
                    continue
            candidates = []
            st = state.next_version(routing_table=routing) if changed else state
            for nid, node in data_nodes.items():
                if self.decide(shard, node, st) == Decision.YES:
                    candidates.append(nid)
            if not candidates:
                continue
            target = min(candidates, key=lambda nid: (loads[nid], nid))
            new_shard = shard.initialize(target)
            routing = routing.put_index(
                routing.index(shard.index).replace_shard(shard, new_shard))
            loads[target] += 1
            changed = True
        if not changed:
            return state
        return state.next_version(routing_table=routing)

    # -- lifecycle events ----------------------------------------------------

    def apply_started_shards(self, state: ClusterState,
                             started: Iterable[ShardRouting]) -> ClusterState:
        routing = state.routing_table
        changed = False
        for shard in started:
            irt = routing.index(shard.index)
            current = next((sr for sr in irt.shard_group(shard.shard_id)
                            if sr.allocation_id == shard.allocation_id), None)
            if current is None or current.state != ShardState.INITIALIZING:
                continue
            routing = routing.put_index(
                irt.replace_shard(current, current.start()))
            changed = True
        if not changed:
            return state
        return self.reroute(state.next_version(routing_table=routing))

    def apply_failed_shard(self, state: ClusterState,
                           failed: ShardRouting) -> ClusterState:
        """Failed primary: promote an active replica, then schedule a new
        replica copy; failed replica: back to unassigned (reference:
        NodeRemovalClusterStateTaskExecutor → AllocationService.reroute)."""
        routing = state.routing_table
        irt = routing.index(failed.index)
        current = next((sr for sr in irt.shard_group(failed.shard_id)
                        if sr.allocation_id == failed.allocation_id and
                        sr.allocation_id is not None), None)
        if current is None:
            return state
        irt = irt.replace_shard(current, current.fail())
        metadata = state.metadata
        if current.primary:
            # every primary failure bumps the shard's primary term so stale
            # primaries can be fenced (IndexMetadata primaryTerms semantics)
            metadata = metadata.update_index(
                metadata.index(failed.index)
                .with_primary_term_bump(failed.shard_id))
            replicas = [sr for sr in irt.shard_group(failed.shard_id)
                        if not sr.primary and sr.active]
            if replicas:
                promoted = replicas[0]
                irt = irt.replace_shard(promoted, promoted.promote_to_primary())
                demoted = next(sr for sr in irt.shard_group(failed.shard_id)
                               if sr.primary and sr.state == ShardState.UNASSIGNED)
                irt = irt.replace_shard(
                    demoted, ShardRouting(index=failed.index,
                                          shard_id=failed.shard_id,
                                          primary=False))
        routing = routing.put_index(irt)
        return self.reroute(state.next_version(routing_table=routing,
                                               metadata=metadata))

    def disassociate_dead_nodes(self, state: ClusterState,
                                dead: Iterable[str]) -> ClusterState:
        dead_set = set(dead)
        out = state
        for nid in dead_set:
            for shard in list(out.routing_table.shards_on_node(nid)):
                if shard.node_id in dead_set:
                    out = self.apply_failed_shard(out, shard)
        return out
