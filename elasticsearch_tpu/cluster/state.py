"""Immutable, versioned cluster state — the one shared truth.

Reference: cluster/ClusterState.java:86 (immutable + Diffable incremental
publication), cluster/node/DiscoveryNodeRole.java:33 (roles). Every change
produces a new state with version+1 under the master's current term;
publication ships a diff when the receiver has the parent version
(PublicationTransportHandler.java:89) and falls back to the full state.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, FrozenSet, Mapping, Optional, Tuple

from elasticsearch_tpu.cluster.metadata import Metadata
from elasticsearch_tpu.cluster.routing import RoutingTable


class Roles:
    MASTER = "master"
    DATA = "data"
    INGEST = "ingest"
    ALL: FrozenSet[str] = frozenset({MASTER, DATA, INGEST})


@dataclass(frozen=True)
class DiscoveryNode:
    node_id: str
    name: str = ""
    roles: FrozenSet[str] = field(default_factory=lambda: frozenset(Roles.ALL))
    address: str = "local"
    # node attributes for awareness/filter allocation (node.attr.* —
    # DiscoveryNode.getAttributes analog); frozen tuple of (key, value)
    attrs: Tuple[Tuple[str, str], ...] = ()
    # per-boot identity (DiscoveryNode.getEphemeralId analog): a fresh
    # value every process start, so a rejoin can distinguish "the same
    # running process re-sent its join" (no-op) from "the process
    # restarted" (replace the entry + republish the full state)
    ephemeral_id: str = ""

    def attr(self, key: str) -> Optional[str]:
        for k, v in self.attrs:
            if k == key:
                return v
        return None

    @property
    def is_master_eligible(self) -> bool:
        return Roles.MASTER in self.roles

    @property
    def is_data(self) -> bool:
        return Roles.DATA in self.roles

    def to_dict(self) -> Dict[str, Any]:
        out = {"id": self.node_id, "name": self.name or self.node_id,
               "roles": sorted(self.roles), "address": self.address}
        if self.attrs:
            out["attributes"] = dict(self.attrs)
        if self.ephemeral_id:
            out["ephemeral_id"] = self.ephemeral_id
        return out

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "DiscoveryNode":
        return DiscoveryNode(node_id=d["id"], name=d.get("name", ""),
                             roles=frozenset(d.get("roles", Roles.ALL)),
                             address=d.get("address", "local"),
                             attrs=tuple(sorted(
                                 d.get("attributes", {}).items())),
                             ephemeral_id=d.get("ephemeral_id", ""))


@dataclass(frozen=True)
class ClusterState:
    cluster_name: str = "elasticsearch-tpu"
    term: int = 0                    # master term (coordination epoch)
    version: int = 0                 # monotonic within and across terms
    state_uuid: str = "_na_"
    master_node_id: Optional[str] = None
    nodes: Mapping[str, DiscoveryNode] = field(default_factory=dict)
    metadata: Metadata = field(default_factory=Metadata)
    routing_table: RoutingTable = field(default_factory=RoutingTable)
    blocks: Tuple[str, ...] = ()     # global blocks, e.g. STATE_NOT_RECOVERED
    # voting configuration: node ids whose quorum commits state (Zen2's
    # VotingConfiguration; reconfigured as master-eligible nodes join/leave)
    voting_config: FrozenSet[str] = frozenset()

    STATE_NOT_RECOVERED_BLOCK = "state-not-recovered"
    NO_MASTER_BLOCK = "no-master"

    # -- functional updates --------------------------------------------------

    def next_version(self, **changes: Any) -> "ClusterState":
        import uuid as uuid_mod
        return replace(self, version=self.version + 1,
                       state_uuid=uuid_mod.uuid4().hex, **changes)

    def with_nodes(self, nodes: Mapping[str, DiscoveryNode],
                   master_node_id: Optional[str]) -> "ClusterState":
        return self.next_version(nodes=dict(nodes),
                                 master_node_id=master_node_id)

    def with_metadata(self, metadata: Metadata) -> "ClusterState":
        return self.next_version(metadata=metadata)

    def with_routing(self, routing_table: RoutingTable) -> "ClusterState":
        return self.next_version(routing_table=routing_table)

    def with_block(self, block: str) -> "ClusterState":
        if block in self.blocks:
            return self
        return self.next_version(blocks=self.blocks + (block,))

    def without_block(self, block: str) -> "ClusterState":
        if block not in self.blocks:
            return self
        return self.next_version(
            blocks=tuple(b for b in self.blocks if b != block))

    @property
    def master_node(self) -> Optional[DiscoveryNode]:
        return self.nodes.get(self.master_node_id) \
            if self.master_node_id else None

    def data_nodes(self) -> Dict[str, DiscoveryNode]:
        return {nid: n for nid, n in self.nodes.items() if n.is_data}

    def master_eligible_nodes(self) -> Dict[str, DiscoveryNode]:
        return {nid: n for nid, n in self.nodes.items()
                if n.is_master_eligible}

    # -- serialization + diffs ----------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "cluster_name": self.cluster_name,
            "term": self.term, "version": self.version,
            "state_uuid": self.state_uuid,
            "master_node": self.master_node_id,
            "nodes": {nid: n.to_dict() for nid, n in self.nodes.items()},
            "metadata": self.metadata.to_dict(),
            "routing_table": self.routing_table.to_dict(),
            "blocks": list(self.blocks),
            "voting_config": sorted(self.voting_config),
        }

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "ClusterState":
        return ClusterState(
            cluster_name=d.get("cluster_name", "elasticsearch-tpu"),
            term=d.get("term", 0), version=d.get("version", 0),
            state_uuid=d.get("state_uuid", "_na_"),
            master_node_id=d.get("master_node"),
            nodes={nid: DiscoveryNode.from_dict(n)
                   for nid, n in d.get("nodes", {}).items()},
            metadata=Metadata.from_dict(d.get("metadata", {})),
            routing_table=RoutingTable.from_dict(d.get("routing_table", {})),
            blocks=tuple(d.get("blocks", ())),
            voting_config=frozenset(d.get("voting_config", ())))

    def diff_from(self, parent: "ClusterState") -> Dict[str, Any]:
        """Sections changed since `parent` (identity-compared — cheap because
        unchanged sections are shared between immutable states)."""
        diff: Dict[str, Any] = {
            "from_uuid": parent.state_uuid, "to_uuid": self.state_uuid,
            "term": self.term, "version": self.version,
            "master_node": self.master_node_id,
            "blocks": list(self.blocks),
            "voting_config": sorted(self.voting_config),
        }
        if self.nodes is not parent.nodes:
            diff["nodes"] = {nid: n.to_dict()
                             for nid, n in self.nodes.items()}
        if self.metadata is not parent.metadata:
            diff["metadata"] = self.metadata.to_dict()
        if self.routing_table is not parent.routing_table:
            diff["routing_table"] = self.routing_table.to_dict()
        return diff

    def apply_diff(self, diff: Mapping[str, Any]) -> "ClusterState":
        if diff["from_uuid"] != self.state_uuid:
            raise IncompatibleClusterStateError(
                f"diff base {diff['from_uuid']} != local {self.state_uuid}")
        out = self
        nodes = ({nid: DiscoveryNode.from_dict(n)
                  for nid, n in diff["nodes"].items()}
                 if "nodes" in diff else self.nodes)
        metadata = (Metadata.from_dict(diff["metadata"])
                    if "metadata" in diff else self.metadata)
        routing = (RoutingTable.from_dict(diff["routing_table"])
                   if "routing_table" in diff else self.routing_table)
        return replace(out, term=diff["term"], version=diff["version"],
                       state_uuid=diff["to_uuid"],
                       master_node_id=diff.get("master_node"),
                       nodes=nodes, metadata=metadata, routing_table=routing,
                       blocks=tuple(diff.get("blocks", ())),
                       voting_config=frozenset(diff.get("voting_config", ())))


class IncompatibleClusterStateError(Exception):
    """Receiver can't apply a diff (wrong base) — sender retries full state."""
