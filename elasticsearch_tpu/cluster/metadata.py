"""Index + cluster metadata: the schema half of cluster state.

Reference: cluster/metadata/IndexMetadata.java:84 and Metadata. Immutable;
every mutation returns a new object with a bumped version. Serialization is
dict-shaped (the control plane's JSON wire).
"""

from __future__ import annotations

import uuid as uuid_mod
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Mapping, Optional, Tuple

from elasticsearch_tpu.utils.errors import (
    IllegalArgumentError, IndexAlreadyExistsError, IndexNotFoundError,
)


@dataclass(frozen=True)
class IndexMetadata:
    name: str
    uuid: str
    number_of_shards: int = 1
    number_of_replicas: int = 0
    version: int = 1
    state: str = "open"                       # open | close
    mappings: Mapping[str, Any] = field(default_factory=dict)
    settings: Mapping[str, Any] = field(default_factory=dict)
    aliases: Tuple[str, ...] = ()
    # alias name -> properties: {"filter": query?, "routing": str?,
    # "is_write_index": bool?} (AliasMetadata analog). aliases keeps the
    # plain name tuple for cheap membership; configs carry the rest.
    alias_configs: Mapping[str, Any] = field(default_factory=dict)
    # per-shard primary term, bumped on every primary failover
    # (IndexMetadata.java primaryTerms[]; carried by every replicated op)
    primary_terms: Tuple[int, ...] = ()

    def __post_init__(self) -> None:
        if self.number_of_shards < 1:
            raise IllegalArgumentError("number_of_shards must be >= 1")
        if self.number_of_replicas < 0:
            raise IllegalArgumentError("number_of_replicas must be >= 0")
        if not self.primary_terms:
            object.__setattr__(self, "primary_terms",
                               tuple([1] * self.number_of_shards))

    def primary_term(self, shard: int) -> int:
        return self.primary_terms[shard]

    def with_primary_term_bump(self, shard: int) -> "IndexMetadata":
        terms = list(self.primary_terms)
        terms[shard] += 1
        return replace(self, primary_terms=tuple(terms),
                       version=self.version + 1)

    @staticmethod
    def create(name: str, number_of_shards: int = 1,
               number_of_replicas: int = 0,
               mappings: Optional[Mapping[str, Any]] = None,
               settings: Optional[Mapping[str, Any]] = None) -> "IndexMetadata":
        return IndexMetadata(name=name, uuid=uuid_mod.uuid4().hex,
                             number_of_shards=number_of_shards,
                             number_of_replicas=number_of_replicas,
                             mappings=dict(mappings or {}),
                             settings=dict(settings or {}))

    def with_mappings(self, mappings: Mapping[str, Any]) -> "IndexMetadata":
        return replace(self, mappings=dict(mappings), version=self.version + 1)

    def with_replicas(self, n: int) -> "IndexMetadata":
        return replace(self, number_of_replicas=n, version=self.version + 1)

    def with_settings(self, settings: Mapping[str, Any]) -> "IndexMetadata":
        merged = {**self.settings, **settings}
        return replace(self, settings=merged, version=self.version + 1)

    def with_aliases(self, aliases: Tuple[str, ...],
                     alias_configs: Optional[Mapping[str, Any]] = None
                     ) -> "IndexMetadata":
        configs = dict(alias_configs if alias_configs is not None
                       else self.alias_configs)
        configs = {k: v for k, v in configs.items() if k in aliases}
        return replace(self, aliases=tuple(aliases),
                       alias_configs=configs, version=self.version + 1)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "uuid": self.uuid,
            "number_of_shards": self.number_of_shards,
            "number_of_replicas": self.number_of_replicas,
            "version": self.version, "state": self.state,
            "mappings": dict(self.mappings), "settings": dict(self.settings),
            "aliases": list(self.aliases),
            "alias_configs": dict(self.alias_configs),
            "primary_terms": list(self.primary_terms),
        }

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "IndexMetadata":
        return IndexMetadata(
            name=d["name"], uuid=d["uuid"],
            number_of_shards=d["number_of_shards"],
            number_of_replicas=d["number_of_replicas"],
            version=d.get("version", 1), state=d.get("state", "open"),
            mappings=dict(d.get("mappings", {})),
            settings=dict(d.get("settings", {})),
            aliases=tuple(d.get("aliases", ())),
            alias_configs=dict(d.get("alias_configs", {})),
            primary_terms=tuple(d.get("primary_terms", ())))


@dataclass(frozen=True)
class Metadata:
    """All cluster-wide persistent metadata (indices, templates, settings)."""

    indices: Mapping[str, IndexMetadata] = field(default_factory=dict)
    # composable index templates: name -> {index_patterns, priority,
    # template: {settings, mappings, aliases}}
    # (cluster/metadata/ComposableIndexTemplate.java analog)
    templates: Mapping[str, Any] = field(default_factory=dict)
    # ILM policies: name -> {phases: {hot: {...}, delete: {...}}}
    # (x-pack/plugin/core/.../ilm/LifecyclePolicy.java analog)
    ilm_policies: Mapping[str, Any] = field(default_factory=dict)
    # security entities: {"users": {name: {hash, salt, roles}},
    # "roles": {name: {cluster, indices}}} — the .security index analog
    security: Mapping[str, Any] = field(default_factory=dict)
    # named custom sections (Metadata.Custom analog): transforms, watches,
    # ... — each a {name: body} map owned by one service
    custom: Mapping[str, Any] = field(default_factory=dict)
    persistent_settings: Mapping[str, Any] = field(default_factory=dict)
    version: int = 0

    @property
    def data_streams(self) -> Dict[str, Any]:
        """name -> {timestamp_field, generation, indices: [backing...]}
        (cluster/metadata/DataStream.java analog, stored as a custom
        section so it replicates/persists like all metadata)."""
        return dict(self.custom.get("data_streams", {}))

    def with_data_stream(self, name: str,
                         body: Optional[Mapping[str, Any]]) -> "Metadata":
        return self.with_custom_entry("data_streams", name, body)

    def index(self, name: str) -> IndexMetadata:
        # alias resolution: a name may be an alias for exactly one index,
        # or for several when exactly one carries is_write_index
        # (AliasOrIndex.Alias.getWriteIndex semantics)
        if name in self.indices:
            return self.indices[name]
        ds = self.custom.get("data_streams", {}).get(name)
        if ds and ds.get("indices"):
            # a data stream resolves to its WRITE index (the latest
            # backing index) for single-index operations
            backing = ds["indices"][-1]
            if backing not in self.indices:
                raise IndexNotFoundError(backing)
            return self.indices[backing]
        matches = [im for im in self.indices.values() if name in im.aliases]
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            writers = [im for im in matches
                       if (im.alias_configs.get(name) or {})
                       .get("is_write_index")]
            if len(writers) == 1:
                return writers[0]
            raise IllegalArgumentError(
                f"alias [{name}] has more than one index associated "
                f"and no single is_write_index")
        raise IndexNotFoundError(name)

    def alias_filters(self, expression: str) -> list:
        """Query filters attached to aliases the expression reaches —
        LITERALLY or via a wildcard part matching the ALIAS name (the
        access path determines filtering; `_all`/bare wildcards over
        index names do not route through aliases).
        Returns [(alias, index, filter), ...]."""
        import fnmatch as _fn
        out = []
        for part in (expression or "").split(","):
            part = part.strip()
            if not part or part in self.indices or part == "_all":
                continue
            for im in self.indices.values():
                for alias in im.aliases:
                    if alias == part or ("*" in part and
                                         _fn.fnmatch(alias, part)):
                        filt = (im.alias_configs.get(alias)
                                or {}).get("filter")
                        if filt is not None:
                            out.append((alias, im.name, filt))
        return out

    def has_index(self, name: str) -> bool:
        try:
            self.index(name)
            return True
        except IndexNotFoundError:
            return False

    def put_index(self, im: IndexMetadata) -> "Metadata":
        if im.name in self.indices:
            raise IndexAlreadyExistsError(
                f"index [{im.name}] already exists")
        return replace(self, indices={**self.indices, im.name: im},
                       version=self.version + 1)

    def update_index(self, im: IndexMetadata) -> "Metadata":
        if im.name not in self.indices:
            raise IndexNotFoundError(im.name)
        return replace(self, indices={**self.indices, im.name: im},
                       version=self.version + 1)

    def remove_index(self, name: str) -> "Metadata":
        if name not in self.indices:
            raise IndexNotFoundError(name)
        indices = {k: v for k, v in self.indices.items() if k != name}
        return replace(self, indices=indices, version=self.version + 1)

    def with_template(self, name: str,
                      template: Optional[Mapping[str, Any]]) -> "Metadata":
        """Put (or with None, delete) one composable index template."""
        templates = {k: v for k, v in self.templates.items() if k != name}
        if template is not None:
            templates[name] = dict(template)
        return replace(self, templates=templates, version=self.version + 1)

    def with_ilm_policy(self, name: str,
                        policy: Optional[Mapping[str, Any]]) -> "Metadata":
        policies = {k: v for k, v in self.ilm_policies.items() if k != name}
        if policy is not None:
            policies[name] = dict(policy)
        return replace(self, ilm_policies=policies,
                       version=self.version + 1)

    def with_security_entity(self, kind: str, name: str,
                             body: Optional[Mapping[str, Any]]
                             ) -> "Metadata":
        """Put (or with None, delete) one user/role under security[kind]."""
        section = {k: v for k, v in
                   dict(self.security.get(kind, {})).items() if k != name}
        if body is not None:
            section[name] = dict(body)
        return replace(self, security={**self.security, kind: section},
                       version=self.version + 1)

    def with_custom_entry(self, section: str, name: str,
                          body: Optional[Mapping[str, Any]]) -> "Metadata":
        """Put (or with None, delete) one entry of a custom section."""
        entries = {k: v for k, v in
                   dict(self.custom.get(section, {})).items() if k != name}
        if body is not None:
            entries[name] = dict(body)
        return replace(self, custom={**self.custom, section: entries},
                       version=self.version + 1)

    def with_persistent_settings(self, settings: Mapping[str, Any]) -> "Metadata":
        # a None value unsets the key (the reference's null-reset semantics
        # for PUT _cluster/settings)
        merged = {**self.persistent_settings, **settings}
        merged = {k: v for k, v in merged.items() if v is not None}
        return replace(self, persistent_settings=merged,
                       version=self.version + 1)

    def matching_templates(self, index_name: str) -> list:
        """Templates whose index_patterns match, highest priority first
        (MetadataIndexTemplateService.findV2Template analog)."""
        import fnmatch
        hits = []
        for name, t in self.templates.items():
            if any(fnmatch.fnmatch(index_name, p)
                   for p in t.get("index_patterns", [])):
                hits.append((int(t.get("priority", 0)), name, t))
        hits.sort(key=lambda h: (-h[0], h[1]))
        return [(name, t) for _, name, t in hits]

    def to_dict(self) -> Dict[str, Any]:
        return {"indices": {k: v.to_dict() for k, v in self.indices.items()},
                "templates": dict(self.templates),
                "ilm_policies": dict(self.ilm_policies),
                "security": dict(self.security),
                "custom": dict(self.custom),
                "persistent_settings": dict(self.persistent_settings),
                "version": self.version}

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Metadata":
        return Metadata(
            indices={k: IndexMetadata.from_dict(v)
                     for k, v in d.get("indices", {}).items()},
            templates=dict(d.get("templates", {})),
            ilm_policies=dict(d.get("ilm_policies", {})),
            security=dict(d.get("security", {})),
            custom=dict(d.get("custom", {})),
            persistent_settings=dict(d.get("persistent_settings", {})),
            version=d.get("version", 0))


def resolve_index_expression(expression: Optional[str],
                             metadata: "Metadata") -> list:
    """Resolve comma lists, ``*`` wildcards, ``_all`` and aliases to concrete
    index names (IndexNameExpressionResolver analog,
    cluster/metadata/IndexNameExpressionResolver.java). Unknown concrete
    names raise IndexNotFoundError; unmatched wildcards resolve empty."""
    import fnmatch

    names = set()
    all_names = list(metadata.indices)
    alias_map: Dict[str, list] = {}
    for im in metadata.indices.values():
        for alias in im.aliases:
            alias_map.setdefault(alias, []).append(im.name)
    streams = metadata.custom.get("data_streams", {})
    for part in (expression or "_all").split(","):
        part = part.strip()
        if part in ("_all", "*", ""):
            names.update(all_names)
        elif "*" in part:
            matched = [n for n in all_names if fnmatch.fnmatch(n, part)]
            matched += [n for a, targets in alias_map.items()
                        if fnmatch.fnmatch(a, part) for n in targets]
            # a wildcard over data-stream NAMES reaches all their backing
            # indices (IndexNameExpressionResolver's data-stream aware
            # wildcard resolution)
            for ds_name, ds in streams.items():
                if fnmatch.fnmatch(ds_name, part):
                    matched += list(ds.get("indices", []))
            names.update(matched)
        elif part in metadata.indices:
            names.add(part)
        elif part in alias_map:
            names.update(alias_map[part])
        elif part in streams:
            # searching a data stream searches EVERY backing index
            names.update(streams[part].get("indices", []))
        else:
            raise IndexNotFoundError(part)
    return sorted(names)
