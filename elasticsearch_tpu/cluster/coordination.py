"""Raft-like cluster coordination: elections + 2-phase state publication.

Reference: cluster/coordination/Coordinator.java:95 (modes CANDIDATE/LEADER/
FOLLOWER), CoordinationState.java:38 (the TLA+-modeled safety core),
PublicationTransportHandler.java:89 (diff-or-full publication),
FollowersChecker.java:64 / LeaderChecker.java:62 (failure detection),
ElectionSchedulerFactory.java:47 (randomized backoff).

Split mirrors the reference: ``CoordinationState`` holds the pure safety
rules (term bumps, join votes with freshness checks, accept/commit quorums)
and owns all persistent state; ``Coordinator`` drives it over the transport
with timers. Safety argument (Raft's): election and publish quorums are
both majorities of the voting config, so they intersect; a joiner with
fresher accepted state than the candidate refuses to vote, hence any winner
has every committed state.

The whole module is scheduler-driven, so the deterministic simulation in
tests/test_coordination.py runs real Coordinators through partitions with
virtual time (AbstractCoordinatorTestCase.java:143 analog).
"""

from __future__ import annotations

import logging
import random as random_mod
import uuid as uuid_mod
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.cluster.state import ClusterState, DiscoveryNode
from elasticsearch_tpu.cluster.state import IncompatibleClusterStateError
from elasticsearch_tpu.transport.scheduler import Cancellable, Scheduler
from elasticsearch_tpu.transport.transport import TransportService
from elasticsearch_tpu.utils.errors import NotMasterError

logger = logging.getLogger(__name__)


# transport action names (reference registers these in Coordinator's ctor)
PRE_VOTE = "coordination/pre_vote"
START_JOIN = "coordination/start_join"
PUBLISH = "coordination/publish"
COMMIT = "coordination/commit"
FOLLOWER_CHECK = "coordination/follower_check"
NODE_JOIN = "coordination/node_join"


class Mode:
    CANDIDATE = "CANDIDATE"
    LEADER = "LEADER"
    FOLLOWER = "FOLLOWER"


def is_quorum(votes: Set[str], voting_config: Set[str]) -> bool:
    return len(votes & voting_config) * 2 > len(voting_config)


@dataclass
class PersistedState:
    """What must survive restart (gateway/GatewayMetaState.java:79 analog;
    disk persistence is wired in via the gateway module)."""
    current_term: int = 0
    accepted_state: ClusterState = field(default_factory=ClusterState)
    # (term, version) of the newest accepted state; accepted_state.term is
    # the MASTER term the state was published in — identical here.


class CoordinationState:
    """Pure consensus rules. No I/O, no timers — every method is a
    transition that either mutates persistent state and returns a message
    to send, or raises. (CoordinationState.java:38 analog.)"""

    def __init__(self, node_id: str, persisted: PersistedState):
        self.node_id = node_id
        self.persisted = persisted
        # volatile (reset on restart)
        self.join_votes: Set[str] = set()
        self.election_won = False
        self.publish_votes: Set[str] = set()
        self.last_published: Optional[Tuple[int, int]] = None  # (term, version)

    # -- accessors -----------------------------------------------------------

    @property
    def current_term(self) -> int:
        return self.persisted.current_term

    @property
    def accepted(self) -> ClusterState:
        return self.persisted.accepted_state

    def is_fresher_or_equal(self, term: int, version: int) -> bool:
        """Is OUR accepted state at least as fresh as (term, version)?"""
        ours = (self.accepted.term, self.accepted.version)
        return ours >= (term, version)

    # -- term bumps + votes ---------------------------------------------------

    def handle_start_join(self, candidate_id: str, new_term: int
                          ) -> Dict[str, Any]:
        """A candidate asks us to move to new_term and vote for it. One vote
        per term (Raft): moving to the term IS casting the vote."""
        if new_term <= self.current_term:
            raise CoordinationError(
                f"start_join term {new_term} <= current {self.current_term}")
        self.persisted.current_term = new_term
        self.join_votes = set()
        self.election_won = False
        self.publish_votes = set()
        return {"term": new_term, "voter": self.node_id,
                "last_accepted_term": self.accepted.term,
                "last_accepted_version": self.accepted.version}

    def handle_join(self, join: Dict[str, Any]) -> bool:
        """Count a vote. Returns True if this join wins the election.
        Rejects votes from nodes with FRESHER state than ours — the
        freshness half of the safety argument."""
        if join["term"] != self.current_term:
            raise CoordinationError(
                f"join term {join['term']} != current {self.current_term}")
        if (join["last_accepted_term"], join["last_accepted_version"]) > \
                (self.accepted.term, self.accepted.version):
            raise CoordinationError(
                "joiner has fresher accepted state than candidate")
        self.join_votes.add(join["voter"])
        won_now = (not self.election_won and
                   is_quorum(self.join_votes, set(self.accepted.voting_config)))
        if won_now:
            self.election_won = True
        return won_now

    # -- publication ----------------------------------------------------------

    def handle_client_value(self, state: ClusterState) -> Dict[str, Any]:
        """Leader: stamp a new state for publication in our term."""
        if not self.election_won:
            raise NotMasterError("not elected")
        if state.version <= self.accepted.version:
            raise CoordinationError(
                f"new version {state.version} <= accepted "
                f"{self.accepted.version}")
        from dataclasses import replace
        state = replace(state, term=self.current_term)
        self.publish_votes = set()
        self.last_published = (state.term, state.version)
        return {"term": self.current_term, "state": state}

    def handle_publish_request(self, term: int, state: ClusterState
                               ) -> Dict[str, Any]:
        """Accept iff it's for our current term and strictly newer than our
        accepted state. Persists before acking (the 'accepted' phase)."""
        if term != self.current_term:
            raise CoordinationError(
                f"publish term {term} != current {self.current_term}")
        incoming = (state.term, state.version)
        ours = (self.accepted.term, self.accepted.version)
        if incoming <= ours:
            raise CoordinationError(
                f"publish {incoming} not newer than accepted {ours}")
        self.persisted.accepted_state = state
        return {"term": term, "version": state.version,
                "voter": self.node_id}

    def handle_publish_response(self, resp: Dict[str, Any]) -> bool:
        """Leader: count an ack; True when the quorum commits (term,version)."""
        if (resp["term"], resp["version"]) != self.last_published:
            return False
        self.publish_votes.add(resp["voter"])
        return is_quorum(self.publish_votes,
                         set(self.accepted.voting_config))

    def handle_commit(self, term: int, version: int) -> ClusterState:
        """Mark the accepted state committed; returns it for applying."""
        if term != self.current_term or \
                (self.accepted.term, self.accepted.version) != (term, version):
            raise CoordinationError(
                f"commit ({term},{version}) does not match accepted "
                f"({self.accepted.term},{self.accepted.version})")
        return self.accepted


class CoordinationError(Exception):
    pass


@dataclass
class CoordinatorSettings:
    election_initial_timeout: float = 0.1     # first election randomized in (0, t]
    election_backoff: float = 0.1             # added per failed attempt
    election_max_timeout: float = 10.0
    heartbeat_interval: float = 1.0           # leader -> follower checks
    follower_timeout: float = 3.0             # follower: no check => candidate
    publish_timeout: float = 30.0


class Coordinator:
    """Drives CoordinationState over the transport with timers.

    Lifecycle: start() as CANDIDATE -> randomized election -> LEADER (wins)
    or FOLLOWER (someone else's publish arrives). The elected leader also
    runs the MasterService role: submit_state_update() queues single-file
    batched updates executed + published one at a time
    (cluster/service/MasterService.java:73 analog).
    """

    def __init__(self, node: DiscoveryNode, transport_service: TransportService,
                 scheduler: Scheduler, initial_state: ClusterState,
                 settings: Optional[CoordinatorSettings] = None,
                 rng: Optional[random_mod.Random] = None,
                 on_committed: Optional[Callable[[ClusterState], None]] = None,
                 seed_peers: Optional[List[str]] = None,
                 persisted_state: Optional[PersistedState] = None):
        self.node = node
        self.ts = transport_service
        self.scheduler = scheduler
        self.settings = settings or CoordinatorSettings()
        # stable across processes: hash() of str is randomized per process
        # (PYTHONHASHSEED), which silently destroyed cross-run determinism
        self.rng = rng or random_mod.Random(
            zlib.crc32(node.node_id.encode()) & 0xFFFF)
        persisted = persisted_state if persisted_state is not None \
            else PersistedState(accepted_state=initial_state)
        self.state = CoordinationState(node.node_id, persisted)
        self.mode = Mode.CANDIDATE
        self.leader_id: Optional[str] = None
        self.applied_state: ClusterState = persisted.accepted_state
        self.on_committed = on_committed
        self._election_attempts = 0
        self._election_timer: Optional[Cancellable] = None
        self._heartbeat_timer: Optional[Cancellable] = None
        self._follower_timer: Optional[Cancellable] = None
        self._publishing = False
        self._update_queue: List[Tuple[str, Callable[[ClusterState], ClusterState],
                                       Callable[[Optional[Exception]], None]]] = []
        self._started = False
        # seed peers: always-probeable addresses (discovery/PeerFinder.java:55
        # probes seed hosts precisely so a node whose accepted membership
        # view is stale/shrunken can still find the quorum)
        self.seed_peers = list(seed_peers or [])
        self._join_nodes: Dict[str, Dict[str, Any]] = {}
        self._inflight_update: Optional[
            Tuple[int, Callable[[Optional[Exception]], None], str]] = None

        for action, handler in [
            (PRE_VOTE, self._on_pre_vote),
            (START_JOIN, self._on_start_join),
            (PUBLISH, self._on_publish),
            (COMMIT, self._on_commit),
            (FOLLOWER_CHECK, self._on_follower_check),
            (NODE_JOIN, self._on_node_join),
        ]:
            self.ts.register_handler(action, handler)
        self._missed_checks: Dict[str, int] = {}

    # -- helpers --------------------------------------------------------------

    def _peers(self) -> List[str]:
        """Master-eligible peers: last accepted membership UNION seed peers
        (the accepted view alone can be shrunken after partitions)."""
        peers = set(self.state.accepted.master_eligible_nodes())
        peers.update(self.seed_peers)
        peers.discard(self.node.node_id)
        return sorted(peers)

    def _voting_config(self) -> Set[str]:
        return set(self.state.accepted.voting_config)

    def _cancel(self, *timers: str) -> None:
        for name in timers:
            t = getattr(self, name)
            if t is not None:
                t.cancel()
                setattr(self, name, None)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._started = True
        self._become_candidate("started")

    def stop(self) -> None:
        self._started = False
        self._cancel("_election_timer", "_heartbeat_timer", "_follower_timer")

    def _become_candidate(self, reason: str) -> None:
        self.mode = Mode.CANDIDATE
        self.leader_id = None
        self._publishing = False
        self._cancel("_heartbeat_timer", "_follower_timer")
        self._fail_queued_updates(NotMasterError(f"stepped down: {reason}"))
        self._schedule_election()

    def _become_leader(self) -> None:
        self.mode = Mode.LEADER
        self.leader_id = self.node.node_id
        self._cancel("_election_timer", "_follower_timer")
        self._election_attempts = 0
        self._start_heartbeats()
        # republish the accepted state under our new term so it commits
        # (Zen2: the winner's first publication carries its freshest state),
        # folding every voter back into membership — joins ARE node-joins
        # (JoinTaskExecutor analog); a prior partition may have shrunk the
        # accepted membership view
        base = self.state.accepted
        nodes = dict(base.nodes)
        nodes[self.node.node_id] = self.node
        for voter in self.state.join_votes:
            if voter not in nodes:
                info = self._join_nodes.get(voter)
                if info:
                    nodes[voter] = DiscoveryNode.from_dict(info)
        new_state = base.with_nodes(nodes, self.node.node_id)
        self._publish(new_state)

    def _become_follower(self, leader_id: str) -> None:
        if self.mode != Mode.FOLLOWER or self.leader_id != leader_id:
            self.mode = Mode.FOLLOWER
            self.leader_id = leader_id
            self._cancel("_election_timer", "_heartbeat_timer")
            self._fail_queued_updates(NotMasterError("following " + leader_id))
            self._election_attempts = 0
        self._reset_follower_timer()

    # -- elections ------------------------------------------------------------

    def _schedule_election(self) -> None:
        if not self._started:
            return
        self._cancel("_election_timer")
        upper = min(self.settings.election_initial_timeout +
                    self._election_attempts * self.settings.election_backoff,
                    self.settings.election_max_timeout)
        delay = self.rng.uniform(0, upper) if upper > 0 else 0.0
        self._election_timer = self.scheduler.schedule(delay, self._run_election)

    def _run_election(self) -> None:
        if self.mode != Mode.CANDIDATE or not self._started:
            return
        self._election_attempts += 1
        self._schedule_election()          # retry backoff if this one stalls
        # pre-vote round: don't bump terms unless a quorum would follow us
        # (PreVoteCollector analog — avoids term inflation from isolated nodes)
        votes: Set[str] = set()
        responded = {"done": False}
        req = {"term": self.state.current_term,
               "last_accepted_term": self.state.accepted.term,
               "last_accepted_version": self.state.accepted.version}

        def on_pre_vote(from_id: str, resp, err) -> None:
            if responded["done"] or err is not None or resp is None:
                return
            if not resp.get("grant"):
                # peer follows a live leader — (re)join through it instead of
                # fighting the election (PeerFinder-discovers-master analog).
                # Idempotent if we're already a member: the leader's add()
                # no-ops. Our own membership view may be stale, so don't
                # consult it.
                leader = resp.get("leader")
                if leader and leader != self.node.node_id and \
                        self.mode == Mode.CANDIDATE:
                    self._request_node_join(leader)
                return
            votes.add(from_id)
            if is_quorum(votes, self._voting_config()):
                responded["done"] = True
                self._start_real_election()

        self._on_pre_vote_local(votes)
        if is_quorum(votes, self._voting_config()):
            self._start_real_election()
            return
        for peer in self._peers():
            self.ts.send_request(
                peer, PRE_VOTE, req,
                lambda r, e, p=peer: on_pre_vote(p, r, e), timeout=1.0)

    def _on_pre_vote_local(self, votes: Set[str]) -> None:
        votes.add(self.node.node_id)

    def _start_real_election(self) -> None:
        if self.mode != Mode.CANDIDATE:
            return
        new_term = self.state.current_term + 1
        for peer in [self.node.node_id] + self._peers():
            self.ts.send_request(
                peer, START_JOIN,
                {"candidate": self.node.node_id, "term": new_term},
                self._on_join_response, timeout=1.0)

    def _on_join_response(self, resp, err) -> None:
        # start_join returns the voter's join directly as its response
        if err is not None or resp is None:
            return
        self._count_join(resp)

    def _count_join(self, join: Dict[str, Any]) -> None:
        if self.mode != Mode.CANDIDATE:
            return
        try:
            won = self.state.handle_join(join)
        except CoordinationError:
            return
        if join.get("node"):
            self._join_nodes[join["voter"]] = join["node"]
        if won:
            self._become_leader()

    # -- handlers -------------------------------------------------------------

    def _on_pre_vote(self, req: Dict[str, Any], sender: str) -> Dict[str, Any]:
        # grant if we have no live leader and the candidate is as fresh as us
        fresh = not self.state.is_fresher_or_equal(
            req["last_accepted_term"], req["last_accepted_version"] + 1)
        # fresh == candidate's accepted >= ours
        grant = (self.mode != Mode.LEADER and self.leader_id is None and fresh)
        return {"grant": bool(grant), "leader": self.leader_id}

    def _on_start_join(self, req: Dict[str, Any], sender: str
                       ) -> Dict[str, Any]:
        join = self.state.handle_start_join(req["candidate"], req["term"])
        join["node"] = self.node.to_dict()   # joins double as node-joins
        # moving to a higher term deposes us/stops following
        if self.mode != Mode.CANDIDATE:
            self._become_candidate(f"higher term {req['term']}")
        return join

    def _on_publish(self, req: Dict[str, Any], sender: str) -> Dict[str, Any]:
        term = req["term"]
        if term > self.state.current_term:
            # implicit start_join: adopt the term, then accept
            self.state.handle_start_join(sender, term)
        if "diff" in req:
            try:
                state = self.applied_state.apply_diff(req["diff"])
            except IncompatibleClusterStateError:
                return {"need_full": True}
        else:
            state = ClusterState.from_dict(req["state"])
        ack = self.state.handle_publish_request(term, state)
        if sender != self.node.node_id:
            self._become_follower(sender)
        return ack

    def _on_commit(self, req: Dict[str, Any], sender: str) -> Dict[str, Any]:
        state = self.state.handle_commit(req["term"], req["version"])
        self._apply(state)
        return {}

    def _on_follower_check(self, req: Dict[str, Any], sender: str
                           ) -> Dict[str, Any]:
        if req["term"] < self.state.current_term:
            raise CoordinationError("check from stale leader")
        if req["term"] > self.state.current_term:
            self.state.handle_start_join(sender, req["term"])
        self._become_follower(sender)
        # the responder's identity rides along so the leader's failure
        # detector doubles as a REBOOT detector: a restarted process
        # answers checks with the SAME applied (term, version) — its
        # gateway persisted them — but different content (routing reset),
        # so version comparison alone can never notice it. The ephemeral
        # id can (DiscoveryNode per-boot identity).
        return {"ok": True, "applied_term": self.applied_state.term,
                "applied_version": self.applied_state.version,
                "node": self.node.to_dict()}

    # -- publication ----------------------------------------------------------

    def _publish(self, new_state: ClusterState) -> None:
        self._publishing = True
        try:
            pub = self.state.handle_client_value(new_state)
        except (NotMasterError, CoordinationError):
            self._publishing = False
            self._become_candidate("publication rejected locally")
            return
        state: ClusterState = pub["state"]
        term = pub["term"]
        targets = list(state.nodes) or [self.node.node_id]
        if self.node.node_id not in targets:
            targets.append(self.node.node_id)
        committed = {"done": False}
        timeout_handle = self.scheduler.schedule(
            self.settings.publish_timeout,
            lambda: self._publication_failed(term, state.version, committed))

        def on_ack(resp, err, target: str) -> None:
            if isinstance(resp, dict) and resp.get("need_full"):
                # retry that node with the full state
                self.ts.send_request(
                    target, PUBLISH,
                    {"term": term, "state": state.to_dict()},
                    lambda r, e, t=target: on_ack(r, e, t),
                    timeout=self.settings.publish_timeout)
                return
            if err is not None or resp is None:
                return
            if committed["done"]:
                # late ack after the quorum commit fan-out already went
                # out — typical for a rebooted follower whose diff came
                # back need_full and whose full-state retry cost an
                # extra round-trip. Without a commit of its own, that
                # follower is left accepted-but-never-applied, and
                # catch-up can't heal it (its re-publish of the same
                # version is rejected as not-newer-than-accepted).
                self.ts.send_request(target, COMMIT,
                                     {"term": term,
                                      "version": state.version},
                                     lambda r, e: None, timeout=30.0)
                return
            if self.state.handle_publish_response(resp):
                committed["done"] = True
                timeout_handle.cancel()
                self._send_commits(term, state.version, targets)

        base = self.applied_state
        diff_payload = ({"term": term, "diff": state.diff_from(base)}
                        if base.state_uuid != "_na_" else None)
        full_payload = {"term": term, "state": state.to_dict()}
        for target in targets:
            use_diff = (diff_payload is not None and
                        target != self.node.node_id and target in base.nodes)
            self.ts.send_request(
                target, PUBLISH, diff_payload if use_diff else full_payload,
                lambda r, e, t=target: on_ack(r, e, t),
                timeout=self.settings.publish_timeout)

    def _send_commits(self, term: int, version: int, targets: List[str]) -> None:
        for target in targets:
            self.ts.send_request(target, COMMIT,
                                 {"term": term, "version": version},
                                 lambda r, e: None, timeout=30.0)
        self._publishing = False
        # the next queued update drains only after OUR commit applies
        # (_on_applied_for_updates) so the in-flight slot is free again

    def _publication_failed(self, term: int, version: int,
                            committed: Dict[str, bool]) -> None:
        if committed["done"]:
            return
        committed["done"] = True
        self._become_candidate(f"publication ({term},{version}) timed out")

    def _apply(self, state: ClusterState) -> None:
        if state.version <= self.applied_state.version and \
                state.state_uuid == self.applied_state.state_uuid:
            return
        self.applied_state = state
        if self.on_committed is not None:
            # An applier failure must never wedge the master-service queue:
            # the state IS committed cluster-wide regardless of what one
            # node's appliers do with it (ClusterApplierService.java:74
            # catches applier exceptions the same way). The local index
            # error surfaces through shard-level failure, not here.
            try:
                self.on_committed(state)
            except Exception:  # noqa: BLE001
                logger.exception("cluster state applier failed on %s v%s",
                                 self.node.node_id, state.version)
        self._on_applied_for_updates(state)

    # -- MasterService role ---------------------------------------------------

    def submit_state_update(
            self, description: str,
            update_fn: Callable[[ClusterState], ClusterState],
            on_done: Callable[[Optional[Exception]], None] = lambda e: None
    ) -> None:
        """Queue a cluster-state mutation; executed single-file on the
        elected master, published, committed, then on_done(None). Any
        failure (not master, no quorum) => on_done(error)."""
        if self.mode != Mode.LEADER:
            on_done(NotMasterError(
                f"node [{self.node.node_id}] is not the master"))
            return
        self._update_queue.append((description, update_fn, on_done))
        if not self._publishing:
            self._drain_update_queue()

    def _drain_update_queue(self) -> None:
        if self.mode != Mode.LEADER or self._publishing or \
                self._inflight_update is not None or not self._update_queue:
            return
        description, update_fn, on_done = self._update_queue.pop(0)
        base = self.state.accepted
        try:
            new_state = update_fn(base)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            on_done(e)
            self._drain_update_queue()
            return
        if new_state is base or new_state is None:
            on_done(None)
            self._drain_update_queue()
            return
        # completion fires on the commit of exactly this version — or on
        # failure via _fail_queued_updates when we step down
        version = new_state.version
        self._inflight_update = (version, on_done, description)
        self._publish(new_state)

    def _on_applied_for_updates(self, state: ClusterState) -> None:
        inflight = self._inflight_update
        if inflight is not None and state.version >= inflight[0]:
            self._inflight_update = None
            inflight[1](None)
            self._drain_update_queue()

    def _fail_queued_updates(self, error: Exception) -> None:
        inflight = self._inflight_update
        if inflight is not None:
            self._inflight_update = None
            inflight[1](error)
        queue, self._update_queue = self._update_queue, []
        for _desc, _fn, on_done in queue:
            on_done(error)

    # -- failure detection ----------------------------------------------------

    def _start_heartbeats(self) -> None:
        self._cancel("_heartbeat_timer")

        def beat() -> None:
            if self.mode != Mode.LEADER:
                return
            missed = self._missed_checks
            for peer in [nid for nid in self.state.accepted.nodes
                         if nid != self.node.node_id]:
                def on_resp(r, e, p=peer) -> None:
                    if e is None:
                        missed[p] = 0
                        if r and (r.get("applied_term", 0),
                                  r.get("applied_version", 0)) < \
                                (self.applied_state.term,
                                 self.applied_state.version):
                            self._catch_up(p)
                        elif r and r.get("node"):
                            # same version but a NEW ephemeral id: the
                            # process rebooted into gateway-reset state
                            # that our version checks can't distinguish.
                            # Re-admit it like a join — the entry replace
                            # bumps the version, and the uuid mismatch
                            # forces a full-state redelivery, which the
                            # rebooted node's reconciler turns into
                            # in-place store recovery.
                            responder = DiscoveryNode.from_dict(r["node"])
                            known = self.applied_state.nodes.get(p)
                            if known is not None and \
                                    responder.ephemeral_id and \
                                    known.ephemeral_id != \
                                    responder.ephemeral_id:
                                self._readmit_rebooted(responder)
                    else:
                        missed[p] = missed.get(p, 0) + 1
                        if missed[p] >= 3:
                            self._on_follower_failed(p)
                self.ts.send_request(peer, FOLLOWER_CHECK,
                                     {"term": self.state.current_term,
                                      "leader": self.node.node_id},
                                     on_resp,
                                     timeout=self.settings.heartbeat_interval)
            self._heartbeat_timer = self.scheduler.schedule(
                self.settings.heartbeat_interval, beat)

        self._heartbeat_timer = self.scheduler.schedule(
            self.settings.heartbeat_interval, beat)

    def _readmit_rebooted(self, joining: DiscoveryNode) -> None:
        """Replace a member entry whose process restarted behind it (seen
        via the heartbeat's ephemeral id). Same update as a NODE_JOIN from
        a restarted process; idempotent — once the entry carries the new
        ephemeral id the guard no-ops."""
        if self.mode != Mode.LEADER:
            return

        def update(state: ClusterState) -> ClusterState:
            existing = state.nodes.get(joining.node_id)
            if existing is None or \
                    existing.ephemeral_id == joining.ephemeral_id:
                return state
            return state.with_nodes(
                {**state.nodes, joining.node_id: joining},
                state.master_node_id)
        self.submit_state_update(
            f"node-rebooted [{joining.node_id}]", update)

    def _catch_up(self, peer: str) -> None:
        """Re-send the COMMITTED state to a lagging follower (a healed
        partition leaves followers with stale applied state until the next
        publication; the reference relies on every publication being full
        per-node + LagDetector — here the leader pushes directly).

        Must use applied_state, never state.accepted: accepted may be an
        in-flight publication that hasn't reached quorum, and committing it
        on one follower could surface a state the cluster later loses."""
        if self.mode != Mode.LEADER:
            return
        state = self.applied_state
        if state.term != self.state.current_term:
            return  # our first publication hasn't committed yet

        def on_ack(r, e) -> None:
            if e is None and r is not None and r.get("need_full"):
                return
            # send the commit even when the publish was REJECTED: a
            # follower that already ACCEPTED this exact (term, version)
            # but missed only the commit round (reboot raced a
            # diff->need_full->full retry against the commit fan-out)
            # rejects the re-publish as not-newer-than-accepted — the
            # commit is precisely what it is missing. handle_commit
            # validates the (term, version) match, so an unconditional
            # send is safe; a genuine mismatch just errors out remotely.
            self.ts.send_request(peer, COMMIT,
                                 {"term": state.term,
                                  "version": state.version},
                                 lambda r2, e2: None, timeout=30.0)
        self.ts.send_request(peer, PUBLISH,
                             {"term": self.state.current_term,
                              "state": state.to_dict()},
                             on_ack, timeout=30.0)

    def _on_follower_failed(self, node_id: str) -> None:
        """Leader noticed a dead follower (FollowersChecker analog). Remove
        it from the cluster state via a normal state update."""
        if self.mode != Mode.LEADER:
            return
        self._missed_checks.pop(node_id, None)

        def remove(state: ClusterState) -> ClusterState:
            if node_id not in state.nodes:
                return state
            nodes = {nid: n for nid, n in state.nodes.items() if nid != node_id}
            return state.with_nodes(nodes, self.node.node_id)
        self.submit_state_update(f"node-left [{node_id}]", remove)

    # -- membership (re)join --------------------------------------------------

    def _request_node_join(self, leader_id: str) -> None:
        self.ts.send_request(leader_id, NODE_JOIN,
                             {"node": self.node.to_dict()},
                             lambda r, e: None, timeout=5.0)

    def _on_node_join(self, req: Dict[str, Any], sender: str
                      ) -> Dict[str, Any]:
        """A node (re)joins through the elected leader: added to the cluster
        state, which the next publication delivers to it
        (JoinHelper/JoinTaskExecutor analog)."""
        if self.mode != Mode.LEADER:
            raise NotMasterError("not the master")
        joining = DiscoveryNode.from_dict(req["node"])

        def add(state: ClusterState) -> ClusterState:
            existing = state.nodes.get(joining.node_id)
            if existing is not None:
                if existing.ephemeral_id == joining.ephemeral_id:
                    # the same running process re-sent its join (e.g. a
                    # one-way partition keeps triggering its pre-vote →
                    # rejoin path): a pure duplicate, and it must stay a
                    # NO-OP or one flapping node drives unbounded
                    # publication churn
                    return state
                # a NEW ephemeral id = the process restarted: its
                # in-memory state is whatever the gateway persisted (with
                # routing reset). Replace the entry — the version bump
                # makes the next publication re-deliver the full
                # committed state; otherwise nothing publishes and the
                # rebooted node serves stale state forever (the
                # reference's JoinTaskExecutor + ephemeral-id semantics)
                return state.with_nodes(
                    {**state.nodes, joining.node_id: joining},
                    state.master_node_id)
            state = state.with_nodes(
                {**state.nodes, joining.node_id: joining},
                self.node.node_id)
            # re-enfranchisement: ONLY a joiner whose exclusion was
            # cleared while it was away (recorded as voting_pending)
            # re-enters the voting configuration. Unconditional growth
            # would let a transient joiner leave behind an even-sized
            # config whose quorum a later departure can never reach.
            pending = state.metadata.custom.get("voting_pending", {})
            if joining.is_master_eligible and \
                    joining.node_id in pending and \
                    joining.node_id not in state.voting_config:
                from dataclasses import replace
                state = replace(state, voting_config=frozenset(
                    set(state.voting_config) | {joining.node_id}))
                state = state.next_version(
                    metadata=state.metadata.with_custom_entry(
                        "voting_pending", joining.node_id, None))
            return state
        self.submit_state_update(f"node-join [{joining.node_id}]", add)
        return {}

    def _reset_follower_timer(self) -> None:
        self._cancel("_follower_timer")
        self._follower_timer = self.scheduler.schedule(
            self.settings.follower_timeout,
            lambda: self._become_candidate("leader check timeout"))
