"""Shard routing table: which node holds which shard copy, in which state.

Reference: cluster/routing/RoutingTable.java:58, ShardRouting states
UNASSIGNED/INITIALIZING/STARTED/RELOCATING, and OperationRouting.java:216
(murmur3(routing) % shards doc partitioning — implemented in
utils/murmur3.py's route_shard). Immutable, like everything in cluster
state.
"""

from __future__ import annotations

import uuid as uuid_mod
from dataclasses import dataclass, field, replace
from enum import Enum
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from elasticsearch_tpu.utils.errors import ShardNotFoundError


class ShardState(str, Enum):
    UNASSIGNED = "UNASSIGNED"
    INITIALIZING = "INITIALIZING"
    STARTED = "STARTED"
    RELOCATING = "RELOCATING"


@dataclass(frozen=True)
class ShardRouting:
    index: str
    shard_id: int
    primary: bool
    state: ShardState = ShardState.UNASSIGNED
    node_id: Optional[str] = None
    relocating_node_id: Optional[str] = None
    allocation_id: Optional[str] = None       # identity of this shard copy
    # consecutive allocation failures (UnassignedInfo.getNumFailedAllocations
    # analog) — MaxRetryDecider stops retry storms; reset by an explicit
    # reroute with retry_failed
    failed_attempts: int = 0
    # why the last copy failed (UnassignedInfo.getDetails analog) —
    # surfaced by _cluster/allocation/explain so operators can see e.g.
    # a corruption marker keeping a shard red
    unassigned_reason: Optional[str] = None
    # the allocation id this copy held BEFORE it became unassigned
    # (UnassignedInfo + in-sync-allocation-ids analog): the gateway
    # allocator matches on-disk copies against it so a restarted shard
    # goes back to the node actually holding its data
    last_allocation_id: Optional[str] = None

    @property
    def active(self) -> bool:
        return self.state in (ShardState.STARTED, ShardState.RELOCATING)

    @property
    def assigned(self) -> bool:
        return self.node_id is not None

    def initialize(self, node_id: str) -> "ShardRouting":
        assert self.state == ShardState.UNASSIGNED
        return replace(self, state=ShardState.INITIALIZING, node_id=node_id,
                       allocation_id=uuid_mod.uuid4().hex)

    def start(self) -> "ShardRouting":
        assert self.state == ShardState.INITIALIZING
        # a successful start clears the failure streak: MaxRetryDecider
        # counts CONSECUTIVE failures (UnassignedInfo is discarded once a
        # shard starts in the reference)
        return replace(self, state=ShardState.STARTED, failed_attempts=0,
                       unassigned_reason=None, last_allocation_id=None)

    def relocate(self, target_node: str) -> "ShardRouting":
        assert self.state == ShardState.STARTED
        return replace(self, state=ShardState.RELOCATING,
                       relocating_node_id=target_node)

    def fail(self, reason: Optional[str] = None) -> "ShardRouting":
        # an ACTIVE copy's identity is its own allocation id; a copy that
        # never started (failed mid-recovery) keeps pointing at the prior
        # on-disk identity, so the gateway fetch can still match the data
        # that outlived the failed attempt
        last = self.allocation_id if self.active else \
            (self.last_allocation_id or self.allocation_id)
        return ShardRouting(index=self.index, shard_id=self.shard_id,
                            primary=self.primary,
                            failed_attempts=self.failed_attempts + 1,
                            unassigned_reason=reason or
                            self.unassigned_reason,
                            last_allocation_id=last)

    def promote_to_primary(self) -> "ShardRouting":
        return replace(self, primary=True)

    def to_dict(self) -> Dict[str, Any]:
        return {"index": self.index, "shard": self.shard_id,
                "primary": self.primary, "state": self.state.value,
                "node": self.node_id,
                "relocating_node": self.relocating_node_id,
                "allocation_id": self.allocation_id,
                "failed_attempts": self.failed_attempts,
                "unassigned_reason": self.unassigned_reason,
                "last_allocation_id": self.last_allocation_id}

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "ShardRouting":
        return ShardRouting(index=d["index"], shard_id=d["shard"],
                            primary=d["primary"],
                            state=ShardState(d["state"]),
                            node_id=d.get("node"),
                            relocating_node_id=d.get("relocating_node"),
                            allocation_id=d.get("allocation_id"),
                            failed_attempts=d.get("failed_attempts", 0),
                            unassigned_reason=d.get("unassigned_reason"),
                            last_allocation_id=d.get("last_allocation_id"))


@dataclass(frozen=True)
class IndexRoutingTable:
    """All shard copies of one index: shards[shard_id] = (primary, *replicas)."""

    index: str
    shards: Mapping[int, Tuple[ShardRouting, ...]] = field(default_factory=dict)

    @staticmethod
    def new(index: str, n_shards: int, n_replicas: int) -> "IndexRoutingTable":
        shards: Dict[int, Tuple[ShardRouting, ...]] = {}
        for sid in range(n_shards):
            group = [ShardRouting(index=index, shard_id=sid, primary=True)]
            group += [ShardRouting(index=index, shard_id=sid, primary=False)
                      for _ in range(n_replicas)]
            shards[sid] = tuple(group)
        return IndexRoutingTable(index=index, shards=shards)

    def shard_group(self, shard_id: int) -> Tuple[ShardRouting, ...]:
        if shard_id not in self.shards:
            raise ShardNotFoundError(
                f"shard [{self.index}][{shard_id}] not found")
        return self.shards[shard_id]

    def primary(self, shard_id: int) -> ShardRouting:
        for sr in self.shard_group(shard_id):
            if sr.primary:
                return sr
        raise ShardNotFoundError(
            f"no primary for shard [{self.index}][{shard_id}]")

    def replace_shard(self, old: ShardRouting, new: ShardRouting
                      ) -> "IndexRoutingTable":
        group = list(self.shards[old.shard_id])
        idx = group.index(old)
        group[idx] = new
        return IndexRoutingTable(
            index=self.index,
            shards={**self.shards, old.shard_id: tuple(group)})

    def all_shards(self) -> Iterable[ShardRouting]:
        for group in self.shards.values():
            yield from group

    @property
    def all_primaries_active(self) -> bool:
        return all(self.primary(sid).active for sid in self.shards)

    @property
    def all_active(self) -> bool:
        return all(sr.active for sr in self.all_shards())

    def to_dict(self) -> Dict[str, Any]:
        return {"index": self.index,
                "shards": {str(sid): [sr.to_dict() for sr in group]
                           for sid, group in self.shards.items()}}

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "IndexRoutingTable":
        return IndexRoutingTable(
            index=d["index"],
            shards={int(sid): tuple(ShardRouting.from_dict(s) for s in group)
                    for sid, group in d.get("shards", {}).items()})


@dataclass(frozen=True)
class RoutingTable:
    indices: Mapping[str, IndexRoutingTable] = field(default_factory=dict)

    def index(self, name: str) -> IndexRoutingTable:
        if name not in self.indices:
            raise ShardNotFoundError(f"no routing for index [{name}]")
        return self.indices[name]

    def has_index(self, name: str) -> bool:
        return name in self.indices

    def put_index(self, irt: IndexRoutingTable) -> "RoutingTable":
        return RoutingTable(indices={**self.indices, irt.index: irt})

    def remove_index(self, name: str) -> "RoutingTable":
        return RoutingTable(indices={k: v for k, v in self.indices.items()
                                     if k != name})

    def all_shards(self) -> Iterable[ShardRouting]:
        for irt in self.indices.values():
            yield from irt.all_shards()

    def shards_on_node(self, node_id: str) -> List[ShardRouting]:
        return [sr for sr in self.all_shards() if sr.node_id == node_id or
                sr.relocating_node_id == node_id]

    def to_dict(self) -> Dict[str, Any]:
        return {"indices": {k: v.to_dict() for k, v in self.indices.items()}}

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "RoutingTable":
        return RoutingTable(
            indices={k: IndexRoutingTable.from_dict(v)
                     for k, v in d.get("indices", {}).items()})
