"""Cluster control plane: immutable state, routing, allocation, consensus.

Reference layer L3 (SURVEY.md §1): cluster/ClusterState.java:86 (immutable
versioned state), cluster/routing/ (shard routing + allocation),
cluster/coordination/ (Zen2 consensus). Host-side Python over the transport
layer — the MPMD control plane of the two-plane split; the SPMD data plane
lives in parallel/.
"""

from elasticsearch_tpu.cluster.state import (
    ClusterState, DiscoveryNode, Roles,
)
from elasticsearch_tpu.cluster.metadata import IndexMetadata, Metadata
from elasticsearch_tpu.cluster.routing import (
    IndexRoutingTable, RoutingTable, ShardRouting, ShardState,
)
from elasticsearch_tpu.cluster.allocation import AllocationService

__all__ = [
    "AllocationService", "ClusterState", "DiscoveryNode", "IndexMetadata",
    "IndexRoutingTable", "Metadata", "Roles", "RoutingTable", "ShardRouting",
    "ShardState",
]
