"""Field mappings and document parsing.

Mirrors the role of the reference's mapper layer —
``MapperService`` (index/mapper/MapperService.java:75),
``DocumentParser`` (index/mapper/DocumentParser.java:44) and the 29 field
mappers (index/mapper/*FieldMapper.java) plus the x-pack ``dense_vector``
(x-pack/plugin/vectors/.../mapper/DenseVectorFieldMapper.java) and
``rank_features`` (modules/mapper-extras/.../RankFeaturesFieldMapper.java) —
re-designed for a segment model where parsing produces *typed columns*
(terms with positions, numeric doc values, vectors) destined for padded
device arrays rather than a Lucene document.
"""

from __future__ import annotations

import datetime as _dt
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.analysis import AnalysisRegistry, Token
from elasticsearch_tpu.utils.errors import IllegalArgumentError, MapperParsingError

# Max vector dims, mirroring the reference's cap
# (x-pack/plugin/vectors/.../mapper/DenseVectorFieldMapper.java:45 — MAX_DIMS_COUNT=2048).
MAX_VECTOR_DIMS = 4096


@dataclass
class ParsedField:
    """One field's parsed contribution to a document."""
    name: str
    kind: str                                   # 'terms' | 'numeric' | 'vector' | 'features' | 'bool' | 'geo'
    terms: Optional[List[Token]] = None         # text: analyzed tokens with positions
    exact_terms: Optional[List[str]] = None     # keyword: untokenized values
    numeric: Optional[List[float]] = None       # numeric/date doc values
    vector: Optional[List[float]] = None        # dense_vector
    features: Optional[Dict[str, float]] = None # rank_features sparse weights
    geo: Optional[Tuple[float, float]] = None   # (lat, lon)


@dataclass
class ParsedDocument:
    doc_id: str
    source: Dict[str, Any]
    fields: Dict[str, ParsedField] = field(default_factory=dict)
    routing: Optional[str] = None


class FieldMapper:
    """Base field mapper. Subclasses parse one JSON value into a ParsedField."""

    type_name = "unknown"
    searchable = True
    has_doc_values = False

    def __init__(self, name: str, params: Dict[str, Any], analysis: AnalysisRegistry):
        self.name = name
        self.params = params

    def parse(self, value: Any) -> ParsedField:
        raise NotImplementedError

    def to_mapping(self) -> Dict[str, Any]:
        out = {"type": self.type_name}
        out.update(self.params)
        return out


class TextFieldMapper(FieldMapper):
    """Analyzed full-text field (reference: index/mapper/TextFieldMapper.java)."""

    type_name = "text"

    def __init__(self, name: str, params: Dict[str, Any], analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.analyzer = analysis.get(params.get("analyzer", "standard"))
        self.search_analyzer = analysis.get(
            params.get("search_analyzer", params.get("analyzer", "standard")))

    def parse(self, value: Any) -> ParsedField:
        if isinstance(value, list):
            tokens: List[Token] = []
            pos_base = 0
            for v in value:
                toks = self.analyzer.analyze(str(v))
                for t in toks:
                    t.position += pos_base
                tokens.extend(toks)
                # position gap of 100 between array values, like Lucene's
                # default; every value advances the base, even empty ones
                pos_base = (toks[-1].position if toks else pos_base) + 100
            return ParsedField(self.name, "terms", terms=tokens)
        return ParsedField(self.name, "terms", terms=self.analyzer.analyze(str(value)))


class KeywordFieldMapper(FieldMapper):
    """Exact-value field (reference: index/mapper/KeywordFieldMapper.java)."""

    type_name = "keyword"
    has_doc_values = True

    def __init__(self, name: str, params: Dict[str, Any], analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.ignore_above = params.get("ignore_above")

    def parse(self, value: Any) -> ParsedField:
        values = value if isinstance(value, list) else [value]
        out = []
        for v in values:
            s = str(v)
            if self.ignore_above is not None and len(s) > self.ignore_above:
                continue
            out.append(s)
        return ParsedField(self.name, "terms", exact_terms=out)


_INT_RANGES = {
    "byte": (-(1 << 7), (1 << 7) - 1),
    "short": (-(1 << 15), (1 << 15) - 1),
    "integer": (-(1 << 31), (1 << 31) - 1),
    "long": (-(1 << 63), (1 << 63) - 1),
}


class NumberFieldMapper(FieldMapper):
    """Numeric types (reference: index/mapper/NumberFieldMapper.java)."""

    has_doc_values = True

    def __init__(self, name: str, params: Dict[str, Any], analysis: AnalysisRegistry,
                 type_name: str = "long"):
        super().__init__(name, params, analysis)
        self.type_name = type_name
        self.scaling_factor = params.get("scaling_factor")
        if type_name == "scaled_float" and not self.scaling_factor:
            raise MapperParsingError(f"scaled_float [{name}] requires [scaling_factor]")

    def parse(self, value: Any) -> ParsedField:
        values = value if isinstance(value, list) else [value]
        out = []
        for v in values:
            if self.type_name in _INT_RANGES:
                # parse integral types exactly (no float round-trip, which
                # corrupts values above 2^53 and mis-ranges values near 2^63)
                try:
                    i = int(v) if not isinstance(v, float) else int(round(v))
                except (TypeError, ValueError):
                    raise MapperParsingError(
                        f"failed to parse field [{self.name}] of type [{self.type_name}]: [{v}]")
                lo, hi = _INT_RANGES[self.type_name]
                if not (lo <= i <= hi):
                    raise MapperParsingError(
                        f"value [{v}] out of range for field [{self.name}] of type [{self.type_name}]")
                # keep exact int (segment builder stores integral doc values
                # as int64 columns; float64 would corrupt above 2^53)
                out.append(i)
                continue
            try:
                f = float(v)
            except (TypeError, ValueError):
                raise MapperParsingError(
                    f"failed to parse field [{self.name}] of type [{self.type_name}]: [{v}]")
            if self.type_name == "scaled_float":
                out.append(round(f * self.scaling_factor) / self.scaling_factor)
            else:
                out.append(f)
        return ParsedField(self.name, "numeric", numeric=out)


class BooleanFieldMapper(FieldMapper):
    type_name = "boolean"
    has_doc_values = True

    def parse(self, value: Any) -> ParsedField:
        values = value if isinstance(value, list) else [value]
        out = []
        for v in values:
            if isinstance(v, bool):
                out.append(1.0 if v else 0.0)
            elif v in ("true", "True"):
                out.append(1.0)
            elif v in ("false", "False"):
                out.append(0.0)
            else:
                raise MapperParsingError(f"cannot parse boolean [{v}] for [{self.name}]")
        return ParsedField(self.name, "numeric", numeric=out)


_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

_DATE_FORMATS = [
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%dT%H:%M:%S.%f",
    "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d", "%Y/%m/%d",
]


def parse_date_millis(value: Any) -> float:
    """Parse a date to epoch millis. Accepts epoch numbers and common ISO formats.

    Reference analog: DateFieldMapper with 'strict_date_optional_time||epoch_millis'.
    """
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    s = str(value)
    if s.endswith("Z"):
        s = s[:-1] + "+0000"
    for fmt in _DATE_FORMATS:
        try:
            dt = _dt.datetime.strptime(s, fmt)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return (dt - _EPOCH).total_seconds() * 1000.0
        except ValueError:
            continue
    try:
        return float(s)  # epoch millis as string
    except ValueError:
        raise MapperParsingError(f"failed to parse date [{value}]")


class DateFieldMapper(FieldMapper):
    type_name = "date"
    has_doc_values = True

    def parse(self, value: Any) -> ParsedField:
        values = value if isinstance(value, list) else [value]
        return ParsedField(self.name, "numeric", numeric=[parse_date_millis(v) for v in values])


class DenseVectorFieldMapper(FieldMapper):
    """Dense float vector for kNN (reference: x-pack DenseVectorFieldMapper).

    Unlike the reference (which stores vectors in binary doc values and scores
    them via painless script loops), vectors here become rows of an
    HBM-resident matrix scored with a tiled MXU matmul (ops/knn.py).
    """

    type_name = "dense_vector"

    def __init__(self, name: str, params: Dict[str, Any], analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.dims = int(params.get("dims", 0))
        if not (0 < self.dims <= MAX_VECTOR_DIMS):
            raise MapperParsingError(
                f"dense_vector [{name}] requires 0 < dims <= {MAX_VECTOR_DIMS}, got {self.dims}")
        self.similarity = params.get("similarity", "cosine")
        if self.similarity not in ("cosine", "dot_product", "l2_norm"):
            raise MapperParsingError(f"unknown similarity [{self.similarity}] for [{name}]")
        self.index_options = params.get("index_options")  # e.g. {'type': 'ivf', 'nlist': 1024}

    def parse(self, value: Any) -> ParsedField:
        if not isinstance(value, list) or len(value) != self.dims:
            raise MapperParsingError(
                f"dense_vector [{self.name}] expects {self.dims} dims, "
                f"got {len(value) if isinstance(value, list) else type(value).__name__}")
        try:
            vec = [float(x) for x in value]
        except (TypeError, ValueError):
            raise MapperParsingError(
                f"dense_vector [{self.name}] contains non-numeric values")
        if any(math.isnan(x) or math.isinf(x) for x in vec):
            raise MapperParsingError(f"dense_vector [{self.name}] contains non-finite values")
        return ParsedField(self.name, "vector", vector=vec)


RANGE_TYPES = {"integer_range", "long_range", "float_range",
               "double_range", "date_range"}


class RangeFieldMapper(FieldMapper):
    """Interval-valued fields (index/mapper/RangeFieldMapper.java):
    a document stores {gte/gt, lte/lt}; queries test interval relations
    (intersects/contains/within). Bounds live on internal ``#lo``/``#hi``
    numeric companion columns (the same pattern as join's parent id)."""

    has_doc_values = False

    def __init__(self, name: str, params: Dict[str, Any],
                 analysis: AnalysisRegistry, type_name: str = "long_range"):
        super().__init__(name, params, analysis)
        self.type_name = type_name

    def _coerce(self, v: Any) -> float:
        if self.type_name == "date_range":
            return float(parse_date_millis(v))
        return float(v)

    def _one_bounds(self, value: Any) -> Tuple[float, float]:
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"range field [{self.name}] expects an object with "
                f"gte/gt/lte/lt bounds")
        try:
            if "gte" in value:
                lo = self._coerce(value["gte"])
            elif "gt" in value:
                lo = self._coerce(value["gt"])   # open bound approximated
            else:
                lo = -math.inf
            if "lte" in value:
                hi = self._coerce(value["lte"])
            elif "lt" in value:
                hi = self._coerce(value["lt"])
            else:
                hi = math.inf
        except (TypeError, ValueError) as e:
            raise MapperParsingError(
                f"failed to parse range field [{self.name}]: {e}")
        if lo > hi:
            raise MapperParsingError(
                f"range field [{self.name}] has gte > lte")
        return lo, hi

    def bounds(self, value: Any) -> Tuple[List[float], List[float]]:
        """([lo...], [hi...]) — a doc may carry several ranges."""
        values = value if isinstance(value, list) else [value]
        pairs = [self._one_bounds(v) for v in values]
        return [p[0] for p in pairs], [p[1] for p in pairs]

    def parse(self, value: Any) -> ParsedField:
        self.bounds(value)   # validate; companions store the numbers
        return ParsedField(self.name, "terms", exact_terms=[])


class JoinFieldMapper(FieldMapper):
    """Parent-child relations within one index
    (modules/parent-join ParentJoinFieldMapper analog).

    A parent doc stores the relation name; a child doc stores
    {"name": <child_rel>, "parent": <parent_id>} and must be routed by the
    parent id so both land on the same shard. The relation name indexes as
    a keyword on this field; the parent id indexes on a companion
    ``<field>#parent`` keyword column the join queries read."""

    type_name = "join"
    has_doc_values = False

    def __init__(self, name: str, params: Dict[str, Any],
                 analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        relations = params.get("relations") or {}
        self.parents = set(relations.keys())
        self.children = set()
        for kids in relations.values():
            self.children.update(kids if isinstance(kids, list) else [kids])

    def parse(self, value: Any) -> ParsedField:
        if isinstance(value, str):
            rel, parent = value, None
        elif isinstance(value, dict):
            rel = value.get("name")
            parent = value.get("parent")
        else:
            raise MapperParsingError(
                f"join [{self.name}] expects a relation name or object")
        if rel not in self.parents | self.children:
            raise MapperParsingError(
                f"unknown join relation [{rel}] for [{self.name}]")
        if rel in self.children and parent is None:
            raise MapperParsingError(
                f"join relation [{rel}] requires [parent]")
        return ParsedField(self.name, "terms", exact_terms=[str(rel)])


class PercolatorFieldMapper(FieldMapper):
    """Stored-query field (modules/percolator PercolatorFieldMapper
    analog): the value is a query body, validated by parsing at INDEX
    time so a broken alert query is rejected when registered, not
    silently skipped at percolation time. The body itself stays in
    _source; percolation evaluates it against a one-doc memory index
    (search/percolate.py)."""

    type_name = "percolator"
    searchable = False

    def parse(self, value: Any) -> ParsedField:
        from elasticsearch_tpu.search import dsl
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"percolator [{self.name}] expects a query object")
        try:
            dsl.parse_query(value)
        except Exception as e:  # noqa: BLE001 — surface as a mapping error
            raise MapperParsingError(
                f"percolator [{self.name}] failed to parse query: {e}")
        # source-only: no columnar contribution
        return ParsedField(self.name, "terms", terms=[])


class RankFeaturesFieldMapper(FieldMapper):
    """Sparse weighted features (reference: RankFeaturesFieldMapper.java).

    The substrate for learned sparse retrieval (ELSER-style text_expansion):
    a document maps feature names to positive weights; queries score with a
    sparse dot product kernel (ops/sparse.py).
    """

    type_name = "rank_features"

    def parse(self, value: Any) -> ParsedField:
        if not isinstance(value, dict):
            raise MapperParsingError(f"rank_features [{self.name}] expects an object")
        feats = {}
        for k, v in value.items():
            try:
                w = float(v)
            except (TypeError, ValueError):
                raise MapperParsingError(
                    f"rank_features [{self.name}] has non-numeric weight for [{k}]")
            if w < 0:
                raise MapperParsingError(
                    f"rank_features [{self.name}] weights must be >= 0, got {w} for [{k}]")
            feats[str(k)] = w
        return ParsedField(self.name, "features", features=feats)


class RankFeatureFieldMapper(FieldMapper):
    """Single named feature (reference: RankFeatureFieldMapper.java)."""

    type_name = "rank_feature"

    def __init__(self, name: str, params: Dict[str, Any], analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.positive_score_impact = bool(params.get("positive_score_impact", True))

    def parse(self, value: Any) -> ParsedField:
        w = float(value)
        if w < 0:
            raise MapperParsingError(f"rank_feature [{self.name}] must be >= 0")
        return ParsedField(self.name, "features", features={self.name: w})


class GeoPointFieldMapper(FieldMapper):
    type_name = "geo_point"
    has_doc_values = True

    def parse(self, value: Any) -> ParsedField:
        try:
            if isinstance(value, dict):
                lat, lon = float(value["lat"]), float(value["lon"])
            elif isinstance(value, str):
                parts = value.split(",")
                if len(parts) != 2:
                    raise ValueError("expected 'lat,lon'")
                lat, lon = float(parts[0]), float(parts[1])
            elif isinstance(value, list) and len(value) == 2:
                lon, lat = float(value[0]), float(value[1])  # GeoJSON order
            else:
                raise ValueError(f"unsupported geo_point format {type(value).__name__}")
        except (KeyError, ValueError, TypeError) as e:
            raise MapperParsingError(f"cannot parse geo_point [{value}] for [{self.name}]: {e}")
        if not (-90 <= lat <= 90) or not (-180 <= lon <= 180):
            raise MapperParsingError(f"geo_point [{value}] out of range for [{self.name}]")
        return ParsedField(self.name, "geo", geo=(lat, lon))


class GeoShapeFieldMapper(FieldMapper):
    """GeoJSON geometries (index/mapper/GeoShapeFieldMapper analog).

    The reference triangulates into a BKD tree; here the shape stays in
    _source (validated at index time) and geo_shape queries evaluate
    relations host-side over candidate docs (search/geoshape.py). A
    centroid lands in the geo column so existence and bbox prefilters
    stay columnar."""

    type_name = "geo_shape"

    def parse(self, value: Any) -> ParsedField:
        from elasticsearch_tpu.search.geoshape import parse_shape
        try:
            shape = parse_shape(value)      # validates or raises
            min_lon, min_lat, max_lon, max_lat = shape.bbox()
        except MapperParsingError:
            raise
        except (TypeError, ValueError, KeyError, IndexError,
                IllegalArgumentError) as e:
            # IllegalArgumentError covers empty geometries from bbox()
            raise MapperParsingError(
                f"failed to parse geo_shape [{self.name}]: {e}")
        return ParsedField(self.name, "geo",
                           geo=((min_lat + max_lat) / 2.0,
                                (min_lon + max_lon) / 2.0))


class CompletionFieldMapper(FieldMapper):
    """Auto-complete inputs (reference: index/mapper/CompletionFieldMapper).

    The reference builds an FST; here inputs live in the keyword term
    dictionary and suggest does a prefix scan over it (search/suggest.py).
    Option scoring uses document frequency (per-doc weights are accepted
    in the input shape but not yet ranked on)."""

    type_name = "completion"
    has_doc_values = True

    def parse(self, value: Any) -> ParsedField:
        inputs = value.get("input", []) if isinstance(value, dict) \
            else value
        if not isinstance(inputs, list):
            inputs = [inputs]
        return ParsedField(self.name, "terms",
                           exact_terms=[str(v) for v in inputs])


class IpFieldMapper(FieldMapper):
    """IPv4/IPv6 field (index/mapper/IpFieldMapper.java analog).

    Values index as canonical address strings in the keyword term dict;
    CIDR term queries and IP ranges are handled ip-aware at query time
    (search/execute.py) by testing the segment's term dictionary, which
    stays small relative to doc count."""

    type_name = "ip"
    has_doc_values = True

    def parse(self, value: Any) -> ParsedField:
        import ipaddress
        values = value if isinstance(value, list) else [value]
        out = []
        for v in values:
            try:
                out.append(str(ipaddress.ip_address(str(v))))
            except ValueError:
                raise MapperParsingError(
                    f"failed to parse ip [{v}] for field [{self.name}]")
        return ParsedField(self.name, "terms", exact_terms=out)


class BinaryFieldMapper(FieldMapper):
    """Base64 blob stored in _source only, not searchable
    (index/mapper/BinaryFieldMapper.java analog)."""

    type_name = "binary"
    searchable = False

    def parse(self, value: Any) -> ParsedField:
        import base64
        import binascii
        values = value if isinstance(value, list) else [value]
        for v in values:
            try:
                base64.b64decode(str(v), validate=True)
            except (binascii.Error, ValueError):
                raise MapperParsingError(
                    f"failed to parse base64 for binary field [{self.name}]")
        return ParsedField(self.name, "terms", exact_terms=[])


class TokenCountFieldMapper(FieldMapper):
    """Stores the analyzed token count of its input as a numeric column
    (modules/mapper-extras TokenCountFieldMapper analog)."""

    type_name = "token_count"
    has_doc_values = True

    def __init__(self, name: str, params: Dict[str, Any],
                 analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.analyzer = analysis.get(params.get("analyzer", "standard"))

    def parse(self, value: Any) -> ParsedField:
        values = value if isinstance(value, list) else [value]
        return ParsedField(self.name, "numeric", numeric=[
            float(len(self.analyzer.analyze(str(v)))) for v in values])


class SearchAsYouTypeFieldMapper(TextFieldMapper):
    """Text field with shingle + prefix companions for type-ahead
    (modules/mapper-extras SearchAsYouTypeFieldMapper analog): indexing
    feeds ``._2gram`` / ``._3gram`` shingle subfields and an
    ``._index_prefix`` edge-ngram subfield; multi_match type bool_prefix
    targets the set."""

    type_name = "search_as_you_type"

    def __init__(self, name: str, params: Dict[str, Any],
                 analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.max_shingle_size = int(params.get("max_shingle_size", 3))


class AliasFieldMapper(FieldMapper):
    """Alternate name for an existing field
    (index/mapper/FieldAliasMapper.java analog). Queries resolve the
    alias to its path before execution."""

    type_name = "alias"
    searchable = False

    def __init__(self, name: str, params: Dict[str, Any],
                 analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.path = params.get("path")
        if not self.path:
            raise MapperParsingError(
                f"alias field [{name}] requires [path]")

    def parse(self, value: Any) -> ParsedField:
        raise MapperParsingError(
            f"field alias [{self.name}] cannot hold a value")


class ConstantKeywordFieldMapper(FieldMapper):
    """One value shared by every document of the index
    (x-pack ConstantKeywordFieldMapper analog): term queries for the
    value match ALL docs, handled at query time."""

    type_name = "constant_keyword"

    def __init__(self, name: str, params: Dict[str, Any],
                 analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.value = params.get("value")

    def parse(self, value: Any) -> ParsedField:
        if self.value is None:
            self.value = str(value)      # first seen value pins the constant
            self.params["value"] = self.value
        elif str(value) != self.value:
            raise MapperParsingError(
                f"constant_keyword [{self.name}] only accepts "
                f"[{self.value}], got [{value}]")
        return ParsedField(self.name, "terms", exact_terms=[self.value])


# separator between path and leaf value in flattened field terms —
# chosen outside the printable range so user values cannot collide
FLATTENED_SEP = "\x1f"


class FlattenedFieldMapper(FieldMapper):
    """Whole-object-as-keywords field (x-pack FlattenedFieldMapper
    analog): every leaf value indexes under the root name, and keyed
    lookups (``field.key``) resolve via path-prefixed terms without new
    per-key mappings."""

    type_name = "flattened"

    def parse(self, value: Any) -> ParsedField:
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"flattened field [{self.name}] expects an object")
        terms: List[str] = []

        def walk(prefix: str, obj: Any) -> None:
            if isinstance(obj, dict):
                for k, v in obj.items():
                    walk(f"{prefix}.{k}" if prefix else str(k), v)
            elif isinstance(obj, list):
                for v in obj:
                    walk(prefix, v)
            elif obj is not None:
                leaf = str(obj).lower() if isinstance(obj, bool) else str(obj)
                terms.append(leaf)
                terms.append(f"{prefix}{FLATTENED_SEP}{leaf}")

        walk("", value)
        return ParsedField(self.name, "terms", exact_terms=terms)


class WildcardFieldMapper(KeywordFieldMapper):
    """Keyword variant optimized for wildcard/regexp matching in the
    reference (x-pack WildcardFieldMapper's ngram acceleration); here the
    term dictionary scan already serves those queries, so the type is
    behaviorally a keyword without length limits."""

    type_name = "wildcard"

    def __init__(self, name: str, params: Dict[str, Any],
                 analysis: AnalysisRegistry):
        super().__init__(name, params, analysis)
        self.ignore_above = None


def parse_date_nanos_millis(value: Any) -> float:
    """Date with nanosecond precision -> fractional epoch millis
    (DateFieldMapper.Resolution.NANOSECONDS analog; %f caps at 6 digits
    so the 9-digit fraction parses separately)."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    s = str(value)
    import re as _re
    m = _re.match(r"^(.*?)\.(\d{7,9})(Z|[+-]\d{2}:?\d{2})?$", s)
    if m:
        base, frac, tz = m.groups()
        millis = parse_date_millis(base + (tz or "Z"))
        return millis + float(f"0.{frac}") * 1000.0
    return parse_date_millis(value)


class DateNanosFieldMapper(DateFieldMapper):
    type_name = "date_nanos"

    def parse(self, value: Any) -> ParsedField:
        values = value if isinstance(value, list) else [value]
        return ParsedField(self.name, "numeric", numeric=[
            parse_date_nanos_millis(v) for v in values])


class Murmur3FieldMapper(FieldMapper):
    """Stores the murmur3 hash of values as a numeric column for cheap
    cardinality aggregation (plugins/mapper-murmur3 analog)."""

    type_name = "murmur3"
    has_doc_values = True

    def parse(self, value: Any) -> ParsedField:
        from elasticsearch_tpu.utils.murmur3 import murmur3_32
        values = value if isinstance(value, list) else [value]
        return ParsedField(self.name, "numeric", numeric=[
            float(murmur3_32(str(v).encode("utf-8"))) for v in values])


# root-level mapping keys that are configuration, never field names
# (index/mapper/DocumentMapperParser root handlers analog)
_ROOT_MAPPING_KEYS = frozenset(
    ("dynamic", "dynamic_templates", "date_detection",
     "numeric_detection", "runtime"))

_MAPPER_TYPES = {
    "text": TextFieldMapper,
    "keyword": KeywordFieldMapper,
    "completion": CompletionFieldMapper,
    "boolean": BooleanFieldMapper,
    "date": DateFieldMapper,
    "date_nanos": DateNanosFieldMapper,
    "dense_vector": DenseVectorFieldMapper,
    "join": JoinFieldMapper,
    "percolator": PercolatorFieldMapper,
    "rank_features": RankFeaturesFieldMapper,
    "rank_feature": RankFeatureFieldMapper,
    "geo_point": GeoPointFieldMapper,
    "geo_shape": GeoShapeFieldMapper,
    "ip": IpFieldMapper,
    "binary": BinaryFieldMapper,
    "token_count": TokenCountFieldMapper,
    "search_as_you_type": SearchAsYouTypeFieldMapper,
    "alias": AliasFieldMapper,
    "constant_keyword": ConstantKeywordFieldMapper,
    "flattened": FlattenedFieldMapper,
    "wildcard": WildcardFieldMapper,
    "murmur3": Murmur3FieldMapper,
}
for _num in ("long", "integer", "short", "byte", "double", "float", "half_float", "scaled_float"):
    _MAPPER_TYPES[_num] = _num  # sentinel; handled in build_mapper
for _rng in RANGE_TYPES:
    _MAPPER_TYPES[_rng] = _rng  # sentinel; handled in build_mapper

NUMERIC_TYPES = frozenset(
    ("long", "integer", "short", "byte", "double", "float", "half_float",
     "scaled_float", "date", "boolean"))


def build_mapper(name: str, spec: Dict[str, Any], analysis: AnalysisRegistry) -> FieldMapper:
    spec = dict(spec)
    type_name = spec.pop("type", "object")
    factory = _MAPPER_TYPES.get(type_name)
    if factory is None:
        raise MapperParsingError(f"no handler for type [{type_name}] on field [{name}]")
    if isinstance(factory, str):
        if factory in RANGE_TYPES:
            return RangeFieldMapper(name, spec, analysis,
                                    type_name=factory)
        return NumberFieldMapper(name, spec, analysis, type_name=factory)
    return factory(name, spec, analysis)


class MapperService:
    """Per-index schema: field name → mapper; parses documents; merges mapping updates.

    Reference analog: index/mapper/MapperService.java:75 (+ DocumentParser.java:44).
    Supports dynamic mapping: unseen fields get a type inferred from the JSON value
    (string → text with .keyword subfield, number → long/double, bool, date-ish → date).
    """

    def __init__(self, mapping: Optional[Dict[str, Any]] = None,
                 analysis: Optional[AnalysisRegistry] = None,
                 dynamic: Any = True):
        self.analysis = analysis or AnalysisRegistry()
        # tri-state like the reference: True (map new fields), False (ignore
        # them, still store in _source), "strict" (reject the document)
        self.dynamic = _parse_dynamic(dynamic)
        self._mappers: Dict[str, FieldMapper] = {}
        # container paths: full path -> "object" | "nested". Nested paths
        # additionally gate nested-query semantics (parity work pending).
        self._object_types: Dict[str, str] = {}
        if mapping:
            self.merge(mapping)

    def merge(self, mapping: Dict[str, Any]) -> None:
        props = mapping.get("properties")
        if props is None:
            # bare-props convenience form: everything except known root
            # mapping keys (dynamic, _source, _meta, ...) is a field spec.
            # Malformed (non-dict) specs fail loudly here exactly as they
            # would under an explicit "properties" key.
            props = {}
            for k, v in mapping.items():
                if k.startswith("_") or k in _ROOT_MAPPING_KEYS:
                    continue
                if not isinstance(v, dict):
                    raise MapperParsingError(
                        f"expected map for property [{k}] but got "
                        f"[{type(v).__name__}]")
                props[k] = v
        self._merge_props("", props)
        if "dynamic" in mapping:
            self.dynamic = _parse_dynamic(mapping["dynamic"])
        # internal companion columns (never serialized): join parent ids,
        # and range bounds as two numeric doc-value columns
        for name, m in list(self._mappers.items()):
            if m.type_name == "join":
                companion = f"{name}#parent"
                if companion not in self._mappers:
                    self._mappers[companion] = KeywordFieldMapper(
                        companion, {}, self.analysis)
            elif m.type_name in RANGE_TYPES:
                for suffix in ("#lo", "#hi"):
                    companion = f"{name}{suffix}"
                    if companion not in self._mappers:
                        self._mappers[companion] = NumberFieldMapper(
                            companion, {}, self.analysis,
                            type_name="double")
            elif m.type_name == "search_as_you_type":
                self._make_sayt_companions(name, m)

    def _make_sayt_companions(self, name: str,
                              m: "SearchAsYouTypeFieldMapper") -> None:
        """._2gram/._3gram shingles + ._index_prefix edge-ngrams."""
        from elasticsearch_tpu.analysis.analyzers import (
            Analyzer, lowercase_filter, make_edge_ngram_filter,
            make_shingle_filter, standard_tokenizer,
        )
        for n in range(2, m.max_shingle_size + 1):
            sub = f"{name}._{n}gram"
            if sub in self._mappers:
                continue
            sh = Analyzer(f"__sayt_{n}gram", standard_tokenizer,
                          [lowercase_filter,
                           make_shingle_filter(n, n,
                                               output_unigrams=False)])
            mapper = TextFieldMapper(sub, {}, self.analysis)
            mapper.analyzer = sh
            mapper.search_analyzer = sh
            self._mappers[sub] = mapper
        sub = f"{name}._index_prefix"
        if sub not in self._mappers:
            pre = Analyzer(
                "__sayt_prefix", standard_tokenizer,
                [lowercase_filter,
                 make_shingle_filter(1, m.max_shingle_size),
                 make_edge_ngram_filter(1, 20)])
            mapper = TextFieldMapper(sub, {}, self.analysis)
            mapper.analyzer = pre
            # queries send the literal prefix; only indexing expands ngrams
            from elasticsearch_tpu.analysis import STANDARD
            mapper.search_analyzer = STANDARD
            self._mappers[sub] = mapper

    def _merge_props(self, prefix: str, props: Dict[str, Any]) -> None:
        for name, spec in props.items():
            full = f"{prefix}{name}"
            if not isinstance(spec, dict):
                raise MapperParsingError(
                    f"expected map for property [{full}] but got "
                    f"[{type(spec).__name__}]")
            # inner objects: implicit (properties, no type) or explicit
            # object/nested (ObjectMapper/NestedObjectMapper analog) —
            # recurse, record the container kind, no leaf mapper
            if spec.get("type") in ("object", "nested") or \
                    ("properties" in spec and "type" not in spec):
                existing = self._mappers.get(full)
                if existing is not None:
                    raise MapperParsingError(
                        f"mapper [{full}] cannot change type from "
                        f"[{existing.type_name}] to [object]")
                prior_kind = self._object_types.get(full)
                if "type" in spec:
                    # explicit object<->nested change is rejected (ES:
                    # "can't merge a non object mapping ... nested")
                    if prior_kind is not None and prior_kind != spec["type"]:
                        raise MapperParsingError(
                            f"mapper [{full}] cannot change type from "
                            f"[{prior_kind}] to [{spec['type']}]")
                    self._object_types[full] = spec["type"]
                elif prior_kind is None:
                    # implicit properties-only spec keeps an existing kind
                    self._object_types[full] = "object"
                self._merge_props(f"{full}.", spec.get("properties", {}))
                continue
            new = build_mapper(full, spec, self.analysis)
            existing = self._mappers.get(full)
            if existing is not None and existing.type_name != new.type_name:
                raise MapperParsingError(
                    f"mapper [{full}] cannot change type from "
                    f"[{existing.type_name}] to [{new.type_name}]")
            if full in self._object_types:
                raise MapperParsingError(
                    f"mapper [{full}] cannot change type from "
                    f"[{self._object_types[full]}] to [{new.type_name}]")
            self._mappers[full] = new
            # text fields get an automatic .keyword subfield unless disabled,
            # mirroring ES dynamic-template default behavior
            for sub, subspec in spec.get("fields", {}).items():
                self._mappers[f"{full}.{sub}"] = build_mapper(f"{full}.{sub}", subspec, self.analysis)

    def resolve_field(self, field_name: str) -> str:
        """Follow a field alias to its target path (FieldAliasMapper
        analog); non-aliases resolve to themselves."""
        m = self._mappers.get(field_name)
        if m is not None and m.type_name == "alias":
            return m.path
        return field_name

    def mapper(self, field_name: str) -> Optional[FieldMapper]:
        m = self._mappers.get(field_name)
        if m is not None and m.type_name == "alias":
            return self._mappers.get(m.path)
        return m

    def field_type(self, field_name: str) -> Optional[str]:
        m = self.mapper(field_name)
        return m.type_name if m else None

    def field_names(self) -> List[str]:
        return sorted(self._mappers.keys())

    def to_mapping(self) -> Dict[str, Any]:
        props: Dict[str, Any] = {}
        for name, m in sorted(self._mappers.items()):
            if "#" in name:
                continue   # internal companion columns (join#parent)
            node = props
            parts = name.split(".")
            # .keyword-style subfields render under 'fields'
            parent = ".".join(parts[:-1])
            if parent in self._mappers and self._mappers[parent].type_name == "text":
                parent_spec = _descend(props, parent.split("."))
                parent_spec.setdefault("fields", {})[parts[-1]] = m.to_mapping()
                continue
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = m.to_mapping()
        # container types survive the round-trip: nested always explicitly,
        # and empty object containers too (else serialize->reparse would
        # silently drop them and a later put_mapping could repurpose the
        # path as a leaf field, diverging from live mappers)
        for path, kind in self._object_types.items():
            node = props
            parts = path.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            leaf = node.setdefault(parts[-1], {})
            if kind == "nested" or not leaf:
                leaf["type"] = kind
        return {"properties": props}

    def _infer(self, name: str, value: Any) -> Optional[FieldMapper]:
        if isinstance(value, bool):
            spec: Dict[str, Any] = {"type": "boolean"}
        elif isinstance(value, int):
            spec = {"type": "long"}
        elif isinstance(value, float):
            spec = {"type": "double"}
        elif isinstance(value, str):
            spec = {"type": "text"}
            if _looks_like_date(value):
                try:
                    parse_date_millis(value)
                    spec = {"type": "date"}
                except MapperParsingError:
                    pass
        elif isinstance(value, dict):
            return None  # object: recurse in parse
        elif isinstance(value, list):
            return self._infer(name, value[0]) if value else None
        else:
            return None
        if name in self._object_types:
            # a scalar arriving at an object/nested container path is a
            # document error, not a mapping update (DocumentParser rejects
            # "tried to parse field [x] as object" the same way)
            raise MapperParsingError(
                f"object mapping for [{name}] tried to parse value as "
                f"{self._object_types[name]}, got a concrete value")
        self._mappers[name] = build_mapper(name, spec, self.analysis)
        if spec["type"] == "text":
            self._mappers[f"{name}.keyword"] = build_mapper(
                f"{name}.keyword", {"type": "keyword", "ignore_above": 256}, self.analysis)
        return self._mappers[name]

    def parse_document(self, doc_id: str, source: Dict[str, Any],
                       routing: Optional[str] = None) -> ParsedDocument:
        doc = ParsedDocument(doc_id=doc_id, source=source, routing=routing)
        self._parse_obj("", source, doc)
        # a child join doc MUST be routed (by its parent id) or it can land
        # on a different shard than the parent and every join query would
        # silently miss it (the reference's RoutingMissingException)
        for name, mapper in self._mappers.items():
            if getattr(mapper, "type_name", "") != "join":
                continue
            parsed = doc.fields.get(name)
            if parsed is not None and parsed.exact_terms and \
                    parsed.exact_terms[0] in mapper.children and \
                    routing is None:
                raise MapperParsingError(
                    f"routing is required for join child documents "
                    f"([{name}] relation [{parsed.exact_terms[0]}])")
        return doc

    def _parse_obj(self, prefix: str, obj: Dict[str, Any], doc: ParsedDocument) -> None:
        for key, value in obj.items():
            name = f"{prefix}{key}"
            if value is None:
                continue
            mapper = self._mappers.get(name)
            if mapper is None:
                if isinstance(value, dict):
                    self._parse_obj(f"{name}.", value, doc)
                    continue
                if self.dynamic == "strict":
                    raise MapperParsingError(
                        f"mapping set to strict, dynamic introduction of [{name}] is not allowed")
                if self.dynamic is False:
                    continue  # ignore unmapped field; it stays in _source only
                mapper = self._infer(name, value)
                if mapper is None:
                    if isinstance(value, list) and value and isinstance(value[0], dict):
                        for item in value:
                            self._parse_obj(f"{name}.", item, doc)
                    continue
            parsed = mapper.parse(value)
            if name in doc.fields:
                _merge_parsed(doc.fields[name], parsed)
            else:
                doc.fields[name] = parsed
            # feed the join parent-id companion column
            if mapper.type_name == "join" and isinstance(value, dict) and \
                    value.get("parent") is not None:
                comp = f"{name}#parent"
                companion = self._mappers.get(comp)
                if companion is not None:
                    doc.fields[comp] = companion.parse(str(value["parent"]))
            # feed range bound companions (lists align: lo[i] pairs with
            # hi[i]; unbounded sides store +-inf, comparable like the
            # query side's open bounds)
            if mapper.type_name in RANGE_TYPES:
                los, his = mapper.bounds(value)
                for suffix, bound_list in (("#lo", los), ("#hi", his)):
                    comp = self._mappers.get(f"{name}{suffix}")
                    if comp is not None:
                        doc.fields[f"{name}{suffix}"] = \
                            comp.parse(bound_list)
            # feed text.keyword subfields
            kw = self._mappers.get(f"{name}.keyword")
            if kw is not None and mapper.type_name == "text":
                sub = kw.parse(value)
                subname = f"{name}.keyword"
                if subname in doc.fields:
                    _merge_parsed(doc.fields[subname], sub)
                else:
                    doc.fields[subname] = sub
            # feed search_as_you_type shingle/prefix companions
            if mapper.type_name == "search_as_you_type":
                for suffix in ([f"._{n}gram" for n in range(2, 10)]
                               + ["._index_prefix"]):
                    comp = self._mappers.get(f"{name}{suffix}")
                    if comp is None:
                        continue
                    sub = comp.parse(value)
                    subname = f"{name}{suffix}"
                    if subname in doc.fields:
                        _merge_parsed(doc.fields[subname], sub)
                    else:
                        doc.fields[subname] = sub


def _merge_parsed(into: ParsedField, other: ParsedField) -> None:
    for attr in ("terms", "exact_terms", "numeric"):
        a, b = getattr(into, attr), getattr(other, attr)
        if b:
            setattr(into, attr, (a or []) + b)
    if other.features:
        into.features = {**(into.features or {}), **other.features}
    if other.vector:
        into.vector = other.vector
    if other.geo:
        into.geo = other.geo


def _descend(props: Dict[str, Any], parts: List[str]) -> Dict[str, Any]:
    node = props
    for p in parts[:-1]:
        node = node[p]["properties"]
    return node[parts[-1]]


def _parse_dynamic(value: Any) -> Any:
    if value in ("strict",):
        return "strict"
    if value in (False, "false"):
        return False
    return True


def _looks_like_date(s: str) -> bool:
    if len(s) < 8 or not s[:4].isdigit():
        return False
    return s[4] in "-/" and any(c.isdigit() for c in s[5:7])
