from elasticsearch_tpu.mapping.mappers import (
    FieldMapper,
    MapperService,
    ParsedDocument,
    ParsedField,
    build_mapper,
    parse_date_millis,
)

__all__ = [
    "FieldMapper",
    "MapperService",
    "ParsedDocument",
    "ParsedField",
    "build_mapper",
    "parse_date_millis",
]
