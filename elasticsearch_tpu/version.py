"""Version constants.

Mirrors the role of the reference's buildSrc/version.properties:1 and
``org.elasticsearch.Version`` (server/src/main/java/org/elasticsearch/Version.java):
a single integer wire id used in transport handshakes plus a human string.
"""

__version__ = "0.1.0"

# Wire-format version id, bumped on any incompatible serialization change.
# Reference analog: Version.CURRENT.id used in the TCP header
# (server/.../transport/TcpHeader.java:31-49).
WIRE_VERSION = 1_000_099

# Lowest wire version we can still talk to (rolling-upgrade support).
MIN_COMPATIBLE_WIRE_VERSION = 1_000_099
