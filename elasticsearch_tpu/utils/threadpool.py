"""Named execution pools with bounded admission — the backpressure layer.

The reference sizes real thread pools per workload (threadpool/
ThreadPool.java:69-130: search, write, get, management, ... each with a
queue bound) and rejects work beyond the queue with
EsRejectedExecutionException → HTTP 429. This build's node is an
event-loop, so the analog is ADMISSION control across async boundaries:
a pool grants in-flight slots (acquire at request entry, release at
completion), queues a bounded overflow, and rejects the rest. The write
pool additionally accounts in-flight request BYTES — the reference's
indexing-pressure limit (IndexingPressure.java) that stops a node from
buffering unbounded bulk payloads.

The overload control plane adds three behaviors on top of the static
bounds:

- **Little's-law queue resizing** (QueueResizingEsThreadPoolExecutor
  analog): the pool measures its completion rate over frames of
  ``frame_size`` tasks and moves ``queue_size`` toward
  ``rate * target_latency`` (bounded by [min_queue, max_queue], at most
  QUEUE_ADJUSTMENT per frame) — so past saturation the queue bounds the
  LATENCY of admitted work, not an arbitrary count. Resizing engages
  only when min_queue != max_queue (the reference's gate).
- **Per-tenant weighted-fair admission**: queued work is segregated per
  tenant key (the search path passes the index expression) and drained
  round-robin. When the queue is full, an arriving tenant whose backlog
  is under its fair share displaces the NEWEST queued entry of the
  fattest tenant instead of being rejected — one hot index can saturate
  its own share of the queue but cannot starve the rest of the fleet.
- **Computed Retry-After**: every rejection carries the seconds until a
  queue slot is expected to free (queue depth over the measured
  completion rate), surfaced as the HTTP ``Retry-After`` header so
  clients back off for a meaningful duration instead of a guess.
"""

from __future__ import annotations

import math
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, Optional, Tuple

from elasticsearch_tpu.utils.errors import RejectedExecutionError

# the default tenant for callers that don't segregate admission
DEFAULT_TENANT = "_default"


class Pool:
    """One named admission pool: in-flight slots + a bounded, per-tenant
    fair queue + frame-based completion-rate measurement."""

    # largest queue_size move per measurement frame (the reference's
    # QueueResizingEsThreadPoolExecutor tweak bound)
    QUEUE_ADJUSTMENT = 50
    RETRY_AFTER_MAX_S = 60
    # tenant keys come from client-supplied index expressions: bound the
    # rejection map (overflow pools into "_other") so hostile expression
    # churn can't grow node memory or the stats payload forever
    TENANT_CAP = 128

    def __init__(self, name: str, size: int, queue_size: int,
                 now_fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.size = size
        self.queue_size = queue_size
        self.active = 0
        self._now = now_fn or time.monotonic
        # tenant -> deque[(task, on_reject)], drained round-robin so no
        # tenant's backlog can monopolize the freed slots
        self.queues: "OrderedDict[str, Deque[Tuple]]" = OrderedDict()
        self.queued_total = 0
        self.completed = 0
        self.rejected = 0
        self.rejected_by_tenant: Dict[str, int] = {}
        self.largest_queue = 0
        # Little's-law adaptive resizing: engaged when min != max
        self.target_latency_s: Optional[float] = None
        self.min_queue = queue_size
        self.max_queue = queue_size
        self.frame_size = 100
        self._frame_completed = 0
        # the rate is completions per BUSY second: _busy_anchor is set
        # when an idle pool receives work and advanced at each
        # completion, so idle time — before a frame OR in the middle of
        # one — never reads as a slow pool (a stale rate would tell
        # clients to back off 60s from a pool that drains in
        # milliseconds, and shrink a healthy queue)
        self._busy_anchor: Optional[float] = None
        self._frame_busy_s = 0.0
        self.task_rate = 0.0        # completions/busy-second, last frame
        self.resizes = 0
        self._draining = False
        self.retry_after_issued = 0
        self.last_retry_after_s = 0

    # -- admission --------------------------------------------------------

    def submit(self, task: Callable[[], None],
               tenant: Optional[str] = None,
               on_reject: Optional[Callable[[Exception], None]] = None
               ) -> None:
        """Run task now if a slot is free, queue it within bounds (fairly
        across tenants), reject the overflow. A queued task may later be
        DISPLACED by a starved tenant — its ``on_reject`` is invoked with
        the rejection instead of the task ever running. The task MUST
        arrange for release() exactly once when its work (including async
        continuations) completes."""
        tenant = tenant or DEFAULT_TENANT
        if self.active == 0 and self.queued_total == 0:
            self._busy_anchor = self._now()    # idle -> busy transition
        if self.active < self.size:
            self.active += 1
            task()
            return
        if self.queued_total >= self.queue_size and \
                self._shed_for(tenant) is None:
            raise self._reject_error(tenant)
        self._enqueue(tenant, task, on_reject)

    def _enqueue(self, tenant, task, on_reject) -> None:
        queue = self.queues.get(tenant)
        if queue is None:
            queue = self.queues[tenant] = deque()
        queue.append((task, on_reject))
        self.queued_total += 1
        self.largest_queue = max(self.largest_queue, self.queued_total)

    def _shed_for(self, tenant: str):
        """Full queue: make room for ``tenant`` by shedding the newest
        entry of the fattest OTHER tenant — but only when the arriving
        tenant's backlog is strictly under that tenant's (it is below its
        fair share; shedding preserves total boundedness while restoring
        fairness). Returns None when the arrival itself must be rejected
        (it IS the fattest user of the queue)."""
        fat_tenant = None
        fat_len = -1
        for t, q in self.queues.items():
            if len(q) > fat_len:
                fat_tenant, fat_len = t, len(q)
        mine = len(self.queues.get(tenant, ()))
        if fat_tenant is None or fat_tenant == tenant or fat_len <= mine + 1:
            return None
        queue = self.queues[fat_tenant]
        if queue[-1][1] is None:
            # an entry submitted WITHOUT a rejection channel cannot be
            # displaced — shedding it would silently strand its caller;
            # the arrival takes the rejection instead
            return None
        _task, on_reject = queue.pop()
        self.queued_total -= 1
        if not queue:
            del self.queues[fat_tenant]
        err = self._reject_error(fat_tenant)
        try:
            on_reject(err)
        except Exception:  # noqa: BLE001 — a reject-callback failure
            pass           # must not strand the displacing arrival
        return (fat_tenant, err)

    def _reject_error(self, tenant: str) -> RejectedExecutionError:
        self.rejected += 1
        key = tenant if tenant in self.rejected_by_tenant or \
            len(self.rejected_by_tenant) < self.TENANT_CAP else "_other"
        self.rejected_by_tenant[key] = \
            self.rejected_by_tenant.get(key, 0) + 1
        retry_after = self.retry_after_s(tenant)
        self.retry_after_issued += 1
        self.last_retry_after_s = retry_after
        # ONE carrier for the computed backoff: the error metadata (it
        # rides to_json across transport; the REST layer reads it into
        # the body field and the Retry-After header)
        return RejectedExecutionError(
            f"rejected execution on [{self.name}]: queue capacity "
            f"[{self.queue_size}] reached", retry_after=retry_after,
            tenant=tenant)

    def retry_after_s(self, tenant: Optional[str] = None) -> int:
        """Seconds until a new request is expected to be admitted: the
        backlog ahead of it drained at the measured completion rate.
        With no rate measured yet (cold pool), a 1s floor — honest
        enough for a client's first backoff.

        With multiple tenants queued, a rejected ``tenant``'s estimate
        uses its OWN backlog at its FAIR SHARE of the pool rate (the
        queues drain round-robin, so a displacement-shed hot tenant's
        backlog drains at rate/n_tenants — quoting the whole-pool rate
        told exactly the tenants being shed to come back soonest). With
        one (or no) tenant queued, the fair share IS the pool rate and
        the estimate reduces to the whole-queue drain time."""
        if self.task_rate <= 0.0:
            est = 1.0
        else:
            n_tenants = len(self.queues)
            if tenant is not None and n_tenants > 1:
                depth = len(self.queues.get(tenant, ())) + 1
                est = depth * n_tenants / self.task_rate
            else:
                est = (self.queued_total + 1) / self.task_rate
        return max(1, min(self.RETRY_AFTER_MAX_S, int(math.ceil(est))))

    # -- completion + Little's-law resizing -------------------------------

    def release(self) -> None:
        self.active -= 1
        self.completed += 1
        now = self._now()
        if self._busy_anchor is not None:
            self._frame_busy_s += max(now - self._busy_anchor, 0.0)
        # still busy? keep accumulating from here; else stop the clock
        # until the next submit restarts it
        self._busy_anchor = now \
            if (self.active > 0 or self.queued_total) else None
        self._frame_completed += 1
        if self._frame_completed >= self.frame_size:
            self.task_rate = \
                self._frame_completed / max(self._frame_busy_s, 1e-9)
            self._frame_completed = 0
            self._frame_busy_s = 0.0
            self._resize_queue()
        # iterative drain with a reentrancy guard: a queued task that
        # completes (and releases) synchronously must not recurse one
        # frame per backlog entry — a 1000-deep queue of fast-failing
        # tasks would blow the stack mid-drain otherwise
        if self._draining:
            return
        self._draining = True
        try:
            while self.queued_total and self.active < self.size:
                task = self._pop_next()
                if task is None:
                    break
                self.active += 1
                task()
        finally:
            self._draining = False

    def _resize_queue(self) -> None:
        """Little's law (L = λ·W): the queue that holds admitted work to
        the target latency is rate * target. Move toward it by at most
        QUEUE_ADJUSTMENT per frame, inside [min_queue, max_queue]."""
        if not self.target_latency_s or self.min_queue == self.max_queue:
            return
        ideal = self.task_rate * self.target_latency_s
        step = int(round(ideal - self.queue_size))
        step = max(-self.QUEUE_ADJUSTMENT,
                   min(self.QUEUE_ADJUSTMENT, step))
        new = min(self.max_queue, max(self.min_queue,
                                      self.queue_size + step))
        if new != self.queue_size:
            self.queue_size = new
            self.resizes += 1

    def _pop_next(self) -> Optional[Callable[[], None]]:
        """Round-robin across tenant queues: pop the head of the first
        tenant, then rotate it behind the others."""
        for tenant in list(self.queues):
            queue = self.queues[tenant]
            if not queue:
                del self.queues[tenant]
                continue
            task, _on_reject = queue.popleft()
            self.queued_total -= 1
            if queue:
                self.queues.move_to_end(tenant)
            else:
                del self.queues[tenant]
            return task
        return None

    # -- surfaces ---------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {"threads": self.size, "active": self.active,
                "queue": self.queued_total, "queue_size": self.queue_size,
                "completed": self.completed, "rejected": self.rejected,
                "largest": self.largest_queue}

    def admission_stats(self) -> Dict[str, Any]:
        """The ``_nodes/stats`` ``search_admission`` queue block: live
        bounds, the adaptive controller's state, per-tenant rejections
        and the Retry-After values issued."""
        return {
            "queue": {
                "current": self.queued_total,
                "limit": self.queue_size,
                "min": self.min_queue,
                "max": self.max_queue,
                "resizes": self.resizes,
                "target_latency_ms": (
                    round(self.target_latency_s * 1000.0, 1)
                    if self.target_latency_s else None),
                "task_rate_per_s": round(self.task_rate, 3),
            },
            "active": self.active,
            "slots": self.size,
            "rejected_total": self.rejected,
            "rejections_by_tenant": dict(self.rejected_by_tenant),
            "retry_after": {"issued": self.retry_after_issued,
                            "last_s": self.last_retry_after_s},
        }


# reference pool sizing shape (ThreadPool.java:166-177), scaled to the
# event-loop model: "size" = concurrent in-flight operations
DEFAULT_POOLS = {
    "search": (16, 1000),
    "write": (8, 200),
    "get": (16, 1000),
    "management": (4, 100),
    "generic": (32, 500),
}

# indexing-pressure byte limit for in-flight write payloads — the
# documented default the ``indexing_pressure.memory.limit`` dynamic
# cluster setting overrides (IndexingPressure MAX_INDEXING_BYTES analog:
# 10% of heap there; a fixed 64mb default here)
WRITE_BYTES_LIMIT = 64 * 1024 * 1024


class IndexingPressure:
    """Three-stage in-flight write-byte accounting (IndexingPressure.java
    analog): every write payload is charged at the stage it occupies —
    **coordinating** (the node that parsed the bulk request),
    **primary** (the node executing the shard-level operations), and
    **replica** (a node applying replicated ops) — and released when
    that stage's work completes.

    Coordinating and primary admission share ``limit``: together they
    bound what THIS node has accepted responsibility for. The replica
    stage is checked separately against ``limit * REPLICA_HEADROOM``
    (1.5x) — replica work is downstream of a DIFFERENT node's primary
    having already accepted the bytes, so rejecting it at the shared
    limit would let a node's own coordinating admission starve the
    replication fan-out landing on it (the cross-node deadlock the
    reference's headroom rule exists to break).

    Rejections are typed ``es_rejected_execution_exception`` 429s
    carrying a computed Retry-After: released bytes are frame-measured
    into a drain rate (the Pool completion-rate pattern, on bytes), and
    the rejection's backoff is the time the current in-flight backlog
    needs to drain at that rate (1s floor, 60s cap — the coordinator
    pool's clamp). Rejection counts are per stage; the ``unknown``
    bucket exists so its pinned-at-zero value PROVES every rejection
    was stage-typed."""

    STAGES = ("coordinating", "primary", "replica")
    REPLICA_HEADROOM = 1.5
    # releases per drain-rate measurement frame (Pool.frame_size analog)
    FRAME_RELEASES = 16
    RETRY_AFTER_MAX_S = 60
    ALPHA = 0.3

    def __init__(self, limit: int = WRITE_BYTES_LIMIT,
                 now_fn: Optional[Callable[[], float]] = None):
        self.now = now_fn or time.monotonic
        self.limit = int(limit)
        self.current: Dict[str, int] = {s: 0 for s in self.STAGES}
        self.total: Dict[str, int] = {s: 0 for s in self.STAGES}
        self.rejections: Dict[str, int] = {s: 0 for s in self.STAGES}
        self.rejections["unknown"] = 0
        # byte drain-rate measurement (released bytes per second, EWMA
        # over frames of FRAME_RELEASES releases)
        self._frame_bytes = 0
        self._frame_releases = 0
        self._frame_t0: Optional[float] = None
        self.release_rate_bps = 0.0
        self.retry_after_issued = 0
        self.last_retry_after_s = 0
        # version-memoized dynamic-settings apply (the search.plane.*
        # configure_from_state pattern); _settings_applied tracks whether
        # the CURRENT limit came from cluster settings, so removal
        # restores the default exactly once without clobbering a limit
        # set directly (tests/operators poke write_bytes_limit)
        self._settings_version: Optional[int] = None
        self._settings_applied = False

    # -- admission --------------------------------------------------------

    def stage_limit(self, stage: str) -> int:
        if stage == "replica":
            return int(self.limit * self.REPLICA_HEADROOM)
        return self.limit

    def _stage_occupancy(self, stage: str) -> int:
        """The byte total ``stage`` admission is judged against:
        coordinating+primary share the limit; replica stands alone
        under its headroom."""
        if stage == "replica":
            return self.current["replica"]
        return self.current["coordinating"] + self.current["primary"]

    def acquire(self, stage: str, n: int) -> None:
        if stage not in self.STAGES:
            raise ValueError(f"unknown indexing-pressure stage [{stage}]")
        n = max(int(n), 0)
        would = self._stage_occupancy(stage) + n
        cap = self.stage_limit(stage)
        if would > cap:
            self.rejections[stage] += 1
            retry_after = self.retry_after_s()
            self.retry_after_issued += 1
            self.last_retry_after_s = retry_after
            from elasticsearch_tpu.utils.errors import (
                EsRejectedExecutionError,
            )
            # stage= and retry_after= ride IN the message: replica
            # rejections cross the wire stringified (PR 9 invariant)
            # and the primary re-parses them with write_pressure_info
            raise EsRejectedExecutionError(
                f"rejected execution of {stage} operation: in-flight "
                f"indexing bytes [{would}] would exceed [{cap}] "
                f"stage={stage} retry_after={retry_after}s",
                retry_after=retry_after, stage=stage)
        self.current[stage] += n
        self.total[stage] += n

    def release(self, stage: str, n: int) -> None:
        n = max(int(n), 0)
        self.current[stage] = max(0, self.current[stage] - n)
        now = self.now()
        if self._frame_t0 is None:
            self._frame_t0 = now
        self._frame_bytes += n
        self._frame_releases += 1
        if self._frame_releases >= self.FRAME_RELEASES:
            elapsed = max(now - self._frame_t0, 1e-3)
            rate = self._frame_bytes / elapsed
            self.release_rate_bps = rate if self.release_rate_bps == 0.0 \
                else self.ALPHA * rate + \
                (1 - self.ALPHA) * self.release_rate_bps
            self._frame_bytes = 0
            self._frame_releases = 0
            self._frame_t0 = now

    def retry_after_s(self) -> int:
        """Honest write backoff: seconds until the CURRENT in-flight
        backlog drains at the measured release rate (1s floor, 60s
        cap). Cold node (no frame yet): 1s."""
        backlog = sum(self.current.values())
        rate = self.release_rate_bps
        if rate <= 0.0:
            return 1
        return max(1, min(self.RETRY_AFTER_MAX_S,
                          int(math.ceil((backlog + 1) / rate))))

    # -- dynamic settings -------------------------------------------------

    def configure_from_state(self, state) -> None:
        """Apply ``indexing_pressure.memory.limit`` from committed
        cluster state — version-memoized, so per-request refresh costs
        one integer compare; settings-removal falls back to the
        documented WRITE_BYTES_LIMIT default through the setting's own
        default machinery."""
        version = getattr(state, "version", None)
        if version is None or version == self._settings_version:
            return
        self._settings_version = version
        from elasticsearch_tpu.utils.settings import (
            INDEXING_PRESSURE_MEMORY_LIMIT, setting_from_state,
        )
        raw = state.metadata.persistent_settings.get(
            INDEXING_PRESSURE_MEMORY_LIMIT.key)
        if raw is None:
            if self._settings_applied:
                # setting removed: restore the documented default
                self.limit = int(
                    INDEXING_PRESSURE_MEMORY_LIMIT.default(None))
                self._settings_applied = False
            return
        self.limit = int(setting_from_state(
            state, INDEXING_PRESSURE_MEMORY_LIMIT))
        self._settings_applied = True

    # -- observability ----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The first-class ``_nodes/stats`` indexing_pressure section:
        per-stage current/total/limit/rejections plus the Retry-After
        drain-rate machinery's live values."""
        return {
            "limit_bytes": self.limit,
            "current_bytes": sum(self.current.values()),
            "stages": {
                s: {"current_bytes": self.current[s],
                    "total_bytes": self.total[s],
                    "limit_bytes": self.stage_limit(s),
                    "rejections": self.rejections[s]}
                for s in self.STAGES},
            "rejections": dict(self.rejections),
            "rejections_total": sum(
                self.rejections[s] for s in self.STAGES),
            "retry_after": {
                "issued": self.retry_after_issued,
                "last_s": self.last_retry_after_s,
                "release_rate_bytes_per_s": round(
                    self.release_rate_bps, 1)},
        }


def merge_indexing_pressure_sections(sections) -> Dict[str, Any]:
    """Fleet merge for ``_cluster/stats``: counters and byte gauges
    summed across nodes, per-stage rejection buckets summed per bucket,
    the last Retry-After kept as a maximum (the most-loaded node's
    honest backoff). Tolerates missing/empty sections from nodes that
    failed the fan-out."""
    out: Dict[str, Any] = {
        "limit_bytes": 0, "current_bytes": 0,
        "stages": {s: {"current_bytes": 0, "total_bytes": 0,
                       "rejections": 0}
                   for s in IndexingPressure.STAGES},
        "rejections": {s: 0 for s in
                       (*IndexingPressure.STAGES, "unknown")},
        "rejections_total": 0,
        "retry_after": {"issued": 0, "max_last_s": 0},
    }
    for sec in sections:
        if not sec:
            continue
        out["limit_bytes"] += sec.get("limit_bytes", 0)
        out["current_bytes"] += sec.get("current_bytes", 0)
        for s, stage in (sec.get("stages") or {}).items():
            agg = out["stages"].setdefault(
                s, {"current_bytes": 0, "total_bytes": 0,
                    "rejections": 0})
            for k in agg:
                agg[k] += stage.get(k, 0)
        for reason, n in (sec.get("rejections") or {}).items():
            out["rejections"][reason] = \
                out["rejections"].get(reason, 0) + n
        out["rejections_total"] += sec.get("rejections_total", 0)
        ra = sec.get("retry_after") or {}
        out["retry_after"]["issued"] += ra.get("issued", 0)
        out["retry_after"]["max_last_s"] = max(
            out["retry_after"]["max_last_s"], ra.get("last_s", 0))
    return out


class ThreadPoolService:
    """Per-node admission pools + three-stage write-bytes accounting."""

    def __init__(self, pools: Optional[Dict[str, tuple]] = None,
                 now_fn: Optional[Callable[[], float]] = None):
        self.pools: Dict[str, Pool] = {
            name: Pool(name, size, queue, now_fn=now_fn)
            for name, (size, queue) in (pools or DEFAULT_POOLS).items()}
        self.indexing_pressure = IndexingPressure(now_fn=now_fn)

    def pool(self, name: str) -> Pool:
        return self.pools[name]

    # -- slot admission ---------------------------------------------------

    def submit(self, name: str, task: Callable[[], None],
               tenant: Optional[str] = None,
               on_reject: Optional[Callable[[Exception], None]] = None
               ) -> None:
        """Run task now if a slot is free, queue it within bounds, reject
        beyond them. The task MUST arrange for release(name) exactly once
        when its work (including async continuations) completes.
        ``tenant`` segregates queued work for weighted-fair shedding;
        ``on_reject`` is how a QUEUED task learns it was displaced by a
        starved tenant (a synchronous rejection still raises)."""
        self.pools[name].submit(task, tenant=tenant, on_reject=on_reject)

    def release(self, name: str) -> None:
        self.pools[name].release()

    def configure_search_admission(
            self, target_latency_s: float, min_queue: int, max_queue: int,
            frame_size: int) -> None:
        """Apply the dynamic search.admission.* settings to the search
        pool (cheap assignments — callers refresh per request). The
        current queue_size is clamped into the new bounds so an operator
        narrowing the range takes effect immediately."""
        pool = self.pools.get("search")
        if pool is None:
            return
        if min_queue > max_queue:
            min_queue = max_queue
        pool.min_queue = min_queue
        pool.max_queue = max_queue
        pool.frame_size = max(1, int(frame_size))
        pool.target_latency_s = \
            float(target_latency_s) if min_queue != max_queue else None
        pool.queue_size = min(max_queue, max(min_queue, pool.queue_size))

    # -- write-bytes accounting (indexing pressure) -----------------------
    # legacy single-gate surface: delegates to the coordinating stage of
    # the three-stage IndexingPressure (autoscaling reads the aggregate
    # attributes; older tests drive acquire/release directly)

    @property
    def write_bytes_in_flight(self) -> int:
        return sum(self.indexing_pressure.current.values())

    @property
    def write_bytes_limit(self) -> int:
        return self.indexing_pressure.limit

    @write_bytes_limit.setter
    def write_bytes_limit(self, v: int) -> None:
        self.indexing_pressure.limit = int(v)

    @property
    def write_bytes_rejections(self) -> int:
        return sum(self.indexing_pressure.rejections[s]
                   for s in IndexingPressure.STAGES)

    def acquire_write_bytes(self, n: int) -> None:
        self.indexing_pressure.acquire("coordinating", n)

    def release_write_bytes(self, n: int) -> None:
        self.indexing_pressure.release("coordinating", n)

    def stats(self) -> Dict[str, Any]:
        out = {name: pool.stats() for name, pool in self.pools.items()}
        # back-compat blob inside thread_pool; the full per-stage view
        # is the first-class _nodes/stats "indexing_pressure" section
        out["indexing_pressure"] = {
            "current_bytes": self.write_bytes_in_flight,
            "limit_bytes": self.write_bytes_limit,
            "rejections": self.write_bytes_rejections,
        }
        return out
