"""Named execution pools with bounded admission — the backpressure layer.

The reference sizes real thread pools per workload (threadpool/
ThreadPool.java:69-130: search, write, get, management, ... each with a
queue bound) and rejects work beyond the queue with
EsRejectedExecutionException → HTTP 429. This build's node is an
event-loop, so the analog is ADMISSION control across async boundaries:
a pool grants in-flight slots (acquire at request entry, release at
completion), queues a bounded overflow, and rejects the rest. The write
pool additionally accounts in-flight request BYTES — the reference's
indexing-pressure limit (IndexingPressure.java) that stops a node from
buffering unbounded bulk payloads.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Dict, Optional

from elasticsearch_tpu.utils.errors import RejectedExecutionError


class Pool:
    def __init__(self, name: str, size: int, queue_size: int):
        self.name = name
        self.size = size
        self.queue_size = queue_size
        self.active = 0
        self.queue: Deque[Callable[[], None]] = deque()
        self.completed = 0
        self.rejected = 0
        self.largest_queue = 0

    def stats(self) -> Dict[str, Any]:
        return {"threads": self.size, "active": self.active,
                "queue": len(self.queue), "queue_size": self.queue_size,
                "completed": self.completed, "rejected": self.rejected,
                "largest": self.largest_queue}


# reference pool sizing shape (ThreadPool.java:166-177), scaled to the
# event-loop model: "size" = concurrent in-flight operations
DEFAULT_POOLS = {
    "search": (16, 1000),
    "write": (8, 200),
    "get": (16, 1000),
    "management": (4, 100),
    "generic": (32, 500),
}

# indexing-pressure byte limit for in-flight write payloads
# (IndexingPressure MAX_INDEXING_BYTES analog: 10% of heap; fixed here)
WRITE_BYTES_LIMIT = 64 * 1024 * 1024


class ThreadPoolService:
    """Per-node admission pools + write-bytes accounting."""

    def __init__(self, pools: Optional[Dict[str, tuple]] = None):
        self.pools: Dict[str, Pool] = {
            name: Pool(name, size, queue)
            for name, (size, queue) in (pools or DEFAULT_POOLS).items()}
        self.write_bytes_in_flight = 0
        self.write_bytes_limit = WRITE_BYTES_LIMIT
        self.write_bytes_rejections = 0

    def pool(self, name: str) -> Pool:
        return self.pools[name]

    # -- slot admission ---------------------------------------------------

    def submit(self, name: str, task: Callable[[], None]) -> None:
        """Run task now if a slot is free, queue it within bounds, reject
        beyond them. The task MUST arrange for release(name) exactly once
        when its work (including async continuations) completes."""
        pool = self.pools[name]
        if pool.active < pool.size:
            pool.active += 1
            task()
            return
        if len(pool.queue) >= pool.queue_size:
            pool.rejected += 1
            raise RejectedExecutionError(
                f"rejected execution on [{name}]: queue capacity "
                f"[{pool.queue_size}] reached")
        pool.queue.append(task)
        pool.largest_queue = max(pool.largest_queue, len(pool.queue))

    def release(self, name: str) -> None:
        pool = self.pools[name]
        pool.active -= 1
        pool.completed += 1
        while pool.queue and pool.active < pool.size:
            pool.active += 1
            pool.queue.popleft()()

    # -- write-bytes accounting (indexing pressure) -----------------------

    def acquire_write_bytes(self, n: int) -> None:
        if self.write_bytes_in_flight + n > self.write_bytes_limit:
            self.write_bytes_rejections += 1
            raise RejectedExecutionError(
                f"rejected execution: in-flight indexing bytes "
                f"[{self.write_bytes_in_flight + n}] would exceed "
                f"[{self.write_bytes_limit}]")
        self.write_bytes_in_flight += n

    def release_write_bytes(self, n: int) -> None:
        self.write_bytes_in_flight = max(0, self.write_bytes_in_flight - n)

    def stats(self) -> Dict[str, Any]:
        out = {name: pool.stats() for name, pool in self.pools.items()}
        out["indexing_pressure"] = {
            "current_bytes": self.write_bytes_in_flight,
            "limit_bytes": self.write_bytes_limit,
            "rejections": self.write_bytes_rejections,
        }
        return out
