from elasticsearch_tpu.utils.settings import (
    Property,
    Scope,
    Setting,
    Settings,
    SettingsRegistry,
)
from elasticsearch_tpu.utils.murmur3 import murmur3_32, shard_id_for

__all__ = [
    "Property",
    "Scope",
    "Setting",
    "Settings",
    "SettingsRegistry",
    "murmur3_32",
    "shard_id_for",
]
