"""Typed settings registry.

Mirrors the semantics of the reference's setting infrastructure —
``Setting<T>`` (common/settings/Setting.java:87), ``ClusterSettings``
(common/settings/ClusterSettings.java:125) and ``IndexScopedSettings``
(common/settings/IndexScopedSettings.java:56) — re-expressed in Python:

- every setting is declared once, typed, with scope + dynamicity + validator;
- unknown settings are rejected at registration time (the registry doubles
  as documentation and validation, like the reference);
- dynamic updates flow through registered update-consumers.
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Dict, Generic, Iterable, List, Mapping, Optional, TypeVar

from elasticsearch_tpu.utils.errors import SettingsError

T = TypeVar("T")


class Scope(enum.Enum):
    NODE = "node"          # static, from config file / env only
    CLUSTER = "cluster"    # cluster-wide, possibly dynamic
    INDEX = "index"        # per-index, validated against IndexScopedSettings


class Property(enum.Flag):
    NONE = 0
    DYNAMIC = enum.auto()       # updatable at runtime
    FINAL = enum.auto()         # may never change after creation
    DEPRECATED = enum.auto()


class Setting(Generic[T]):
    """A single typed setting declaration."""

    def __init__(
        self,
        key: str,
        default: Any,
        parser: Callable[[Any], T],
        scope: Scope = Scope.NODE,
        properties: Property = Property.NONE,
        validator: Optional[Callable[[T], None]] = None,
    ):
        self.key = key
        self._default = default  # value, or callable(settings) -> value
        self.parser = parser
        self.scope = scope
        self.properties = properties
        self.validator = validator

    @property
    def dynamic(self) -> bool:
        return bool(self.properties & Property.DYNAMIC)

    def default(self, settings: "Settings") -> T:
        raw = self._default(settings) if callable(self._default) else self._default
        return self.parse(raw)

    def parse(self, raw: Any) -> T:
        try:
            value = self.parser(raw)
        except (ValueError, TypeError) as e:
            raise SettingsError(f"failed to parse setting [{self.key}] with value [{raw}]: {e}")
        if self.validator is not None:
            self.validator(value)
        return value

    def get(self, settings: "Settings") -> T:
        raw = settings.raw_get(self.key)
        if raw is None:
            return self.default(settings)
        return self.parse(raw)

    def exists(self, settings: "Settings") -> bool:
        return settings.raw_get(self.key) is not None

    # ---- convenience constructors -------------------------------------
    @staticmethod
    def int_setting(key: str, default: int, min_value: Optional[int] = None,
                    max_value: Optional[int] = None, scope: Scope = Scope.NODE,
                    properties: Property = Property.NONE) -> "Setting[int]":
        def validate(v: int) -> None:
            if min_value is not None and v < min_value:
                raise SettingsError(f"[{key}] must be >= {min_value}, got {v}")
            if max_value is not None and v > max_value:
                raise SettingsError(f"[{key}] must be <= {max_value}, got {v}")
        return Setting(key, default, int, scope, properties, validate)

    @staticmethod
    def float_setting(key: str, default: float, min_value: Optional[float] = None,
                      scope: Scope = Scope.NODE,
                      properties: Property = Property.NONE) -> "Setting[float]":
        def validate(v: float) -> None:
            if min_value is not None and v < min_value:
                raise SettingsError(f"[{key}] must be >= {min_value}, got {v}")
        return Setting(key, default, float, scope, properties, validate)

    @staticmethod
    def bool_setting(key: str, default: bool, scope: Scope = Scope.NODE,
                     properties: Property = Property.NONE) -> "Setting[bool]":
        def parse(v: Any) -> bool:
            if isinstance(v, bool):
                return v
            s = str(v).lower()
            if s in ("true", "1", "yes"):
                return True
            if s in ("false", "0", "no"):
                return False
            raise ValueError(f"cannot parse boolean [{v}]")
        return Setting(key, default, parse, scope, properties)

    @staticmethod
    def str_setting(key: str, default: str, scope: Scope = Scope.NODE,
                    properties: Property = Property.NONE,
                    choices: Optional[Iterable[str]] = None) -> "Setting[str]":
        validator = None
        if choices is not None:
            allowed = set(choices)

            def validator(v: str) -> None:
                if v not in allowed:
                    raise SettingsError(f"[{key}] must be one of {sorted(allowed)}, got [{v}]")
        return Setting(key, default, str, scope, properties, validator)

    @staticmethod
    def time_setting(key: str, default: str, scope: Scope = Scope.NODE,
                     properties: Property = Property.NONE) -> "Setting[float]":
        """Time value in seconds; accepts '30s', '1m', '500ms', '2h', or a number."""
        return Setting(key, default, parse_time_to_seconds, scope, properties)

    @staticmethod
    def bytes_setting(key: str, default: str, scope: Scope = Scope.NODE,
                      properties: Property = Property.NONE) -> "Setting[int]":
        """Byte size; accepts '512mb', '1gb', '10%' is NOT supported here, or int bytes."""
        return Setting(key, default, parse_bytes, scope, properties)


def parse_time_to_seconds(raw: Any) -> float:
    if isinstance(raw, (int, float)):
        return float(raw)
    s = str(raw).strip().lower()
    for suffix, mult in (("ms", 1e-3), ("s", 1.0), ("m", 60.0), ("h", 3600.0), ("d", 86400.0)):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    return float(s)


def parse_bytes(raw: Any) -> int:
    if isinstance(raw, int):
        return raw
    s = str(raw).strip().lower()
    for suffix, mult in (("kb", 1 << 10), ("mb", 1 << 20), ("gb", 1 << 30), ("tb", 1 << 40), ("b", 1)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(s)


class Settings:
    """An immutable bag of raw setting values (string/number keyed by dotted key)."""

    EMPTY: "Settings"

    def __init__(self, values: Optional[Mapping[str, Any]] = None):
        self._values: Dict[str, Any] = dict(_flatten(values or {}))

    def raw_get(self, key: str) -> Any:
        return self._values.get(key)

    def keys(self) -> Iterable[str]:
        return self._values.keys()

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._values)

    def with_overrides(self, overrides: Mapping[str, Any]) -> "Settings":
        merged = dict(self._values)
        merged.update(_flatten(overrides))
        # None value means "reset to default" (like ES null in settings update)
        return Settings({k: v for k, v in merged.items() if v is not None})

    def filter_prefix(self, prefix: str) -> "Settings":
        return Settings({k: v for k, v in self._values.items() if k.startswith(prefix)})

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Settings) and self._values == other._values

    def __repr__(self) -> str:
        return f"Settings({self._values!r})"


def _flatten(values: Mapping[str, Any], prefix: str = "") -> Dict[str, Any]:
    """Accept nested dicts ({'index': {'number_of_shards': 2}}) or dotted keys."""
    out: Dict[str, Any] = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, Mapping):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


Settings.EMPTY = Settings()


class SettingsRegistry:
    """Registry of declared settings for one scope; validates and dispatches updates.

    Reference analog: AbstractScopedSettings / ClusterSettings
    (common/settings/ClusterSettings.java:125).
    """

    def __init__(self, settings: Settings, declared: Iterable[Setting[Any]], scope: Scope):
        self.scope = scope
        self._declared: Dict[str, Setting[Any]] = {}
        for s in declared:
            if s.key in self._declared:
                raise SettingsError(f"duplicate setting registration [{s.key}]")
            self._declared[s.key] = s
        self._lock = threading.Lock()
        self._settings = settings
        self._consumers: List[tuple] = []  # (setting, callback)
        self.validate(settings)

    @property
    def current(self) -> Settings:
        return self._settings

    def register(self, setting: Setting[Any]) -> None:
        """Late registration (plugins contribute settings)."""
        with self._lock:
            if setting.key in self._declared:
                raise SettingsError(f"duplicate setting registration [{setting.key}]")
            self._declared[setting.key] = setting

    def get(self, setting: Setting[T]) -> T:
        return setting.get(self._settings)

    def get_by_key(self, key: str) -> Any:
        s = self._declared.get(key)
        if s is None:
            raise SettingsError(f"unknown setting [{key}]")
        return s.get(self._settings)

    def validate(self, settings: Settings, allow_unknown_prefixes: Iterable[str] = ()) -> None:
        """Unknown settings fail, like the reference's startup validation."""
        for key in settings.keys():
            if key in self._declared:
                self._declared[key].parse(settings.raw_get(key))
                continue
            if any(key.startswith(p) for p in allow_unknown_prefixes):
                continue
            suggestion = _closest(key, self._declared.keys())
            hint = f", did you mean [{suggestion}]?" if suggestion else ""
            raise SettingsError(f"unknown setting [{key}]{hint}")

    def add_settings_update_consumer(self, setting: Setting[T],
                                     consumer: Callable[[T], None]) -> None:
        if not setting.dynamic:
            raise SettingsError(f"setting [{setting.key}] is not dynamic")
        self._consumers.append((setting, consumer))

    def apply_update(self, overrides: Mapping[str, Any]) -> Settings:
        """Apply a dynamic settings update; rejects non-dynamic keys; fires consumers."""
        flat = _flatten(overrides)
        for key in flat:
            s = self._declared.get(key)
            if s is None:
                raise SettingsError(f"unknown setting [{key}]")
            if not s.dynamic:
                raise SettingsError(f"setting [{key}] is not dynamically updateable")
        with self._lock:
            new_settings = self._settings.with_overrides(flat)
            self.validate(new_settings)
            old = self._settings
            self._settings = new_settings
        for setting, consumer in self._consumers:
            new_val = setting.get(new_settings)
            if setting.get(old) != new_val:
                consumer(new_val)
        return new_settings


# ---------------------------------------------------------------------------
# declared cluster settings (the registry entries services read directly
# from committed persistent settings; TransportSearchAction consumes this
# one per request)
# ---------------------------------------------------------------------------

# SearchService.DEFAULT_ALLOW_PARTIAL_SEARCH_RESULTS analog: the cluster-wide
# default for requests that don't set allow_partial_search_results themselves.
SEARCH_DEFAULT_ALLOW_PARTIAL_RESULTS: Setting[bool] = Setting.bool_setting(
    "search.default_allow_partial_results", True,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# Shard-level adaptive micro-batching (search/batch_executor.py): eligible
# concurrent shard queries coalesce into single batched device programs.
# enabled=false restores the one-query-per-dispatch path byte-for-byte.
SEARCH_BATCH_ENABLED: Setting[bool] = Setting.bool_setting(
    "search.batch.enabled", True,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# longest a queued shard query may wait for batch-mates under load; an idle
# batcher drains immediately, so this bounds added latency, not typical
SEARCH_BATCH_MAX_WINDOW_MS: Setting[float] = Setting.float_setting(
    "search.batch.max_window_ms", 2.0, min_value=0.0,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# hard cap on queries per batched dispatch (the query dimension of the
# score plane; kept modest so n_q * n_docs_pad stays inside HBM)
SEARCH_BATCH_MAX_SIZE: Setting[int] = Setting.int_setting(
    "search.batch.max_size", 64, min_value=1, max_value=1024,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# occupancy-feedback window controller (search/batch_executor.py): a key
# whose drains carry at least this many live members keeps growing its
# collection window (toward max_window_ms); drains that come up thin
# (<= 1 member) shrink it back so an isolated query never waits for
# batch-mates that aren't coming
SEARCH_BATCH_TARGET_OCCUPANCY: Setting[int] = Setting.int_setting(
    "search.batch.target_occupancy", 4, min_value=2,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# Packed multi-segment device plane (ops/device_segment.py PlaneRegistry):
# a shard's live segments concatenated into one device-resident plane per
# (kind, field) so scoring is one program regardless of segment count.
# enabled=false restores the per-segment dispatch path byte-for-byte.
SEARCH_PLANE_ENABLED: Setting[bool] = Setting.bool_setting(
    "search.plane.enabled", True,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# shards below this segment count serve per-segment (a one-segment plane
# would only double HBM residency for zero dispatch savings)
SEARCH_PLANE_MIN_SEGMENTS: Setting[int] = Setting.int_setting(
    "search.plane.min_segments", 2, min_value=1,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# quantized coarse-pass re-rank depth (ALL coarse-tier classes: int8
# kNN, bf16 bm25/sparse): the coarse pass keeps this many candidates
# per query for the exact f32 re-rank — the STARTING depth; the margin
# check at position k' deepens adaptively (x2 per escalation) whenever
# it cannot prove the true top-k survived the coarse pass
SEARCH_PLANE_RERANK_DEPTH: Setting[int] = Setting.int_setting(
    "search.plane.rerank_depth", 128, min_value=1, max_value=65536,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# adaptive-depth ceiling: a query whose coarse margin still cannot
# clear the error bound at this depth serves EXACT instead (typed
# plane_quantized_fallback). For the bf16 classes the margin is a real
# proof (the a-priori bound exceeds the worst-case bf16 contribution
# error); for int8 kNN it hardens an empirical estimate — no usable
# closed-form bound exists — with the escalate-then-exact backstop and
# the CHAOS-swept golden suites owning the tail
SEARCH_PLANE_RERANK_DEPTH_MAX: Setting[int] = Setting.int_setting(
    "search.plane.rerank_depth_max", 1024, min_value=1,
    max_value=1 << 20, scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# quantized coarse pass + exact f32 re-rank for the plane's
# scatter-bound classes (int8 mirrors for kNN, bf16 term-frequency /
# norm / weight mirrors for bm25 and sparse); false = every plane query
# runs fully exact
SEARCH_PLANE_QUANTIZED: Setting[bool] = Setting.bool_setting(
    "search.plane.quantized", True,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# per-plane residency ceiling in bytes (0 = breaker-only budgeting); a
# plane over the cap is refused AT ADMISSION and the shard serves
# per-segment. Lazily-added components (quantized mirror, shard IVF)
# are charged to the device breaker and counted in residency stats but
# not re-checked against this cap
SEARCH_PLANE_MAX_BYTES: Setting[int] = Setting.int_setting(
    "search.plane.max_bytes", 0, min_value=0,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# Mesh-sharded device planes (ops/device_segment.py MeshPlaneRegistry +
# search/mesh_executor.py): a co-located fan-out — every target shard's
# plane resident on this node's device mesh — runs as ONE SPMD program
# instead of per-shard dispatches. enabled=false restores the RPC
# scatter-gather byte-for-byte.
SEARCH_MESH_ENABLED: Setting[bool] = Setting.bool_setting(
    "search.mesh.enabled", True,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# fan-outs below this co-located shard count keep the per-shard path (a
# one-shard mesh adds residency for zero dispatch savings — the per-shard
# plane already serves it in one program)
SEARCH_MESH_MIN_SHARDS: Setting[int] = Setting.int_setting(
    "search.mesh.min_shards", 2, min_value=2,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# data-parallel degree of the (dp, shard) mesh: the micro-batched query
# stack splits over this many replicas of the corpus stack (HBM cost:
# dp copies); 1 = pure model parallelism over the corpus axis
SEARCH_MESH_DP: Setting[int] = Setting.int_setting(
    "search.mesh.dp", 1, min_value=1, max_value=64,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# multi-host mesh topology: "" = single-host (all local devices, the
# pre-fleet behaviour), "N" = N equal hosts over the visible devices,
# "NxM" = N hosts x M devices per host (the num_nodes/gpus_per_node
# shape real multi-process deployments pin explicitly). Hosts partition
# the device axis contiguously; fan-outs whose target shards all have
# an active copy on a mesh-member host run as ONE program spanning the
# hosts instead of per-shard RPCs
SEARCH_MESH_HOSTS: Setting[str] = Setting.str_setting(
    "search.mesh.hosts", "",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# pre-init the device backend when a node boots (the legacy mesh
# plane's boot-time warmup): mesh_ready() refuses to pay first-init
# inside a search, so without this the FIRST mesh-eligible search per
# process always takes the RPC detour. Applied at boot from the node's
# initial committed state and re-checked (once) when the setting later
# appears in a committed state; counted as mesh_plane_warmups
SEARCH_MESH_WARMUP_AT_BOOT: Setting[bool] = Setting.bool_setting(
    "search.mesh.warmup_at_boot", False,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# Device observatory (search/device_profile.py) recompile-storm
# detector, promoted from DEVICE_PROFILE.configure() module config to
# dynamic cluster settings (the search.plane.* application pattern):
# more than storm_threshold distinct compiles of one kernel family
# inside storm_window is a recompile storm — a broken shape-bucketing
# invariant burning seconds of serving capacity per compile
SEARCH_DEVICE_PROFILE_STORM_THRESHOLD: Setting[int] = Setting.int_setting(
    "search.device_profile.storm_threshold", 8, min_value=1,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

SEARCH_DEVICE_PROFILE_STORM_WINDOW: Setting[float] = Setting.time_setting(
    "search.device_profile.storm_window", "60s",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# individual compiles slower than this log a slow-compile line even
# without a storm (the storm family's sibling knob, applied together)
SEARCH_DEVICE_PROFILE_SLOW_COMPILE: Setting[float] = Setting.time_setting(
    "search.device_profile.slow_compile_threshold", "1s",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# ---------------------------------------------------------------------------
# overload control plane (utils/threadpool.py + action/response_collector.py)
# ---------------------------------------------------------------------------

# Little's-law queue resizing for the search admission pool
# (QueueResizingEsThreadPoolExecutor analog): the pool moves its queue
# bound toward completion_rate * target_latency, so past saturation the
# queue bounds the LATENCY of admitted work. Resizing engages only when
# min != max (the reference's gate — the defaults keep the static 1000).
SEARCH_ADMISSION_TARGET_LATENCY: Setting[float] = Setting.time_setting(
    "search.admission.target_latency", "1s",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

SEARCH_ADMISSION_QUEUE_MIN: Setting[int] = Setting.int_setting(
    "search.admission.queue.min", 1000, min_value=1,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

SEARCH_ADMISSION_QUEUE_MAX: Setting[int] = Setting.int_setting(
    "search.admission.queue.max", 1000, min_value=1,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# completions per measurement frame (the reference's
# queue_resizing frame): rate = frame / elapsed drives the resize
SEARCH_ADMISSION_FRAME: Setting[int] = Setting.int_setting(
    "search.admission.frame", 100, min_value=1,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# Shard-side shed point (search/batch_executor.py ShardQueryBatcher):
# a data node receiving fan-outs from MANY coordinators bounds its own
# queued + in-flight member count and sheds the overflow AT INTAKE with
# a typed, Retry-After-carrying shard_busy rejection the coordinator
# fails over to the next ranked copy. 0 = unbounded — today's behavior,
# byte-for-byte (the reference's SEARCH threadpool queue bound ->
# es_rejected_execution_exception -> retry-on-next-replica contract).
SEARCH_SHARD_MAX_QUEUED_MEMBERS: Setting[int] = Setting.int_setting(
    "search.shard.max_queued_members", 0, min_value=0,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# Little's-law sizing for the shard shed point (the coordinator pool's
# queue-resizing controller applied node-side): the EFFECTIVE bound is
# min(max_queued_members, drain_rate * target_latency) once NodePressure
# has a drain-measured service EWMA — so past saturation the member
# queue bounds the LATENCY of admitted shard work, not an arbitrary
# count. 0 disables the shrink (the static bound alone applies).
SEARCH_SHARD_QUEUE_TARGET_LATENCY: Setting[float] = Setting.time_setting(
    "search.shard.queue_target_latency", "1s",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# ---------------------------------------------------------------------------
# request cache (indices/request_cache.py — IndicesRequestCache analog)
# ---------------------------------------------------------------------------

# master switch over BOTH tiers (the shard result cache and the
# coordinator fused-result cache); false restores uncached serving
# byte-for-byte and clears resident entries (typed "disabled")
SEARCH_REQUEST_CACHE_ENABLED: Setting[bool] = Setting.bool_setting(
    "search.request_cache.enabled", True,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# coverage gate for the top-k shapes (text/kNN/sparse hits+totals with
# size>0): size=0 bodies — counts, aggregation dashboards — always
# cache while the tier is enabled (the reference's default coverage);
# size>0 caches fleet-wide when this is true, or per request via
# ``"request_cache": true`` in the body (the reference's
# ``?request_cache=true`` opt-in)
SEARCH_REQUEST_CACHE_TOPK: Setting[bool] = Setting.bool_setting(
    "search.request_cache.topk", False,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# coordinator fused-result tier toggle: identical co-located fan-outs
# answered from the coordinator without any shard dispatch; false keeps
# the shard tier alone (duplicates still skip device work per shard)
SEARCH_REQUEST_CACHE_COORDINATOR: Setting[bool] = Setting.bool_setting(
    "search.request_cache.coordinator", True,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# LRU eviction budget per tier: resident entries above this evict
# coldest-first BEFORE the request_cache breaker child can trip
SEARCH_REQUEST_CACHE_MAX_BYTES: Setting[int] = Setting.bytes_setting(
    "search.request_cache.max_bytes", "32mb",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# per-entry cap: one pathological response (deep aggs over a huge key
# space) must not evict the whole hot set to cache itself
SEARCH_REQUEST_CACHE_MAX_ENTRY_BYTES: Setting[int] = Setting.bytes_setting(
    "search.request_cache.max_entry_bytes", "1mb",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# Adaptive per-copy shard-query transport timeout (the PR 13 recorded
# leg): the flat 60s becomes min(ceiling, max(floor, 30x the copy's ARS
# response EWMA)), further bounded by the request's own [timeout]
# budget — a stalled copy fails over in RTT-scale time instead of
# waiting out a minute. Unknown copies (no EWMA yet) keep the ceiling.
SEARCH_SHARD_QUERY_TIMEOUT_FLOOR: Setting[float] = Setting.time_setting(
    "search.shard.query_timeout.floor", "2s",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

SEARCH_SHARD_QUERY_TIMEOUT_CEILING: Setting[float] = Setting.time_setting(
    "search.shard.query_timeout.ceiling", "60s",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)

# C3 adaptive replica selection (OperationRouting.USE_ADAPTIVE_REPLICA_
# SELECTION_SETTING analog): false restores pure round-robin rotation
# of shard copies — the chaos suite's baseline for the reroute proof.
CLUSTER_USE_ADAPTIVE_REPLICA_SELECTION: Setting[bool] = \
    Setting.bool_setting(
        "cluster.routing.use_adaptive_replica_selection", True,
        scope=Scope.CLUSTER, properties=Property.DYNAMIC)


# write-path admission budget (IndexingPressure.MAX_INDEXING_BYTES
# analog — 10% of heap there, a fixed default here): the node-wide cap
# on in-flight indexing bytes. Coordinating and primary admission share
# the limit; the replica stage is granted 1.5x headroom (see
# utils/threadpool.py IndexingPressure) so replication fan-out can never
# deadlock behind coordinating admission on the same node. Removing the
# setting restores the documented 64mb default.
INDEXING_PRESSURE_MEMORY_LIMIT: Setting[int] = Setting.bytes_setting(
    "indexing_pressure.memory.limit", "64mb",
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)


# gateway.recover_after_data_nodes-style fleet-completeness release: when
# this many data nodes have joined AND answered the shard-state fetch,
# allocation stops waiting out EXISTING_COPY_GRACE for absent copy-holders
# (0 = disabled; the grace clock stays the fallback)
GATEWAY_EXPECTED_DATA_NODES: Setting[int] = Setting.int_setting(
    "gateway.expected_data_nodes", 0, min_value=0,
    scope=Scope.CLUSTER, properties=Property.DYNAMIC)


# soft-deletes analog (IndexSettings.INDEX_SOFT_DELETES_RETENTION_
# OPERATIONS_SETTING): every engine retains at least this many of its
# newest operations — INCLUDING delete tombstones and noops — as a
# seqno-indexed history, so a briefly-departed copy can catch up by
# replaying only the ops it missed instead of paying a full store copy.
# Retention leases can extend the retained range further; this is the
# floor. Dynamic: a settings update reaches live engines through the
# reconciler's metadata apply.
INDEX_SOFT_DELETES_RETENTION_OPS: Setting[int] = Setting.int_setting(
    "index.soft_deletes.retention.ops", 1024, min_value=0,
    scope=Scope.INDEX, properties=Property.DYNAMIC)

# peer-recovery retention lease expiry (IndexSettings.INDEX_SOFT_DELETES_
# RETENTION_LEASE_PERIOD_SETTING): a tracked copy's lease is renewed every
# time its local checkpoint advances; once a departed copy has been gone
# longer than this, its lease expires and the history it was holding may
# be pruned — the copy then pays the file-based path on return.
INDEX_RETENTION_LEASE_PERIOD: Setting[float] = Setting.time_setting(
    "index.soft_deletes.retention_lease.period", "12h",
    scope=Scope.INDEX, properties=Property.DYNAMIC)


def setting_from_state(state, setting: Setting[T]) -> T:
    """Read a dynamic cluster setting off a committed cluster state's
    persistent settings. Missing values — and unparseable operator
    values — fall back to the setting's default, so a bad update can
    never wedge a hot path. The one read-side idiom every service that
    consumes dynamic settings directly from state shares."""
    raw = None
    if state is not None:
        raw = state.metadata.persistent_settings.get(setting.key)
    if raw is None:
        return setting.default(None)
    try:
        return setting.parse(raw)
    except Exception:  # noqa: BLE001 — fail toward the default
        return setting.default(None)


def _closest(key: str, candidates: Iterable[str]) -> Optional[str]:
    """Cheap typo suggestion: smallest prefix-distance candidate."""
    best, best_score = None, 0
    for c in candidates:
        score = len(_common_prefix(key, c))
        if score > best_score:
            best, best_score = c, score
    return best if best_score >= 3 else None


def _common_prefix(a: str, b: str) -> str:
    i = 0
    while i < min(len(a), len(b)) and a[i] == b[i]:
        i += 1
    return a[:i]
