"""Exception hierarchy.

Mirrors the role of ``org.elasticsearch.ElasticsearchException`` and friends
(reference: server/src/main/java/org/elasticsearch/ElasticsearchException.java):
every error carries an HTTP status so the REST layer can map failures
uniformly, and errors serialize to/from JSON for transport.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def exception_type_name(class_name: str) -> str:
    """CamelCase class name -> snake_case '_exception' wire name, e.g.
    IndexNotFoundError -> index_not_found_exception (ES-compatible)."""
    if class_name.endswith("Error"):
        class_name = class_name[: -len("Error")]
    elif class_name.endswith("Exception"):
        class_name = class_name[: -len("Exception")]
    out = []
    for i, ch in enumerate(class_name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out) + "_exception"


class SearchEngineError(Exception):
    """Base for all engine errors. Carries an HTTP status code."""

    status = 500

    def __init__(self, message: str, **metadata: Any):
        super().__init__(message)
        self.message = message
        self.metadata: Dict[str, Any] = metadata

    @property
    def error_type(self) -> str:
        return exception_type_name(type(self).__name__)

    def to_json(self) -> Dict[str, Any]:
        body: Dict[str, Any] = {"type": self.error_type, "reason": self.message}
        body.update(self.metadata)
        return body


class IndexNotFoundError(SearchEngineError):
    status = 404

    def __init__(self, index: str):
        super().__init__(f"no such index [{index}]", index=index)
        self.index = index


class IndexAlreadyExistsError(SearchEngineError):
    status = 400

    def __init__(self, index: str):
        super().__init__(f"index [{index}] already exists", index=index)


class DocumentMissingError(SearchEngineError):
    status = 404

    def __init__(self, index: str, doc_id: str):
        super().__init__(f"[{index}][{doc_id}]: document missing", index=index)


class ShardNotFoundError(SearchEngineError):
    status = 404


class MapperParsingError(SearchEngineError):
    status = 400


class ResourceNotFoundError(SearchEngineError):
    status = 404


class IllegalArgumentError(SearchEngineError):
    status = 400


class QueryParsingError(SearchEngineError):
    status = 400


class VersionConflictError(SearchEngineError):
    """Optimistic-concurrency failure (seq_no/primary_term or version mismatch).

    Reference analog: VersionConflictEngineException
    (server/.../index/engine/VersionConflictEngineException.java).
    """

    status = 409


class CircuitBreakingError(SearchEngineError):
    """Memory budget exceeded; request rejected instead of OOMing the device.

    Reference analog: common/breaker/CircuitBreakingException.java.
    """

    status = 429


class RejectedExecutionError(SearchEngineError):
    """Executor queue full. Reference analog: EsRejectedExecutionException."""

    status = 429


class EsRejectedExecutionError(RejectedExecutionError):
    """Write-path indexing-pressure rejection: in-flight write bytes at
    one of the three stages (coordinating / primary / replica) would
    exceed the node's ``indexing_pressure.memory.limit`` budget.

    Wire name is ``es_rejected_execution_exception`` — the exact type
    the reference's IndexingPressure rejections carry, which client
    bulk-backoff logic keys on.

    Like ShardBusyError, the message carries machine-parseable
    ``stage=<stage>`` and ``retry_after=<s>s`` suffixes because replica
    rejections travel back to the primary through transport error
    STRINGIFICATION (metadata does not survive); the primary re-parses
    them with ``write_pressure_info`` to tell a transiently-starved
    replica (retry, converge) from a broken one (fail from the in-sync
    set)."""

    status = 429


class ShardBusyError(SearchEngineError):
    """Data-node shard query queue at its member bound: the query was shed
    AT INTAKE (it never touched a drain). The coordinator treats this as a
    ROUTING signal — fail over to the next ranked copy — not a failure;
    only an all-copies-shed shard surfaces it to the caller.

    Reference analog: es_rejected_execution_exception from the SEARCH
    threadpool's bounded queue, which the coordinator retries on the next
    replica (AbstractSearchAsyncAction.onShardFailure + the reference's
    "reads go to any replica, all APIs reroute" contract).

    The message carries machine-parseable ``retry_after=<s>s`` and
    ``queued=<n>`` suffixes because transport Deferred rejections and
    remote-handler errors are STRINGIFIED on the wire (PR 9 invariant) —
    metadata does not survive; the coordinator re-parses it with
    ``shard_busy_info``."""

    status = 429


def shard_busy_info(err: Any) -> Optional[Dict[str, int]]:
    """Parse a (possibly wire-stringified) shard_busy rejection out of any
    error: returns {"retry_after": s, "queued": n} or None. Works on a
    local ShardBusyError, a RemoteTransportError wrapping one, and the
    bare cause string — the one decoder every failover site shares."""
    if err is None:
        return None
    name = type(err).__name__
    text = str(err)
    if name != "ShardBusyError" and \
            getattr(err, "cause_type", "") != "ShardBusyError" and \
            "ShardBusyError" not in text:
        return None
    import re
    ra = re.search(r"retry_after=(\d+)s", text)
    q = re.search(r"queued=(\d+)", text)
    return {"retry_after": int(ra.group(1)) if ra else 1,
            "queued": int(q.group(1)) if q else 0}


def write_pressure_info(err: Any) -> Optional[Dict[str, Any]]:
    """Parse a (possibly wire-stringified) indexing-pressure rejection
    out of any error: returns {"stage": str, "retry_after": s} or None.
    Works on a local EsRejectedExecutionError, a RemoteTransportError
    wrapping one, and the bare cause string — the one decoder the
    primary's replica-retry loop and the bulk item mapper share."""
    if err is None:
        return None
    name = type(err).__name__
    text = str(err)
    if name != "EsRejectedExecutionError" and \
            getattr(err, "cause_type", "") != "EsRejectedExecutionError" \
            and "EsRejectedExecutionError" not in text:
        return None
    import re
    stage = re.search(r"stage=(\w+)", text)
    ra = re.search(r"retry_after=(\d+)s", text)
    return {"stage": stage.group(1) if stage else "unknown",
            "retry_after": int(ra.group(1)) if ra else 1}


class SearchPhaseExecutionError(SearchEngineError):
    """Every shard of a search failed — the whole request fails with the
    underlying cause's status (a request-wide 429 when breakers tripped
    everywhere, not a 200 with empty hits).

    Reference analog: action/search/SearchPhaseExecutionException.java
    (status() derives from the grouped shard failures' causes).
    """

    status = 503

    def __init__(self, message: str, cause_status: int = 503,
                 **metadata):
        super().__init__(message, **metadata)
        self.status = cause_status


class ClusterBlockError(SearchEngineError):
    """Operation blocked by cluster-level block (e.g. no master, read-only).

    Reference analog: cluster/block/ClusterBlockException.java.
    """

    status = 503


class NotMasterError(SearchEngineError):
    status = 503


class TaskCancelledError(SearchEngineError):
    status = 400


class SearchBudgetExceededError(SearchEngineError):
    """The per-request [timeout] budget expired while a shard was still
    collecting: the shard stops work instead of computing results the
    coordinator has already given up on (shard-side analog of the
    coordinator's budget timer; the reference checks the timeout inside
    collection via QueryPhase's timeout-checking collectors)."""

    status = 503


class TransportError(SearchEngineError):
    status = 500


class NodeDisconnectedError(TransportError):
    status = 500


class ReceiveTimeoutError(TransportError):
    status = 500


class SettingsError(IllegalArgumentError):
    status = 400


class SnapshotError(SearchEngineError):
    status = 500


class ShardCorruptedError(SearchEngineError):
    """On-disk data failed checksum verification (or a corruption marker
    is present). The shard must not serve from this store copy.

    Reference analog: Lucene's CorruptIndexException surfaced through
    Store.markStoreCorrupted / Store.failIfCorrupted.
    """

    status = 500


class RecoveryFailedError(SearchEngineError):
    status = 500


class UnavailableShardsError(SearchEngineError):
    """No active copy available to execute the operation
    (action/UnavailableShardsException.java)."""
    status = 503


def error_from_json(body: Dict[str, Any]) -> SearchEngineError:
    """Rehydrate an error from its JSON form (transport deserialization)."""
    err = SearchEngineError(body.get("reason", "unknown"))
    err.metadata = {k: v for k, v in body.items() if k not in ("type", "reason")}
    return err
