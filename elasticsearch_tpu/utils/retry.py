"""Unified retry: exponential backoff with equal jitter and a deadline cap.

Reference analog: action/support/RetryableAction.java:43 — one retry
discipline for every transient-failure loop (reroute-on-stale-routing,
peer recovery, CCR follow), replacing per-call-site fixed-delay spinners.
Backoff is *equal jitter*: the nth retry waits ``base/2 + U(0, base/2)``
where ``base = initial * 2**n`` (capped at ``max_delay``) — delays are
strictly increasing until the cap, and jitter decorrelates retry storms
across concurrent actions.

Driven entirely by the Scheduler seam, so the SAME code backs off in
wall-clock production and in seeded virtual-time simulation (where the
DeterministicScheduler's ``random`` makes the jitter reproducible).
"""

from __future__ import annotations

import random as _random
from typing import Any, Callable, List, Optional

from elasticsearch_tpu.transport.scheduler import Scheduler

__all__ = ["RetryableAction"]

AttemptFn = Callable[[Callable[[Optional[dict], Optional[Exception]], None]],
                     None]
DoneFn = Callable[[Optional[dict], Optional[Exception]], None]


class RetryableAction:
    """Run ``attempt(cb)`` until it succeeds, fails non-retryably, or the
    deadline passes; then call ``on_done(resp, err)`` exactly once.

    ``attempt`` is callback-style (fire an async op, call ``cb(resp, err)``
    once) so replication/recovery code adopts it without restructuring.
    ``is_retryable(err) -> bool`` classifies failures; None retries every
    error. Each backoff delay is appended to ``self.delays`` — observable
    telemetry, and what the chaos suite asserts strict increase on.
    """

    def __init__(self, scheduler: Scheduler, attempt: AttemptFn,
                 on_done: DoneFn, *,
                 initial_delay: float = 0.25,
                 max_delay: float = 30.0,
                 timeout: float = 60.0,
                 is_retryable: Optional[Callable[[Any], bool]] = None):
        if initial_delay <= 0:
            raise ValueError("initial_delay must be positive")
        self.scheduler = scheduler
        self.attempt = attempt
        self.on_done = on_done
        self.initial_delay = initial_delay
        self.max_delay = max_delay
        self.deadline = scheduler.now() + timeout
        self.is_retryable = is_retryable
        # seeded under the deterministic scheduler, wall-random in prod
        self.random = getattr(scheduler, "random", None) or _random
        self.delays: List[float] = []
        self._n = 0
        self._done = False

    # ------------------------------------------------------------------

    def run(self) -> None:
        self._attempt_once()

    def _finish(self, resp: Optional[dict], err: Optional[Exception]) -> None:
        if self._done:
            return
        self._done = True
        self.on_done(resp, err)

    def _next_delay(self) -> float:
        base = min(self.initial_delay * (2 ** self._n), self.max_delay)
        return base / 2.0 + self.random.uniform(0.0, base / 2.0)

    def _attempt_once(self) -> None:
        fired = {"flag": False}

        def cb(resp: Optional[dict], err: Optional[Exception] = None) -> None:
            if fired["flag"] or self._done:
                return
            fired["flag"] = True
            if err is None:
                self._finish(resp, None)
                return
            if self.is_retryable is not None and not self.is_retryable(err):
                self._finish(None, err)
                return
            delay = self._next_delay()
            if self.scheduler.now() + delay > self.deadline:
                # out of budget: surface the LAST error, like the
                # reference's onFinalFailure
                self._finish(None, err)
                return
            self._n += 1
            self.delays.append(delay)
            self.scheduler.schedule(delay, self._attempt_once)

        try:
            self.attempt(cb)
        except Exception as e:  # noqa: BLE001 — sync throw = failed attempt
            cb(None, e)
