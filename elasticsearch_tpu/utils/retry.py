"""Unified retry: exponential backoff with equal jitter and a deadline cap.

Reference analog: action/support/RetryableAction.java:43 — one retry
discipline for every transient-failure loop (reroute-on-stale-routing,
peer recovery, CCR follow), replacing per-call-site fixed-delay spinners.
Backoff is *equal jitter*: the nth retry waits ``base/2 + U(0, base/2)``
where ``base = initial * 2**n`` (capped at ``max_delay``) — delays are
strictly increasing until the cap, and jitter decorrelates retry storms
across concurrent actions.

Driven entirely by the Scheduler seam, so the SAME code backs off in
wall-clock production and in seeded virtual-time simulation (where the
DeterministicScheduler's ``random`` makes the jitter reproducible).
"""

from __future__ import annotations

import random as _random
from typing import Any, Callable, List, Optional

from elasticsearch_tpu.transport.scheduler import Scheduler

__all__ = ["RetryableAction", "retry_transient", "transient_cluster_error"]

AttemptFn = Callable[[Callable[[Optional[dict], Optional[Exception]], None]],
                     None]
DoneFn = Callable[[Optional[dict], Optional[Exception]], None]


class RetryableAction:
    """Run ``attempt(cb)`` until it succeeds, fails non-retryably, or the
    deadline passes; then call ``on_done(resp, err)`` exactly once.

    ``attempt`` is callback-style (fire an async op, call ``cb(resp, err)``
    once) so replication/recovery code adopts it without restructuring.
    ``is_retryable(err) -> bool`` classifies failures; None retries every
    error. Each backoff delay is appended to ``self.delays`` — observable
    telemetry, and what the chaos suite asserts strict increase on.
    """

    def __init__(self, scheduler: Scheduler, attempt: AttemptFn,
                 on_done: DoneFn, *,
                 initial_delay: float = 0.25,
                 max_delay: float = 30.0,
                 timeout: float = 60.0,
                 is_retryable: Optional[Callable[[Any], bool]] = None):
        if initial_delay <= 0:
            raise ValueError("initial_delay must be positive")
        self.scheduler = scheduler
        self.attempt = attempt
        self.on_done = on_done
        self.initial_delay = initial_delay
        self.max_delay = max_delay
        self.deadline = scheduler.now() + timeout
        self.is_retryable = is_retryable
        # seeded under the deterministic scheduler, wall-random in prod
        self.random = getattr(scheduler, "random", None) or _random
        self.delays: List[float] = []
        self._n = 0
        self._done = False

    # ------------------------------------------------------------------

    def run(self) -> None:
        self._attempt_once()

    def _finish(self, resp: Optional[dict], err: Optional[Exception]) -> None:
        if self._done:
            return
        self._done = True
        self.on_done(resp, err)

    def _next_delay(self) -> float:
        base = min(self.initial_delay * (2 ** self._n), self.max_delay)
        return base / 2.0 + self.random.uniform(0.0, base / 2.0)

    def _attempt_once(self) -> None:
        fired = {"flag": False}

        def cb(resp: Optional[dict], err: Optional[Exception] = None) -> None:
            if fired["flag"] or self._done:
                return
            fired["flag"] = True
            if err is None:
                self._finish(resp, None)
                return
            if self.is_retryable is not None and not self.is_retryable(err):
                self._finish(None, err)
                return
            delay = self._next_delay()
            if self.scheduler.now() + delay > self.deadline:
                # out of budget: surface the LAST error, like the
                # reference's onFinalFailure
                self._finish(None, err)
                return
            self._n += 1
            self.delays.append(delay)
            self.scheduler.schedule(delay, self._attempt_once)

        try:
            self.attempt(cb)
        except Exception as e:  # noqa: BLE001 — sync throw = failed attempt
            cb(None, e)


def transient_cluster_error(err: Any, retry_timeouts: bool = False) -> bool:
    """THE transient-failure classifier for control-plane retries (master
    round-trips, ILM/SLM steps, shard-state reports): no elected master
    mid-election, an unreachable node, or a cluster block that a later
    state may lift. Remote errors arrive as RemoteTransportError whose
    message names the cause type, hence the string checks.

    ``retry_timeouts`` gates ReceiveTimeoutError: a timed-out request has
    an AMBIGUOUS outcome (the server may have executed it), so only
    callers whose action is idempotent on the receiver (e.g. shard-failed
    reports, recovery-start) may pass True. Non-idempotent mutations like
    create_snapshot must leave it False — an automatic resend would trade
    a lost ack for a spurious already-exists failure; their periodic
    services re-drive on the next tick where actual state is observable."""
    from elasticsearch_tpu.transport.transport import (
        ConnectTransportError,
    )
    from elasticsearch_tpu.utils.errors import (
        ClusterBlockError, NotMasterError, ReceiveTimeoutError,
    )
    if retry_timeouts and isinstance(err, ReceiveTimeoutError):
        return True
    if isinstance(err, (NotMasterError, ClusterBlockError,
                        ConnectTransportError)):
        return True
    text = str(err)
    return ("NotMasterError" in text or "ClusterBlockError" in text
            or "not connected" in text)


def retry_transient(scheduler: Scheduler, attempt: AttemptFn,
                    on_done: DoneFn, *,
                    initial_delay: float = 0.5,
                    max_delay: float = 5.0,
                    timeout: float = 30.0) -> RetryableAction:
    """A RetryableAction preconfigured for transient control-plane
    failures; returns the (already running) action."""
    action = RetryableAction(scheduler, attempt, on_done,
                             initial_delay=initial_delay,
                             max_delay=max_delay, timeout=timeout,
                             is_retryable=transient_cluster_error)
    action.run()
    return action
