"""x-content: format-agnostic document (de)serialization.

The analog of the reference's libs/x-content abstraction
(libs/x-content/.../XContent.java, XContentType.java): one logical
document model readable/writable as JSON, YAML, CBOR, or SMILE, with
format detection from content-type headers and leading bytes. The
reference wraps Jackson; here JSON is the stdlib, YAML rides the baked-in
PyYAML (safe loader only), and CBOR (RFC 8949) and SMILE are small
self-contained codecs covering the document subset the APIs exchange
(maps, arrays, strings, ints, floats, bools, null, binary).
"""

from __future__ import annotations

import json
import struct
from typing import Any, Optional, Tuple

from elasticsearch_tpu.utils.errors import IllegalArgumentError

JSON = "json"
YAML = "yaml"
CBOR = "cbor"
SMILE = "smile"

CONTENT_TYPES = {
    JSON: "application/json",
    YAML: "application/yaml",
    CBOR: "application/cbor",
    SMILE: "application/smile",
}

# SMILE header: ':', ')', '\n' then a version/flags byte
# (the Jackson Smile format magic)
_SMILE_MAGIC = b":)\n"


def format_from_content_type(content_type: Optional[str]) -> Optional[str]:
    if not content_type:
        return None
    ct = content_type.lower()
    for fmt, mime in CONTENT_TYPES.items():
        if mime in ct or f"/{fmt}" in ct or f"+{fmt}" in ct:
            return fmt
    if "x-ndjson" in ct:
        return JSON
    return None


def sniff_format(raw: bytes) -> str:
    """Leading-bytes detection (XContentFactory.xContentType analog)."""
    if raw.startswith(_SMILE_MAGIC):
        return SMILE
    if raw[:1] in (b"{", b"["):
        return JSON
    # CBOR maps/arrays: major type 4/5 in the first byte, or self-describe
    # tag d9 d9 f7
    if raw[:3] == b"\xd9\xd9\xf7":
        return CBOR
    if raw and (raw[0] >> 5) in (4, 5) and raw[0] >= 0x80:
        return CBOR
    if raw.startswith(b"---") or raw[:1].isalpha():
        return YAML
    return JSON


def loads(raw: bytes, content_type: Optional[str] = None) -> Any:
    fmt = format_from_content_type(content_type) or sniff_format(raw)
    if fmt == JSON:
        return json.loads(raw)
    if fmt == YAML:
        import yaml
        return yaml.safe_load(raw)
    if fmt == CBOR:
        value, offset = _cbor_decode(raw, 0)
        return value
    if fmt == SMILE:
        return _smile_decode(raw)
    raise IllegalArgumentError(f"unsupported content format [{fmt}]")


def _b64_bytes(o: Any) -> Any:
    """Text formats carry binary as base64 (the reference's JSON/YAML
    rendering of binary fields)."""
    import base64
    if isinstance(o, bytes):
        return base64.b64encode(o).decode("ascii")
    raise TypeError(f"not JSON serializable: {type(o).__name__}")


def dumps(value: Any, fmt: str = JSON) -> bytes:
    if fmt == JSON:
        return json.dumps(value, default=_b64_bytes).encode("utf-8")
    if fmt == YAML:
        import base64
        import yaml

        class _Dumper(yaml.SafeDumper):
            pass
        _Dumper.add_representer(
            bytes, lambda dumper, data: dumper.represent_str(
                base64.b64encode(data).decode("ascii")))
        return yaml.dump(value, Dumper=_Dumper,
                         sort_keys=False).encode("utf-8")
    if fmt == CBOR:
        out = bytearray()
        _cbor_encode(value, out)
        return bytes(out)
    if fmt == SMILE:
        return _smile_encode(value)
    raise IllegalArgumentError(f"unsupported content format [{fmt}]")


def response_format(accept: Optional[str],
                    request_format: Optional[str]) -> str:
    """Responses mirror the request format unless Accept overrides
    (RestRequest.getResponseContentType analog)."""
    fmt = format_from_content_type(accept)
    if fmt is not None:
        return fmt
    return request_format or JSON


# ---------------------------------------------------------------------------
# CBOR (RFC 8949) — the document subset
# ---------------------------------------------------------------------------

def _cbor_encode(v: Any, out: bytearray) -> None:
    if v is None:
        out.append(0xF6)
    elif v is True:
        out.append(0xF5)
    elif v is False:
        out.append(0xF4)
    elif isinstance(v, int):
        if v >= 0:
            _cbor_head(0, v, out)
        else:
            _cbor_head(1, -1 - v, out)
    elif isinstance(v, float):
        out.append(0xFB)
        out += struct.pack(">d", v)
    elif isinstance(v, bytes):
        _cbor_head(2, len(v), out)
        out += v
    elif isinstance(v, str):
        b = v.encode("utf-8")
        _cbor_head(3, len(b), out)
        out += b
    elif isinstance(v, (list, tuple)):
        _cbor_head(4, len(v), out)
        for item in v:
            _cbor_encode(item, out)
    elif isinstance(v, dict):
        _cbor_head(5, len(v), out)
        for k, item in v.items():
            _cbor_encode(str(k), out)
            _cbor_encode(item, out)
    else:
        raise IllegalArgumentError(
            f"cannot CBOR-encode [{type(v).__name__}]")


def _cbor_head(major: int, arg: int, out: bytearray) -> None:
    if arg < 24:
        out.append((major << 5) | arg)
    elif arg < 0x100:
        out.append((major << 5) | 24)
        out.append(arg)
    elif arg < 0x10000:
        out.append((major << 5) | 25)
        out += struct.pack(">H", arg)
    elif arg < 0x100000000:
        out.append((major << 5) | 26)
        out += struct.pack(">I", arg)
    else:
        out.append((major << 5) | 27)
        out += struct.pack(">Q", arg)


def _cbor_decode(raw: bytes, i: int) -> Tuple[Any, int]:
    if i >= len(raw):
        raise IllegalArgumentError("truncated CBOR input")
    first = raw[i]
    if raw[i : i + 3] == b"\xd9\xd9\xf7":       # self-describe tag
        return _cbor_decode(raw, i + 3)
    major, info = first >> 5, first & 0x1F
    i += 1

    def read_arg() -> Tuple[int, int]:
        nonlocal i
        if info < 24:
            return info, i
        if info == 24:
            v = raw[i]
            return v, i + 1
        if info == 25:
            return struct.unpack_from(">H", raw, i)[0], i + 2
        if info == 26:
            return struct.unpack_from(">I", raw, i)[0], i + 4
        if info == 27:
            return struct.unpack_from(">Q", raw, i)[0], i + 8
        raise IllegalArgumentError(
            f"unsupported CBOR additional info [{info}]")

    if major == 0:
        arg, i = read_arg()
        return arg, i
    if major == 1:
        arg, i = read_arg()
        return -1 - arg, i
    if major == 2:
        n, i = read_arg()
        return raw[i : i + n], i + n
    if major == 3:
        n, i = read_arg()
        return raw[i : i + n].decode("utf-8"), i + n
    if major == 4:
        n, i = read_arg()
        items = []
        for _ in range(n):
            item, i = _cbor_decode(raw, i)
            items.append(item)
        return items, i
    if major == 5:
        n, i = read_arg()
        obj = {}
        for _ in range(n):
            k, i = _cbor_decode(raw, i)
            v, i = _cbor_decode(raw, i)
            obj[k] = v
        return obj, i
    if major == 6:                               # tag: skip, decode item
        _arg, i = read_arg()
        return _cbor_decode(raw, i)
    # major 7: simple values / floats
    if info == 20:
        return False, i
    if info == 21:
        return True, i
    if info in (22, 23):
        return None, i
    if info == 25:                               # half float
        h = struct.unpack_from(">H", raw, i)[0]
        return _half_to_float(h), i + 2
    if info == 26:
        return struct.unpack_from(">f", raw, i)[0], i + 4
    if info == 27:
        return struct.unpack_from(">d", raw, i)[0], i + 8
    raise IllegalArgumentError(f"unsupported CBOR simple value [{info}]")


def _half_to_float(h: int) -> float:
    sign = -1.0 if h & 0x8000 else 1.0
    exp = (h >> 10) & 0x1F
    frac = h & 0x3FF
    if exp == 0:
        return sign * frac * 2.0 ** -24
    if exp == 31:
        return sign * (float("inf") if frac == 0 else float("nan"))
    return sign * (1 + frac / 1024.0) * 2.0 ** (exp - 15)


# ---------------------------------------------------------------------------
# SMILE — the Jackson binary JSON format, document subset.
# Encoder writes without shared-string back-references (legal per spec);
# decoder understands the common token space including shared-name refs.
# ---------------------------------------------------------------------------

def _smile_encode(value: Any) -> bytes:
    out = bytearray(_SMILE_MAGIC)
    out.append(0x00)          # version 0, no shared names/values, no raw
    _smile_write(value, out)
    return bytes(out)


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _smile_vint(n: int, out: bytearray) -> None:
    """Smile VInt: 7 bits per byte big-endian-ish, LAST byte holds 6 bits
    with the sign bit 0x80 set."""
    chunks = [n & 0x3F]
    n >>= 6
    while n:
        chunks.append(n & 0x7F)
        n >>= 7
    for c in reversed(chunks[1:]):
        out.append(c)
    out.append(0x80 | chunks[0])


def _smile_write(v: Any, out: bytearray) -> None:
    if v is None:
        out.append(0x21)
    elif v is True:
        out.append(0x23)
    elif v is False:
        out.append(0x22)
    elif isinstance(v, int):
        z = _zigzag(v)
        out.append(0x24 if z < (1 << 32) else 0x25)   # int32 / int64 vint
        _smile_vint(z, out)
    elif isinstance(v, float):
        out.append(0x29)      # 64-bit double
        bits = struct.unpack(">Q", struct.pack(">d", v))[0]
        # doubles are written as 10 x 7-bit groups, high bits first
        for shift in range(63, -1, -7):
            out.append((bits >> shift) & 0x7F)
    elif isinstance(v, str):
        # long variable-length unicode for every size: always correct
        # (the tiny/short tokens are an encoding-size optimization only)
        b = v.encode("utf-8")
        if not b:
            out.append(0x20)  # empty string
        else:
            out.append(0xE4)
            out += b
            out.append(0xFC)  # end-of-string marker
    elif isinstance(v, bytes):
        out.append(0xE8)      # "safe" binary (7-bit) — encode base64-free
        _smile_vint(len(v), out)
        # 7-bit packing: 7 bytes -> 8 septets
        bits = 0
        nbits = 0
        for byte in v:
            bits = (bits << 8) | byte
            nbits += 8
            while nbits >= 7:
                out.append((bits >> (nbits - 7)) & 0x7F)
                nbits -= 7
        if nbits:
            out.append((bits << (7 - nbits)) & 0x7F)
    elif isinstance(v, (list, tuple)):
        out.append(0xF8)      # START_ARRAY
        for item in v:
            _smile_write(item, out)
        out.append(0xF9)      # END_ARRAY
    elif isinstance(v, dict):
        out.append(0xFA)      # START_OBJECT
        for k, item in v.items():
            _smile_write_key(str(k), out)
            _smile_write(item, out)
        out.append(0xFB)      # END_OBJECT
    else:
        raise IllegalArgumentError(
            f"cannot SMILE-encode [{type(v).__name__}]")


def _smile_write_key(key: str, out: bytearray) -> None:
    b = key.encode("utf-8")
    out.append(0x34)          # long (variable-length) unicode name
    out += b
    out.append(0xFC)


class _SmileReader:
    def __init__(self, raw: bytes):
        if not raw.startswith(_SMILE_MAGIC):
            raise IllegalArgumentError("not a SMILE document")
        self.raw = raw
        self.i = 4            # skip magic + flags byte
        self.flags = raw[3]
        self.shared_names: list = []

    def byte(self) -> int:
        b = self.raw[self.i]
        self.i += 1
        return b

    def read_vint(self) -> int:
        n = 0
        while True:
            b = self.byte()
            if b & 0x80:
                return (n << 6) | (b & 0x3F)
            n = (n << 7) | b

    def until_fc(self) -> bytes:
        start = self.i
        end = self.raw.index(b"\xfc", start)
        self.i = end + 1
        return self.raw[start:end]

    def read_value(self) -> Any:
        t = self.byte()
        if t == 0x21:
            return None
        if t == 0x22:
            return False
        if t == 0x23:
            return True
        if t in (0x24, 0x25):                   # int32 / int64 vint
            return _unzigzag(self.read_vint())
        if t == 0x28:                           # 32-bit float
            bits = 0
            for _ in range(5):
                bits = (bits << 7) | (self.byte() & 0x7F)
            return struct.unpack(">f", struct.pack(">I",
                                                   bits & 0xFFFFFFFF))[0]
        if t == 0x29:                           # 64-bit double
            bits = 0
            for _ in range(10):
                bits = (bits << 7) | (self.byte() & 0x7F)
            return struct.unpack(
                ">d", struct.pack(">Q", bits & (2 ** 64 - 1)))[0]
        if t == 0x20:
            return ""
        if 0x01 <= t <= 0x1F:                   # shared value refs: no
            raise IllegalArgumentError(
                "SMILE shared-value references are not supported")
        if 0x40 <= t <= 0x5F:                   # tiny ASCII (1..32 chars)
            n = (t & 0x1F) + 1
            s = self.raw[self.i : self.i + n].decode("utf-8")
            self.i += n
            return s
        if 0x60 <= t <= 0x7F:                   # small ASCII (33..64)
            n = (t & 0x1F) + 33
            s = self.raw[self.i : self.i + n].decode("utf-8")
            self.i += n
            return s
        if 0x80 <= t <= 0x9F:                   # tiny unicode (2..33)
            n = (t & 0x1F) + 2
            s = self.raw[self.i : self.i + n].decode("utf-8")
            self.i += n
            return s
        if 0xA0 <= t <= 0xBF:                   # short unicode (34..65)
            n = (t & 0x1F) + 34
            s = self.raw[self.i : self.i + n].decode("utf-8")
            self.i += n
            return s
        if t in (0xE0, 0xE4):                   # long ASCII/unicode
            return self.until_fc().decode("utf-8")
        if t == 0xE8:                           # safe binary (7-bit)
            n = self.read_vint()
            total_septets = (n * 8 + 6) // 7
            bits = 0
            nbits = 0
            out = bytearray()
            for _ in range(total_septets):
                bits = (bits << 7) | (self.byte() & 0x7F)
                nbits += 7
                if nbits >= 8:
                    out.append((bits >> (nbits - 8)) & 0xFF)
                    nbits -= 8
            return bytes(out[:n])
        if t == 0xF8:                           # START_ARRAY
            items = []
            while self.raw[self.i] != 0xF9:
                items.append(self.read_value())
            self.i += 1
            return items
        if t == 0xFA:                           # START_OBJECT
            obj = {}
            while self.raw[self.i] != 0xFB:
                key = self.read_key()
                obj[key] = self.read_value()
            self.i += 1
            return obj
        raise IllegalArgumentError(
            f"unsupported SMILE value token [0x{t:02x}]")

    def read_key(self) -> str:
        t = self.byte()
        if t == 0x20:
            return ""
        if 0x30 <= t <= 0x33:
            # LONG shared name ref: 2 bytes, 10-bit index
            # ((t & 0x3) << 8 | next) — indexes 64..1023
            idx = ((t & 0x03) << 8) | self.byte()
            return self.shared_names[idx]
        if 0x40 <= t <= 0x7F:                   # short shared ref (0..63)
            return self.shared_names[t - 0x40]
        if t == 0x34:                           # long unicode name
            name = self.until_fc().decode("utf-8")
            self._share(name)
            return name
        if 0x80 <= t <= 0xBF:                   # short ASCII name
            n = (t & 0x3F) + 1
            name = self.raw[self.i : self.i + n].decode("utf-8")
            self.i += n
            self._share(name)
            return name
        if 0xC0 <= t <= 0xF7:                   # short unicode name
            n = (t & 0x3F) + 2
            name = self.raw[self.i : self.i + n].decode("utf-8")
            self.i += n
            self._share(name)
            return name
        raise IllegalArgumentError(
            f"unsupported SMILE key token [0x{t:02x}]")

    def _share(self, name: str) -> None:
        if len(name.encode("utf-8")) <= 64:
            self.shared_names.append(name)


def _smile_decode(raw: bytes) -> Any:
    return _SmileReader(raw).read_value()
