"""Resource watcher: poll files for changes and notify listeners.

Reference: watcher/ResourceWatcherService.java — a scheduler-driven
polling service (no inotify dependency) that security's file realm and
other file-backed configs register with for hot reload.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

DEFAULT_INTERVAL = 5.0


class ResourceWatcherService:
    def __init__(self, scheduler, interval: float = DEFAULT_INTERVAL):
        self.scheduler = scheduler
        self.interval = interval
        # path -> (last (mtime, size) or None, callback)
        self._watched: Dict[str, Tuple[Optional[tuple], Callable]] = {}
        self._running = False
        self._timer = None

    def watch(self, path: str, on_change: Callable[[str], None]) -> None:
        """Register ``on_change(path)``, fired when the file's mtime/size
        changes, the file appears, or it disappears."""
        self._watched[path] = (self._stat(path), on_change)

    @staticmethod
    def _stat(path: str) -> Optional[tuple]:
        try:
            st = os.stat(path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def start(self) -> None:
        self._running = True
        self._schedule()

    def stop(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()

    def _schedule(self) -> None:
        if not self._running:
            return
        self._timer = self.scheduler.schedule(self.interval, self._tick)

    def _tick(self) -> None:
        if not self._running:
            return
        try:
            self.check_now()
        except Exception:  # noqa: BLE001 — the poll must survive anything
            logger.exception("resource watcher tick failed")
        self._schedule()

    def check_now(self) -> None:
        """One poll pass (public: tests and lazy callers step it)."""
        for path, (last, cb) in list(self._watched.items()):
            current = self._stat(path)
            if current != last:
                self._watched[path] = (current, cb)
                try:
                    cb(path)
                except Exception:  # noqa: BLE001
                    logger.exception("watch callback failed for %s", path)
