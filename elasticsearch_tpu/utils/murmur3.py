"""MurmurHash3 x86 32-bit.

The reference routes documents to shards with murmur3 over the routing key
(cluster/routing/OperationRouting.java:216-222, which delegates to
``Murmur3HashFunction``). We implement the same public algorithm so routing
behavior is stable and well distributed; we do NOT need bit-for-bit parity
with Java's UTF-16 hashing (this is a new framework), so we hash UTF-8 bytes.
"""

from __future__ import annotations

_MASK = 0xFFFFFFFF
_C1 = 0xCC9E2D51
_C2 = 0x1B873593


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3_x86_32 over ``data``; returns unsigned 32-bit int.

    Uses the native C++ implementation when built (native/fast.cpp);
    this pure-Python body is the fallback and the reference semantics."""
    from elasticsearch_tpu import native
    if native.available():
        h = native.murmur3_32(data, seed)
        if h is not None:
            return h
    h = seed & _MASK
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK

    tail = data[nblocks * 4 :]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k

    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


def hash_routing(routing_key: str) -> int:
    """Hash a routing key (usually the document _id) for shard routing."""
    return murmur3_32(routing_key.encode("utf-8"))


def shard_id_for(routing_key: str, num_shards: int, routing_partition_size: int = 1) -> int:
    """Map a routing key to a shard.

    Reference analog: OperationRouting.generateShardId
    (cluster/routing/OperationRouting.java:216-222) — murmur3(routing) % shards,
    with optional partition offset for routing_partition_size.
    """
    h = hash_routing(routing_key)
    if routing_partition_size > 1:
        # spread one routing value over a partition of shards
        offset = hash_routing(routing_key + "#partition") % routing_partition_size
        return (h + offset) % num_shards
    return h % num_shards
