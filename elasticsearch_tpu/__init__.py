"""elasticsearch_tpu — a TPU-native distributed search engine.

A from-scratch re-design of the capabilities of Elasticsearch
(reference: tonycrosby/elasticsearch @ 8.0.0-SNAPSHOT) for TPU hardware:

- Data plane: immutable, padded, device-resident segment arrays scored by
  JAX/XLA/Pallas kernels (BM25 with block-max pruning, dense-vector kNN,
  sparse rank-features, hybrid rank fusion) over a ``jax.sharding.Mesh``.
- Control plane: host-side Python (cluster state + Raft-like coordination,
  seqno replication, recovery, snapshots, REST API), mirroring the
  reference's layer map (see SURVEY.md §1) without porting its code.
"""

from elasticsearch_tpu.version import __version__

__all__ = ["__version__"]
