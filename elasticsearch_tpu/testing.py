"""In-process multi-node test cluster over the deterministic scheduler.

Reference analog: test/framework's InternalTestCluster.java:175 (N real
Node objects in one JVM with mock transports) fused with
AbstractCoordinatorTestCase.java:143 (virtual-time determinism). Every test
run is seed-reproducible; partitions/drops come from InMemoryTransport's
disruption rules (NetworkDisruption analog).
"""

from __future__ import annotations

import errno
import os
import random as _random
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.coordination import CoordinatorSettings, Mode
from elasticsearch_tpu.cluster.state import ClusterState
from elasticsearch_tpu.index.disk_io import FOOTER_SIZE, DiskIO
from elasticsearch_tpu.node.node import Node, NodeClient
from elasticsearch_tpu.transport.scheduler import DeterministicScheduler
from elasticsearch_tpu.transport.transport import InMemoryTransport


class FaultyDiskIO(DiskIO):
    """The disk fault injector: a DiskIO whose operations can be armed to
    fail or corrupt, plus at-rest corruption helpers for files already on
    disk. All randomness draws from the injected (seeded) RNG, so every
    fault interleaving is reproducible (MockDirectoryWrapper +
    CorruptionUtils analog of the reference test framework).

    Write-path faults (``arm``): 'eio' / 'enospc' raise OSError; 'bit_flip'
    flips one random bit of the payload; 'truncate' drops a random tail.
    Rules filter by path substring and operation (write/append/read), and
    can be limited to a fault count.
    """

    def __init__(self, rng: Optional[_random.Random] = None):
        self.random = rng or _random.Random(0)
        self.rules: List[Dict[str, Any]] = []
        self.stats = {"bit_flips": 0, "truncations": 0, "io_errors": 0}

    # -- armed (in-flight) faults ---------------------------------------

    def arm(self, kind: str, match: str = "", op: str = "*",
            count: Optional[int] = None) -> Dict[str, Any]:
        """Arm a fault rule; returns it (pass to disarm, or mutate
        ``rule['remaining']``). kind: eio|enospc|bit_flip|truncate."""
        assert kind in ("eio", "enospc", "bit_flip", "truncate"), kind
        rule = {"kind": kind, "match": match, "op": op, "remaining": count}
        self.rules.append(rule)
        return rule

    def disarm(self, rule: Optional[Dict[str, Any]] = None) -> None:
        if rule is None:
            self.rules.clear()
        elif rule in self.rules:
            self.rules.remove(rule)

    def _fault(self, op: str, path: Path, data: bytes) -> bytes:
        for rule in list(self.rules):
            if rule["remaining"] is not None and rule["remaining"] <= 0:
                continue
            if rule["op"] not in ("*", op):
                continue
            if rule["match"] and rule["match"] not in str(path):
                continue
            if rule["remaining"] is not None:
                rule["remaining"] -= 1
            kind = rule["kind"]
            if kind == "eio":
                self.stats["io_errors"] += 1
                raise OSError(errno.EIO,
                              f"injected I/O error on [{path.name}]")
            if kind == "enospc":
                self.stats["io_errors"] += 1
                raise OSError(errno.ENOSPC,
                              f"injected disk-full on [{path.name}]")
            if kind == "bit_flip" and data:
                data = self._flip_one_bit(data)
                self.stats["bit_flips"] += 1
            elif kind == "truncate" and data:
                data = data[: self.random.randrange(0, len(data))]
                self.stats["truncations"] += 1
        return data

    def _flip_one_bit(self, data: bytes) -> bytes:
        buf = bytearray(data)
        i = self.random.randrange(len(buf))
        buf[i] ^= 1 << self.random.randrange(8)
        return bytes(buf)

    # -- at-rest corruption ---------------------------------------------

    def corrupt_file(self, path: str | Path, skip_footer: bool = False
                     ) -> int:
        """Flip one random bit of a file in place (a cosmic ray / rotting
        sector). ``skip_footer=True`` keeps the flip inside the payload
        region so the test exercises payload CRC, not footer damage.
        Returns the flipped byte offset."""
        path = Path(path)
        data = bytearray(path.read_bytes())
        limit = len(data) - (FOOTER_SIZE if skip_footer else 0)
        i = self.random.randrange(limit)
        data[i] ^= 1 << self.random.randrange(8)
        path.write_bytes(bytes(data))
        return i

    def truncate_file(self, path: str | Path,
                      drop_bytes: Optional[int] = None) -> int:
        """Cut a random (or given) number of tail bytes off a file — a
        torn write that never completed. Returns bytes dropped."""
        path = Path(path)
        size = path.stat().st_size
        if drop_bytes is None:
            drop_bytes = self.random.randrange(1, max(size, 2))
        drop_bytes = min(drop_bytes, size)
        with open(path, "r+b") as f:
            f.truncate(size - drop_bytes)
        return drop_bytes


class InProcessCluster:
    def __init__(self, n_nodes: int = 3, seed: int = 0,
                 data_path: Optional[str] = None,
                 mesh_data_plane: bool = False):
        self.scheduler = DeterministicScheduler(seed=seed)
        self.transport = InMemoryTransport(self.scheduler)
        self.data_path = data_path
        # every shard Store/Translog on every node writes through this
        # seeded injector; quiescent (no armed rules) it is a plain DiskIO
        self.disk_io = FaultyDiskIO(_random.Random(seed ^ 0x5EED))
        node_ids = [f"node{i}" for i in range(n_nodes)]
        self._node_ids = node_ids
        self._mesh_data_plane = mesh_data_plane
        # bootstrap: the initial voting configuration is the full seed set
        # (ClusterBootstrapService analog)
        initial = ClusterState(voting_config=frozenset(node_ids))
        self._initial_state = initial
        self.nodes: Dict[str, Node] = {}
        for nid in node_ids:
            self.nodes[nid] = self._build_node(nid)

    def _build_node(self, nid: str) -> Node:
        return Node(
            nid, self.transport, self.scheduler,
            seed_peers=self._node_ids,
            data_path=(f"{self.data_path}/{nid}" if self.data_path
                       else None),
            initial_state=self._initial_state,
            coordinator_settings=CoordinatorSettings(),
            mesh_data_plane=self._mesh_data_plane,
            disk_io=self.disk_io)

    # ------------------------------------------------------------------

    def start(self, run_for: float = 60.0) -> None:
        for node in self.nodes.values():
            node.start()

        def formed() -> bool:
            master = self.master()
            return (master is not None and
                    len(master.coordinator.applied_state.nodes)
                    == len(self.nodes))
        self.run_until(formed, run_for)

    def stop(self) -> None:
        for node in self.nodes.values():
            node.stop()

    def master(self) -> Optional[Node]:
        leaders = [n for n in self.nodes.values()
                   if n.coordinator.mode == Mode.LEADER]
        return leaders[0] if len(leaders) == 1 else None

    def client(self, node_id: Optional[str] = None) -> NodeClient:
        if node_id is not None:
            return self.nodes[node_id].client
        return next(iter(self.nodes.values())).client

    # ------------------------------------------------------------------
    # deterministic drivers
    # ------------------------------------------------------------------

    def run_until(self, predicate: Callable[[], bool],
                  max_time: float = 60.0) -> None:
        deadline = self.scheduler.now() + max_time
        while not predicate():
            if self.scheduler.now() > deadline or \
                    not self.scheduler.run_one():
                if predicate():
                    return
                raise TimeoutError(
                    f"condition not reached after {max_time}s virtual time")

    def call(self, fn: Callable[[Callable], None], max_time: float = 60.0
             ) -> Tuple[Optional[Dict[str, Any]], Optional[Exception]]:
        """Drive an async client call to completion: fn(on_done) -> (resp, err)."""
        box: List[Tuple[Any, Any]] = []
        fn(lambda resp, err=None: box.append((resp, err)))
        self.run_until(lambda: bool(box), max_time)
        return box[0]

    def ensure_green(self, index: Optional[str] = None,
                     max_time: float = 120.0) -> None:
        self._ensure_status(("green",), index, max_time)

    def ensure_yellow(self, index: Optional[str] = None,
                      max_time: float = 120.0) -> None:
        self._ensure_status(("yellow", "green"), index, max_time)

    def _ensure_status(self, ok, index, max_time) -> None:
        def ready() -> bool:
            master = self.master()
            if master is None:
                return False
            if master.client.cluster_health(index)["status"] not in ok:
                return False
            # every node still in the master's view must have APPLIED the
            # state it's judged by — clients read their local node's applied
            # state. Nodes the master has dropped (or that are partitioned
            # away, hence absent from its membership) can never catch up and
            # must not hold green/yellow hostage.
            version = master.coordinator.applied_state.version
            members = master.coordinator.applied_state.nodes
            return all(n.coordinator.applied_state.version >= version
                       for n in self.nodes.values()
                       if n.node_id in members)
        self.run_until(ready, max_time)

    def await_node_count(self, n: int, max_time: float = 300.0) -> None:
        """Wait until the master's committed membership has exactly n nodes
        (failure detection takes a few heartbeat rounds of virtual time)."""
        def counted() -> bool:
            master = self.master()
            return (master is not None and
                    len(master.coordinator.applied_state.nodes) == n)
        self.run_until(counted, max_time)

    # ------------------------------------------------------------------
    # disruption helpers
    # ------------------------------------------------------------------

    def kill_node(self, node_id: str) -> None:
        """Hard-stop a node (die-with-dignity analog: it just vanishes)."""
        node = self.nodes.pop(node_id)
        node.stop()

    def crash_node(self, node_id: str) -> None:
        """Crash without cleanup: the node drops off the wire (senders get
        connection-refused) but keeps its in-memory state for
        restart_node() — a process crash/restart or a long GC-style pause."""
        self.transport.crash(node_id)

    def restart_node(self, node_id: str) -> None:
        self.transport.restore(node_id)

    def reboot_node(self, node_id: str) -> None:
        """Full process restart: stop the node (in-memory state lost) and
        boot a fresh Node over the same data path — cluster metadata comes
        back through the gateway, shard data through store/translog
        recovery (where integrity checks run)."""
        node = self.nodes.pop(node_id)
        node.stop()
        fresh = self._build_node(node_id)
        self.nodes[node_id] = fresh
        fresh.start()

    def full_restart(self, run_for: float = 60.0) -> None:
        """Stop EVERY node, then boot fresh processes over the same data
        paths — the full-cluster-restart scenario the gateway allocator
        exists for: metadata returns through each node's persisted state,
        routing is re-derived by the shard-state fetch, and every copy
        with fresh local data recovers in place."""
        for node in self.nodes.values():
            node.stop()
        self.nodes.clear()
        for nid in self._node_ids:
            self.nodes[nid] = self._build_node(nid)
        self.start(run_for=run_for)

    def shard_store_path(self, node_id: str, index: str, shard: int
                         ) -> Optional[str]:
        """This node's on-disk store directory for one shard copy (the
        chaos suite corrupts files under it)."""
        if self.data_path is None:
            return None
        node = self.nodes[node_id]
        service = node.indices_service.indices.get(index)
        if service is None:
            return None
        return os.path.join(f"{self.data_path}/{node_id}",
                            service.metadata.uuid, str(shard))

    def partition(self, side_a: List[str], side_b: List[str],
                  style: str = "blackhole") -> None:
        self.transport.partition(side_a, side_b, style=style)

    def partition_one_way(self, from_side: List[str], to_side: List[str],
                          style: str = "blackhole") -> None:
        """Asymmetric partition: from_side -> to_side traffic disrupted,
        reverse direction intact."""
        self.transport.partition_one_way(from_side, to_side, style=style)

    def add_latency(self, sender: str, receiver: str, delay: float,
                    jitter: float = 0.0) -> None:
        """Inject fixed + jittered latency on one directed link (jitter
        draws from the seeded scheduler RNG: reproducible chaos)."""
        self.transport.add_rule(sender, receiver, delay=delay,
                                jitter=jitter)

    def slow_node_drains(self, node_id: str, delay_s: float) -> None:
        """Overload chaos seam: every shard-query drain on ``node_id``
        delivers ``delay_s`` later in virtual time AND reports the delay
        in its self-reported service time — a saturated/slow data node
        (GC pauses, noisy neighbor, thermal throttling) that a wire-level
        latency rule cannot model, because the node itself knows it is
        slow and says so in its pressure piggyback. 0 heals."""
        batcher = self.nodes[node_id].search_transport.batcher
        batcher.fault_drain_delay_s = float(delay_s)

    def constrain_search_admission(self, size: int, queue: int) -> None:
        """Shrink every node's search admission pool (slots + a FIXED
        queue bound) so overload scenarios reach saturation at test
        scale. Direct pool mutation — the dynamic search.admission.*
        settings are deliberately not written, so the admission
        refresh leaves these values alone."""
        for node in self.nodes.values():
            pool = node.thread_pool.pool("search")
            pool.size = int(size)
            pool.queue_size = int(queue)
            pool.min_queue = int(queue)
            pool.max_queue = int(queue)

    def heal(self) -> None:
        self.transport.heal()
