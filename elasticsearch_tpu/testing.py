"""In-process multi-node test cluster over the deterministic scheduler.

Reference analog: test/framework's InternalTestCluster.java:175 (N real
Node objects in one JVM with mock transports) fused with
AbstractCoordinatorTestCase.java:143 (virtual-time determinism). Every test
run is seed-reproducible; partitions/drops come from InMemoryTransport's
disruption rules (NetworkDisruption analog).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.coordination import CoordinatorSettings, Mode
from elasticsearch_tpu.cluster.state import ClusterState
from elasticsearch_tpu.node.node import Node, NodeClient
from elasticsearch_tpu.transport.scheduler import DeterministicScheduler
from elasticsearch_tpu.transport.transport import InMemoryTransport


class InProcessCluster:
    def __init__(self, n_nodes: int = 3, seed: int = 0,
                 data_path: Optional[str] = None,
                 mesh_data_plane: bool = False):
        self.scheduler = DeterministicScheduler(seed=seed)
        self.transport = InMemoryTransport(self.scheduler)
        self.data_path = data_path
        node_ids = [f"node{i}" for i in range(n_nodes)]
        # bootstrap: the initial voting configuration is the full seed set
        # (ClusterBootstrapService analog)
        initial = ClusterState(voting_config=frozenset(node_ids))
        self.nodes: Dict[str, Node] = {}
        for nid in node_ids:
            self.nodes[nid] = Node(
                nid, self.transport, self.scheduler,
                seed_peers=node_ids,
                data_path=(f"{data_path}/{nid}" if data_path else None),
                initial_state=initial,
                coordinator_settings=CoordinatorSettings(),
                mesh_data_plane=mesh_data_plane)

    # ------------------------------------------------------------------

    def start(self, run_for: float = 60.0) -> None:
        for node in self.nodes.values():
            node.start()

        def formed() -> bool:
            master = self.master()
            return (master is not None and
                    len(master.coordinator.applied_state.nodes)
                    == len(self.nodes))
        self.run_until(formed, run_for)

    def stop(self) -> None:
        for node in self.nodes.values():
            node.stop()

    def master(self) -> Optional[Node]:
        leaders = [n for n in self.nodes.values()
                   if n.coordinator.mode == Mode.LEADER]
        return leaders[0] if len(leaders) == 1 else None

    def client(self, node_id: Optional[str] = None) -> NodeClient:
        if node_id is not None:
            return self.nodes[node_id].client
        return next(iter(self.nodes.values())).client

    # ------------------------------------------------------------------
    # deterministic drivers
    # ------------------------------------------------------------------

    def run_until(self, predicate: Callable[[], bool],
                  max_time: float = 60.0) -> None:
        deadline = self.scheduler.now() + max_time
        while not predicate():
            if self.scheduler.now() > deadline or \
                    not self.scheduler.run_one():
                if predicate():
                    return
                raise TimeoutError(
                    f"condition not reached after {max_time}s virtual time")

    def call(self, fn: Callable[[Callable], None], max_time: float = 60.0
             ) -> Tuple[Optional[Dict[str, Any]], Optional[Exception]]:
        """Drive an async client call to completion: fn(on_done) -> (resp, err)."""
        box: List[Tuple[Any, Any]] = []
        fn(lambda resp, err=None: box.append((resp, err)))
        self.run_until(lambda: bool(box), max_time)
        return box[0]

    def ensure_green(self, index: Optional[str] = None,
                     max_time: float = 120.0) -> None:
        self._ensure_status(("green",), index, max_time)

    def ensure_yellow(self, index: Optional[str] = None,
                      max_time: float = 120.0) -> None:
        self._ensure_status(("yellow", "green"), index, max_time)

    def _ensure_status(self, ok, index, max_time) -> None:
        def ready() -> bool:
            master = self.master()
            if master is None:
                return False
            if master.client.cluster_health(index)["status"] not in ok:
                return False
            # every node still in the master's view must have APPLIED the
            # state it's judged by — clients read their local node's applied
            # state. Nodes the master has dropped (or that are partitioned
            # away, hence absent from its membership) can never catch up and
            # must not hold green/yellow hostage.
            version = master.coordinator.applied_state.version
            members = master.coordinator.applied_state.nodes
            return all(n.coordinator.applied_state.version >= version
                       for n in self.nodes.values()
                       if n.node_id in members)
        self.run_until(ready, max_time)

    def await_node_count(self, n: int, max_time: float = 300.0) -> None:
        """Wait until the master's committed membership has exactly n nodes
        (failure detection takes a few heartbeat rounds of virtual time)."""
        def counted() -> bool:
            master = self.master()
            return (master is not None and
                    len(master.coordinator.applied_state.nodes) == n)
        self.run_until(counted, max_time)

    # ------------------------------------------------------------------
    # disruption helpers
    # ------------------------------------------------------------------

    def kill_node(self, node_id: str) -> None:
        """Hard-stop a node (die-with-dignity analog: it just vanishes)."""
        node = self.nodes.pop(node_id)
        node.stop()

    def crash_node(self, node_id: str) -> None:
        """Crash without cleanup: the node drops off the wire (senders get
        connection-refused) but keeps its in-memory state for
        restart_node() — a process crash/restart or a long GC-style pause."""
        self.transport.crash(node_id)

    def restart_node(self, node_id: str) -> None:
        self.transport.restore(node_id)

    def partition(self, side_a: List[str], side_b: List[str],
                  style: str = "blackhole") -> None:
        self.transport.partition(side_a, side_b, style=style)

    def partition_one_way(self, from_side: List[str], to_side: List[str],
                          style: str = "blackhole") -> None:
        """Asymmetric partition: from_side -> to_side traffic disrupted,
        reverse direction intact."""
        self.transport.partition_one_way(from_side, to_side, style=style)

    def add_latency(self, sender: str, receiver: str, delay: float,
                    jitter: float = 0.0) -> None:
        """Inject fixed + jittered latency on one directed link (jitter
        draws from the seeded scheduler RNG: reproducible chaos)."""
        self.transport.add_rule(sender, receiver, delay=delay,
                                jitter=jitter)

    def heal(self) -> None:
        self.transport.heal()
