"""In-process multi-node test cluster over the deterministic scheduler.

Reference analog: test/framework's InternalTestCluster.java:175 (N real
Node objects in one JVM with mock transports) fused with
AbstractCoordinatorTestCase.java:143 (virtual-time determinism). Every test
run is seed-reproducible; partitions/drops come from InMemoryTransport's
disruption rules (NetworkDisruption analog).
"""

from __future__ import annotations

import errno
import os
import random as _random
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.coordination import CoordinatorSettings, Mode
from elasticsearch_tpu.cluster.state import ClusterState
from elasticsearch_tpu.index.disk_io import FOOTER_SIZE, DiskIO
from elasticsearch_tpu.node.node import Node, NodeClient
from elasticsearch_tpu.transport.scheduler import DeterministicScheduler
from elasticsearch_tpu.transport.transport import InMemoryTransport


class FaultyDiskIO(DiskIO):
    """The disk fault injector: a DiskIO whose operations can be armed to
    fail or corrupt, plus at-rest corruption helpers for files already on
    disk. All randomness draws from the injected (seeded) RNG, so every
    fault interleaving is reproducible (MockDirectoryWrapper +
    CorruptionUtils analog of the reference test framework).

    Write-path faults (``arm``): 'eio' / 'enospc' raise OSError; 'bit_flip'
    flips one random bit of the payload; 'truncate' drops a random tail;
    'slow' charges ``delay_s`` of virtual time per matched operation (a
    degraded disk: the op still succeeds, it just takes forever — the
    brownout that backpressure exists for, not the crash that recovery
    exists for). Rules filter by path substring and operation
    (write/append/read), and can be limited to a fault count.
    """

    def __init__(self, rng: Optional[_random.Random] = None):
        self.random = rng or _random.Random(0)
        self.rules: List[Dict[str, Any]] = []
        self.stats = {"bit_flips": 0, "truncations": 0, "io_errors": 0,
                      "slow_ops": 0}
        # virtual-clock seam for 'slow' rules: InProcessCluster wires
        # this to advance the deterministic scheduler's clock, so disk
        # latency is charged INSIDE synchronous write handlers (there is
        # no real sleeping under virtual time)
        self.clock_advance: Optional[Callable[[float], None]] = None

    # -- armed (in-flight) faults ---------------------------------------

    def arm(self, kind: str, match: str = "", op: str = "*",
            count: Optional[int] = None,
            delay_s: float = 0.05) -> Dict[str, Any]:
        """Arm a fault rule; returns it (pass to disarm, or mutate
        ``rule['remaining']``). kind: eio|enospc|bit_flip|truncate|slow;
        ``delay_s`` is the per-operation latency charge for 'slow'."""
        assert kind in ("eio", "enospc", "bit_flip", "truncate",
                        "slow"), kind
        rule = {"kind": kind, "match": match, "op": op, "remaining": count,
                "delay_s": delay_s}
        self.rules.append(rule)
        return rule

    def disarm(self, rule: Optional[Dict[str, Any]] = None) -> None:
        if rule is None:
            self.rules.clear()
        elif rule in self.rules:
            self.rules.remove(rule)

    def _fault(self, op: str, path: Path, data: bytes) -> bytes:
        for rule in list(self.rules):
            if rule["remaining"] is not None and rule["remaining"] <= 0:
                continue
            if rule["op"] not in ("*", op):
                continue
            if rule["match"] and rule["match"] not in str(path):
                continue
            if rule["remaining"] is not None:
                rule["remaining"] -= 1
            kind = rule["kind"]
            if kind == "eio":
                self.stats["io_errors"] += 1
                raise OSError(errno.EIO,
                              f"injected I/O error on [{path.name}]")
            if kind == "enospc":
                self.stats["io_errors"] += 1
                raise OSError(errno.ENOSPC,
                              f"injected disk-full on [{path.name}]")
            if kind == "slow":
                self.stats["slow_ops"] += 1
                if self.clock_advance is not None:
                    self.clock_advance(rule["delay_s"])
            elif kind == "bit_flip" and data:
                data = self._flip_one_bit(data)
                self.stats["bit_flips"] += 1
            elif kind == "truncate" and data:
                data = data[: self.random.randrange(0, len(data))]
                self.stats["truncations"] += 1
        return data

    def _flip_one_bit(self, data: bytes) -> bytes:
        buf = bytearray(data)
        i = self.random.randrange(len(buf))
        buf[i] ^= 1 << self.random.randrange(8)
        return bytes(buf)

    # -- at-rest corruption ---------------------------------------------

    def corrupt_file(self, path: str | Path, skip_footer: bool = False
                     ) -> int:
        """Flip one random bit of a file in place (a cosmic ray / rotting
        sector). ``skip_footer=True`` keeps the flip inside the payload
        region so the test exercises payload CRC, not footer damage.
        Returns the flipped byte offset."""
        path = Path(path)
        data = bytearray(path.read_bytes())
        limit = len(data) - (FOOTER_SIZE if skip_footer else 0)
        i = self.random.randrange(limit)
        data[i] ^= 1 << self.random.randrange(8)
        path.write_bytes(bytes(data))
        return i

    def truncate_file(self, path: str | Path,
                      drop_bytes: Optional[int] = None) -> int:
        """Cut a random (or given) number of tail bytes off a file — a
        torn write that never completed. Returns bytes dropped."""
        path = Path(path)
        size = path.stat().st_size
        if drop_bytes is None:
            drop_bytes = self.random.randrange(1, max(size, 2))
        drop_bytes = min(drop_bytes, size)
        with open(path, "r+b") as f:
            f.truncate(size - drop_bytes)
        return drop_bytes


class VirtualHostBackend:
    """Host-partitioned stand-in for real multi-process JAX: the test
    process's devices split into virtual HOSTS (parallel/mesh.py
    ``HostTopology``), cluster nodes map onto those hosts round-robin,
    and the mesh executor reaches a member host's shards through this
    backend exactly where a real multi-host SPMD program's participant
    would address its own. Liveness is derived, not declared: a node is
    alive while it is in ``cluster.nodes`` and on the wire, and a
    virtual host is alive while EVERY node mapped to it is — so
    ``crash_node``/``kill_node`` take the victim's host down and
    ``restart_node``/``reboot_node`` bring it back, with no extra
    bookkeeping for tests to forget."""

    def __init__(self, cluster: "InProcessCluster", topology):
        self.cluster = cluster
        self.topology = topology
        self._hosts: Dict[str, int] = {
            nid: i % topology.n_hosts
            for i, nid in enumerate(cluster._node_ids)}

    def _node_alive(self, node_id: str) -> bool:
        return node_id in self.cluster.nodes and \
            node_id not in self.cluster.transport._crashed

    def host_of_node(self, node_id: str) -> Optional[int]:
        return self._hosts.get(node_id)

    def host_alive(self, host: int) -> bool:
        nodes = [nid for nid, h in self._hosts.items() if h == host]
        return bool(nodes) and all(self._node_alive(n) for n in nodes)

    def nodes_on_host(self, host: int) -> List[str]:
        return [nid for nid, h in self._hosts.items() if h == host]

    def indices_of(self, node_id: str):
        if not self._node_alive(node_id):
            return None
        return self.cluster.nodes[node_id].indices_service

    def pressure_snapshot(self, node_id: str):
        if not self._node_alive(node_id):
            return None
        batcher = self.cluster.nodes[node_id].search_transport.batcher
        return batcher.node_pressure.snapshot(batcher.queue_depth())


class InProcessCluster:
    def __init__(self, n_nodes: int = 3, seed: int = 0,
                 data_path: Optional[str] = None,
                 mesh_data_plane: bool = False,
                 mesh_hosts: Optional[str] = None):
        self.scheduler = DeterministicScheduler(seed=seed)
        self.transport = InMemoryTransport(self.scheduler)
        self.data_path = data_path
        # every shard Store/Translog on every node writes through this
        # seeded injector; quiescent (no armed rules) it is a plain DiskIO
        self.disk_io = FaultyDiskIO(_random.Random(seed ^ 0x5EED))

        def _advance(d: float) -> None:
            # safe mid-task: run_one resumes from max(self._time, t), so
            # a synchronous advance just means everything already queued
            # before now+d fires "immediately" after the slow op returns
            self.scheduler._time += d
        self.disk_io.clock_advance = _advance
        node_ids = [f"node{i}" for i in range(n_nodes)]
        self._node_ids = node_ids
        self._mesh_data_plane = mesh_data_plane
        # bootstrap: the initial voting configuration is the full seed set
        # (ClusterBootstrapService analog)
        initial = ClusterState(voting_config=frozenset(node_ids))
        self._initial_state = initial
        self.nodes: Dict[str, Node] = {}
        for nid in node_ids:
            self.nodes[nid] = self._build_node(nid)
        # virtual multi-host mesh: partition this process's devices into
        # ``mesh_hosts`` hosts ("N" or "NxM") and register the backend
        # the mesh executor routes cross-host fan-outs through
        self.host_backend: Optional[VirtualHostBackend] = None
        if mesh_hosts:
            from elasticsearch_tpu.parallel.mesh import (
                parse_host_topology, set_host_backend,
            )
            topo = parse_host_topology(mesh_hosts)
            if topo is not None:
                self.host_backend = VirtualHostBackend(self, topo)
                set_host_backend(self.host_backend)

    def _build_node(self, nid: str) -> Node:
        return Node(
            nid, self.transport, self.scheduler,
            seed_peers=self._node_ids,
            data_path=(f"{self.data_path}/{nid}" if self.data_path
                       else None),
            initial_state=self._initial_state,
            coordinator_settings=CoordinatorSettings(),
            mesh_data_plane=self._mesh_data_plane,
            disk_io=self.disk_io)

    # ------------------------------------------------------------------

    def start(self, run_for: float = 60.0) -> None:
        for node in self.nodes.values():
            node.start()

        def formed() -> bool:
            master = self.master()
            return (master is not None and
                    len(master.coordinator.applied_state.nodes)
                    == len(self.nodes))
        self.run_until(formed, run_for)

    def stop(self) -> None:
        for node in self.nodes.values():
            node.stop()
        if self.host_backend is not None:
            from elasticsearch_tpu.parallel.mesh import (
                host_backend, set_host_backend,
            )
            if host_backend() is self.host_backend:
                set_host_backend(None)
            self.host_backend = None

    def master(self) -> Optional[Node]:
        leaders = [n for n in self.nodes.values()
                   if n.coordinator.mode == Mode.LEADER]
        return leaders[0] if len(leaders) == 1 else None

    def client(self, node_id: Optional[str] = None) -> NodeClient:
        if node_id is not None:
            return self.nodes[node_id].client
        return next(iter(self.nodes.values())).client

    # ------------------------------------------------------------------
    # deterministic drivers
    # ------------------------------------------------------------------

    def run_until(self, predicate: Callable[[], bool],
                  max_time: float = 60.0) -> None:
        deadline = self.scheduler.now() + max_time
        while not predicate():
            if self.scheduler.now() > deadline or \
                    not self.scheduler.run_one():
                if predicate():
                    return
                raise TimeoutError(
                    f"condition not reached after {max_time}s virtual time")

    def call(self, fn: Callable[[Callable], None], max_time: float = 60.0
             ) -> Tuple[Optional[Dict[str, Any]], Optional[Exception]]:
        """Drive an async client call to completion: fn(on_done) -> (resp, err)."""
        box: List[Tuple[Any, Any]] = []
        fn(lambda resp, err=None: box.append((resp, err)))
        self.run_until(lambda: bool(box), max_time)
        return box[0]

    def ensure_green(self, index: Optional[str] = None,
                     max_time: float = 120.0) -> None:
        self._ensure_status(("green",), index, max_time)

    def ensure_yellow(self, index: Optional[str] = None,
                      max_time: float = 120.0) -> None:
        self._ensure_status(("yellow", "green"), index, max_time)

    def _ensure_status(self, ok, index, max_time) -> None:
        def ready() -> bool:
            master = self.master()
            if master is None:
                return False
            if master.client.cluster_health(index)["status"] not in ok:
                return False
            # every node still in the master's view must have APPLIED the
            # state it's judged by — clients read their local node's applied
            # state. Nodes the master has dropped (or that are partitioned
            # away, hence absent from its membership) can never catch up and
            # must not hold green/yellow hostage.
            version = master.coordinator.applied_state.version
            members = master.coordinator.applied_state.nodes
            return all(n.coordinator.applied_state.version >= version
                       for n in self.nodes.values()
                       if n.node_id in members)
        self.run_until(ready, max_time)

    def await_node_count(self, n: int, max_time: float = 300.0) -> None:
        """Wait until the master's committed membership has exactly n nodes
        (failure detection takes a few heartbeat rounds of virtual time)."""
        def counted() -> bool:
            master = self.master()
            return (master is not None and
                    len(master.coordinator.applied_state.nodes) == n)
        self.run_until(counted, max_time)

    # ------------------------------------------------------------------
    # disruption helpers
    # ------------------------------------------------------------------

    def kill_node(self, node_id: str) -> None:
        """Hard-stop a node (die-with-dignity analog: it just vanishes)."""
        node = self.nodes.pop(node_id)
        node.stop()

    def crash_node(self, node_id: str) -> None:
        """Crash without cleanup: the node drops off the wire (senders get
        connection-refused) but keeps its in-memory state for
        restart_node() — a process crash/restart or a long GC-style pause."""
        self.transport.crash(node_id)

    def restart_node(self, node_id: str) -> None:
        self.transport.restore(node_id)

    def reboot_node(self, node_id: str) -> None:
        """Full process restart: stop the node (in-memory state lost) and
        boot a fresh Node over the same data path — cluster metadata comes
        back through the gateway, shard data through store/translog
        recovery (where integrity checks run)."""
        node = self.nodes.pop(node_id)
        node.stop()
        fresh = self._build_node(node_id)
        self.nodes[node_id] = fresh
        fresh.start()

    def full_restart(self, run_for: float = 60.0) -> None:
        """Stop EVERY node, then boot fresh processes over the same data
        paths — the full-cluster-restart scenario the gateway allocator
        exists for: metadata returns through each node's persisted state,
        routing is re-derived by the shard-state fetch, and every copy
        with fresh local data recovers in place."""
        for node in self.nodes.values():
            node.stop()
        self.nodes.clear()
        for nid in self._node_ids:
            self.nodes[nid] = self._build_node(nid)
        self.start(run_for=run_for)

    def shard_store_path(self, node_id: str, index: str, shard: int
                         ) -> Optional[str]:
        """This node's on-disk store directory for one shard copy (the
        chaos suite corrupts files under it)."""
        if self.data_path is None:
            return None
        node = self.nodes[node_id]
        service = node.indices_service.indices.get(index)
        if service is None:
            return None
        return os.path.join(f"{self.data_path}/{node_id}",
                            service.metadata.uuid, str(shard))

    def partition(self, side_a: List[str], side_b: List[str],
                  style: str = "blackhole") -> None:
        self.transport.partition(side_a, side_b, style=style)

    def partition_one_way(self, from_side: List[str], to_side: List[str],
                          style: str = "blackhole") -> None:
        """Asymmetric partition: from_side -> to_side traffic disrupted,
        reverse direction intact."""
        self.transport.partition_one_way(from_side, to_side, style=style)

    def add_latency(self, sender: str, receiver: str, delay: float,
                    jitter: float = 0.0) -> None:
        """Inject fixed + jittered latency on one directed link (jitter
        draws from the seeded scheduler RNG: reproducible chaos)."""
        self.transport.add_rule(sender, receiver, delay=delay,
                                jitter=jitter)

    def slow_node_drains(self, node_id: str, delay_s: float) -> None:
        """Overload chaos seam: every shard-query drain on ``node_id``
        delivers ``delay_s`` later in virtual time AND reports the delay
        in its self-reported service time — a saturated/slow data node
        (GC pauses, noisy neighbor, thermal throttling) that a wire-level
        latency rule cannot model, because the node itself knows it is
        slow and says so in its pressure piggyback. 0 heals."""
        batcher = self.nodes[node_id].search_transport.batcher
        batcher.fault_drain_delay_s = float(delay_s)

    def constrain_search_admission(self, size: int, queue: int) -> None:
        """Shrink every node's search admission pool (slots + a FIXED
        queue bound) so overload scenarios reach saturation at test
        scale. Direct pool mutation — the dynamic search.admission.*
        settings are deliberately not written, so the admission
        refresh leaves these values alone."""
        for node in self.nodes.values():
            pool = node.thread_pool.pool("search")
            pool.size = int(size)
            pool.queue_size = int(queue)
            pool.min_queue = int(queue)
            pool.max_queue = int(queue)

    def heal(self) -> None:
        self.transport.heal()


# ---------------------------------------------------------------------------
# fleet-scale overload traffic harness (ROADMAP item 6)
# ---------------------------------------------------------------------------

def _p99(lats: List[float]) -> float:
    """Nearest-rank p99 — the one percentile formula the harness summary
    and the scenario's unloaded baseline share."""
    if not lats:
        return 0.0
    data = sorted(lats)
    return data[int(0.99 * (len(data) - 1))]


class FleetTrafficHarness:
    """Multi-coordinator, multi-tenant traffic over an InProcessCluster —
    the closest thing to "millions of users" a test process can express,
    in fully deterministic virtual time:

    - **diurnal load curve**: arrivals follow a seeded nonhomogeneous
      Poisson process (Lewis-Shedler thinning) whose rate traces
      ``0.35 + 0.65·sin²(π·t/period)`` — two troughs, two peaks per run;
    - **zipfian tenants**: each arrival picks its tenant (index) with
      1/rank weights, so a hot head and a long tail coexist — and a
      configured hot tenant gets a 10:1 flood multiplier inside the peak
      window (the overload plane's canonical adversary);
    - **multi-coordinator**: each arrival enters through a seeded choice
      of coordinator node, so every coordinator's admission pool, ARS
      view, and busy-failover loop is exercised against the SAME data
      nodes — the N-coordinators × M-tenants fan-in no single
      coordinator-side bound can see;
    - **chaos events**: arbitrary ``(t, fn)`` callbacks scheduled into
      the run (rolling restarts via crash/restart, slow nodes via
      ``slow_node_drains``, bounds via settings, ...).

    Every request is recorded (tenant, coordinator, latency, outcome);
    ``summary()`` reduces the record stream to the fleet invariants the
    chaos suite and bench assert: bounded admitted p99, clean 429s with
    honest Retry-After, zero starved tenants, shed/failover accounting.
    """

    def __init__(self, cluster: InProcessCluster,
                 tenants: List[str], coordinators: List[str],
                 seed: int = 0):
        self.c = cluster
        self.tenants = list(tenants)
        self.coordinators = list(coordinators)
        self.random = _random.Random(seed ^ 0xF1EE7)
        self.records: List[Dict[str, Any]] = []
        self._expected = {"n": 0}

    # -- traffic ---------------------------------------------------------

    def _arrivals(self, duration_s: float, total: int,
                  hot_tenant: Optional[str], hot_window: Tuple[float, float],
                  hot_factor: float) -> List[Tuple[float, str, str]]:
        """The seeded arrival plan: (t, tenant, coordinator) tuples.
        Lewis-Shedler thinning against the diurnal shape; zipfian tenant
        choice with the hot multiplier inside the window; plus a floor
        of three scheduled arrivals per tenant so starvation is always
        measurable (a tenant that never arrived cannot be starved)."""
        import math
        period = duration_s / 2.0

        def shape(t: float) -> float:
            return 0.35 + 0.65 * math.sin(math.pi * t / period) ** 2

        # mean of shape over the run is 0.675: pick λ_max to land near
        # `total` accepted arrivals
        lam_max = total / (0.675 * duration_s)
        weights = [1.0 / (rank + 1) for rank in range(len(self.tenants))]
        plan: List[Tuple[float, str, str]] = []
        t = 0.0
        while len(plan) < total:
            t += self.random.expovariate(lam_max)
            if t >= duration_s:
                break
            if self.random.random() > shape(t):
                continue
            w = list(weights)
            if hot_tenant in self.tenants and \
                    hot_window[0] <= t <= hot_window[1]:
                w[self.tenants.index(hot_tenant)] *= hot_factor
            tenant = self.random.choices(self.tenants, weights=w)[0]
            coord = self.random.choice(self.coordinators)
            plan.append((t, tenant, coord))
        # starvation floor: every tenant arrives at least 3 times, spread
        # through the run (outside nothing — they compete like anyone)
        for tenant in self.tenants:
            for frac in (0.2, 0.55, 0.85):
                coord = self.random.choice(self.coordinators)
                plan.append((duration_s * frac, tenant, coord))
        plan.sort(key=lambda e: e[0])
        return plan

    def submit_one(self, tenant: str, coord: str, body: Dict[str, Any]
                   ) -> None:
        sched = self.c.scheduler
        record = {"tenant": tenant, "coord": coord, "t0": sched.now(),
                  "t1": None, "err": None}
        self.records.append(record)

        def done(resp, err=None):
            record["t1"] = sched.now()
            record["err"] = err
            record["resp"] = resp
        self.c.nodes[coord].client.search(tenant, body, done)

    def run(self, duration_s: float, total: int, *,
            hot_tenant: Optional[str] = None,
            hot_window: Optional[Tuple[float, float]] = None,
            hot_factor: float = 10.0,
            events: Optional[List[Tuple[float, Callable[[], None]]]] = None,
            body_fn: Optional[Callable[[str], Dict[str, Any]]] = None,
            max_time: float = 3600.0) -> None:
        """Schedule the whole plan plus chaos events, then drive virtual
        time until every submitted search has resolved."""
        sched = self.c.scheduler
        hot_window = hot_window or (0.45 * duration_s, 0.7 * duration_s)
        plan = self._arrivals(duration_s, total, hot_tenant, hot_window,
                              hot_factor)
        self._expected["n"] += len(plan)

        def make_body(tenant: str) -> Dict[str, Any]:
            if body_fn is not None:
                return body_fn(tenant)
            return {"query": {"match": {
                "body": f"common w{self.random.randrange(8)}"}},
                "size": 5}

        for t, tenant, coord in plan:
            sched.schedule(t, lambda tn=tenant, co=coord:
                           self.submit_one(tn, co, make_body(tn)))
        for t, fn in (events or []):
            sched.schedule(t, fn)
        self.c.run_until(
            lambda: len(self.records) >= self._expected["n"] and
            all(r["t1"] is not None for r in self.records), max_time)

    # -- reduction -------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        from elasticsearch_tpu.utils.errors import shard_busy_info

        admitted = [r for r in self.records if r["err"] is None]
        rejected = [r for r in self.records if r["err"] is not None]
        clean = []
        busy_failures = 0
        for r in rejected:
            err = r["err"]
            status = getattr(err, "status", 500)
            meta = getattr(err, "metadata", None) or {}
            if status == 429 and int(meta.get("retry_after", 0)) >= 1:
                clean.append(r)
            if shard_busy_info(err) is not None or \
                    "shard_busy" in str(err):
                busy_failures += 1
        goodput: Dict[str, int] = {t: 0 for t in self.tenants}
        for r in admitted:
            goodput[r["tenant"]] = goodput.get(r["tenant"], 0) + 1
        partial = sum(
            1 for r in admitted
            if (r.get("resp") or {}).get("_shards", {}).get("failed", 0))
        return {
            "offered": len(self.records),
            "admitted": len(admitted),
            "admitted_p99_s": _p99([r["t1"] - r["t0"]
                                    for r in admitted]),
            "rejected": len(rejected),
            "rejected_clean_429": len(clean),
            "unclean_rejections": len(rejected) - len(clean),
            "request_busy_failures": busy_failures,
            "partial_responses": partial,
            "goodput_by_tenant": goodput,
            "starved_tenants": [t for t, n in goodput.items() if n == 0],
        }


def _fleet_cache_hits(c: "InProcessCluster") -> int:
    """Fleet-wide request-cache hit total across every tier (shard
    request cache, batcher intake, coordinator fused cache)."""
    hits = 0
    for node in c.nodes.values():
        hits += node.search_transport.request_cache.stats["hits"]
        hits += node.search_transport.batcher.stats.get(
            "request_cache_intake_hits", 0)
        fused = getattr(node.search_action, "fused_cache", None)
        if fused is not None:
            hits += fused.stats.get("hits", 0)
    return hits


def fleet_overload_scenario(seed: int, *, n_tenants: int = 4,
                            n_nodes: int = 6, docs: int = 10,
                            total_searches: int = 260,
                            duration_s: float = 1.2,
                            shard_bound: int = 2,
                            slow_delay_s: float = 0.08,
                            admission: Tuple[int, int] = (3, 10),
                            dup_head_fraction: float = 0.0
                            ) -> Dict[str, Any]:
    """THE million-user chaos scenario (ROADMAP item 6), one seed: a
    10:1 hot-tenant flood across 3 coordinators and ``n_tenants``
    zipfian tenants on a diurnal curve, with one slow data node from
    before the flood and a rolling restart mid-peak — against the full
    two-sided overload plane (coordinator admission + per-tenant fair
    shedding, shard-side ``search.shard.max_queued_members`` shed point,
    typed shard_busy failover, C3 ARS fed by pressure piggybacks AND
    busy rejections).

    Returns the measured invariants; the chaos suite asserts them green
    on every seed, bench.py emits them as the ``fleet`` config line."""
    from elasticsearch_tpu.search.telemetry import TELEMETRY

    c = InProcessCluster(n_nodes=n_nodes, seed=seed)
    c.start()
    try:
        import numpy as np
        tenants = [f"t{i}" for i in range(n_tenants)]
        coordinators = [f"node{i}" for i in range(min(3, n_nodes))]
        client = c.client()
        rng = np.random.default_rng(seed)
        box: List[Any] = []

        def wait(n: int) -> None:
            c.run_until(lambda: len(box) >= n, 300.0)

        expected: Dict[str, int] = {}
        for tenant in tenants:
            n0 = len(box)
            client.create_index(tenant, {
                "settings": {"number_of_shards": 1,
                             "number_of_replicas": 1},
                "mappings": {"properties": {"body": {"type": "text"}}}},
                lambda r, e=None: box.append(1))
            wait(n0 + 1)
            c.ensure_green(tenant)
            for i in range(docs):
                n0 = len(box)
                client.index_doc(
                    tenant, f"d{i}",
                    {"body": "common " + " ".join(
                        f"w{int(x)}" for x in rng.integers(0, 8, 4))},
                    lambda r, e=None: box.append(1))
                wait(n0 + 1)
            n0 = len(box)
            client.refresh(tenant, lambda r, e=None: box.append(1))
            wait(n0 + 1)
            expected[tenant] = docs     # every doc carries "common"

        # the two-sided overload plane: coordinator admission pinned
        # tiny (saturation at test scale) + the shard-side member bound
        c.constrain_search_admission(*admission)
        n0 = len(box)
        client.cluster_update_settings(
            {"persistent":
             {"search.shard.max_queued_members": shard_bound}},
            lambda r, e=None: box.append(1))
        wait(n0 + 1)

        # victim: a holder of the HOT tenant's shard copies — slow for
        # the whole run (the ARS routing-around story). Its sibling
        # copy-holder gets slowed too for the first half of the hot
        # window (a noisy-neighbor wave): with BOTH copies slow under a
        # 10:1 flood, the shard-side member bound genuinely engages and
        # the shed -> failover -> backoff-retry loop is exercised, not
        # just reachable. Slowing drains is data-plane only, so master
        # or coordinator victims are fine; CRASH targets must be
        # non-master (membership stays stable) and non-coordinator (a
        # crashed coordinator strands its own in-flight responses on the
        # 60s transport timeout — a different scenario's problem).
        master_id = c.master().node_id
        state = c.nodes[coordinators[0]].coordinator.applied_state
        holders = [sr.node_id for sr in
                   state.routing_table.index(tenants[0]).shard_group(0)
                   if sr.node_id is not None]
        victim = holders[-1]
        hot_sibling = next((h for h in holders if h != victim), None)
        restartable = [nid for nid in c.nodes
                       if nid != master_id and nid != victim and
                       nid not in coordinators][:2]
        c.slow_node_drains(victim, slow_delay_s)

        harness = FleetTrafficHarness(c, tenants, coordinators, seed)

        # unloaded p99: sequential traffic against the SAME cluster,
        # slow node already slow — the bound the flood is judged by
        for k in range(3 * n_tenants):
            harness.submit_one(tenants[k % n_tenants],
                               coordinators[k % len(coordinators)],
                               {"query": {"match": {"body": "common"}},
                                "size": 5})
            c.run_until(
                lambda: all(r["t1"] is not None for r in harness.records),
                300.0)
        unloaded_p99 = _p99([r["t1"] - r["t0"] for r in harness.records
                             if r["err"] is None])
        harness.records.clear()
        harness._expected["n"] = 0

        # zipf-head duplicate flood (dup_head_fraction > 0): that share
        # of the HOT tenant's arrivals repeat one exact cached body —
        # primed through every coordinator ahead of the storm, so the
        # head rides the request-cache tiers (fused / intake / shard)
        # and never reaches the shard shed point, while the distinct
        # tail still overflows the same constrained admission plane
        body_fn = None
        head_flags: List[bool] = []
        cache_hits_before = 0
        if dup_head_fraction > 0:
            n0 = len(box)
            client.cluster_update_settings(
                {"persistent": {"search.request_cache.topk": True}},
                lambda r, e=None: box.append(1))
            wait(n0 + 1)
            hot_body = {"query": {"match": {"body": "common"}},
                        "size": 5, "request_cache": True,
                        "track_total_hits": True}
            for coord in coordinators:
                primed: List[Any] = []
                c.nodes[coord].client.search(
                    tenants[0], dict(hot_body),
                    lambda r, e=None: primed.append(1))
                c.run_until(lambda: bool(primed), 300.0)
            dup_rng = _random.Random(seed ^ 0xD0B)

            marker = {"n": 0}

            def body_fn(tenant: str) -> Dict[str, Any]:
                if tenant == tenants[0] and \
                        dup_rng.random() < dup_head_fraction:
                    head_flags.append(True)
                    return dict(hot_body)
                head_flags.append(False)
                # the tail is CACHE-PROOF (a unique marker term defeats
                # every cache tier): with topk caching on fleet-wide,
                # repeated tail bodies would otherwise be absorbed too
                # and the shed point would never be reached
                marker["n"] += 1
                return {"query": {"match": {
                    "body": f"common u{marker['n']}x{seed}"}},
                    "size": 5}
            cache_hits_before = _fleet_cache_hits(c)

        # per-(node, shard-copy) query counts before the flood: the ARS
        # routing-verdict baseline
        def copy_hits() -> Dict[Tuple[str, str], int]:
            out: Dict[Tuple[str, str], int] = {}
            for nid, node in c.nodes.items():
                for tenant in tenants:
                    if node.indices_service.has_shard(tenant, 0):
                        out[(nid, tenant)] = node.indices_service.shard(
                            tenant, 0).search_stats["query_total"]
            return out

        hits_before = copy_hits()
        fallbacks_before = dict(TELEMETRY.fallbacks)

        # rolling restart mid-peak: each restartable node vanishes from
        # the wire for a slice of the hot window, one after another —
        # and the hot tenant's SIBLING copy is slow for the window's
        # first half, so the flood meets two saturated copies at once
        # the hot window sits ON the second diurnal peak (shape() peaks
        # at 3·duration/4): the 10:1 flood, the rolling restart and the
        # noisy-neighbor wave all land where traffic is already densest
        events: List[Tuple[float, Callable[[], None]]] = []
        win0, win1 = 0.62 * duration_s, 0.9 * duration_s
        if hot_sibling is not None:
            events.append((win0, lambda: c.slow_node_drains(
                hot_sibling, slow_delay_s * 0.6)))
            events.append((win0 + 0.5 * (win1 - win0),
                           lambda: c.slow_node_drains(hot_sibling, 0.0)))
        slot = (win1 - win0) / max(len(restartable), 1) / 2.0
        for k, nid in enumerate(restartable):
            t_down = win0 + (2 * k) * slot
            t_up = t_down + slot
            events.append((t_down, lambda n=nid: c.crash_node(n)))
            events.append((t_up, lambda n=nid: c.restart_node(n)))

        harness.run(duration_s, total_searches, hot_tenant=tenants[0],
                    hot_window=(win0, win1), hot_factor=10.0,
                    events=events, body_fn=body_fn)
        summary = harness.summary()
        if dup_head_fraction > 0:
            # submit order == body_fn call order under the deterministic
            # scheduler, so head_flags aligns with harness.records
            from elasticsearch_tpu.utils.errors import shard_busy_info
            head = [r for i, r in enumerate(harness.records)
                    if i < len(head_flags) and head_flags[i]]
            summary["dup_head"] = {
                "fraction": dup_head_fraction,
                "requests": len(head),
                "admitted": sum(1 for r in head if r["err"] is None),
                "shard_busy_failures": sum(
                    1 for r in head if r["err"] is not None and
                    (shard_busy_info(r["err"]) is not None or
                     "shard_busy" in str(r["err"]))),
                "cache_hits": _fleet_cache_hits(c) - cache_hits_before,
            }
        c.heal()
        c.slow_node_drains(victim, 0.0)

        # correctness probes (zero wrong hits): after the storm, every
        # tenant still answers the known-answer query exactly
        wrong_hits = 0
        for tenant in tenants:
            probe: List[Any] = []
            client.search(tenant, {
                "query": {"match": {"body": "common"}},
                "size": docs, "track_total_hits": True},
                lambda r, e=None: probe.append((r, e)))
            c.run_until(lambda: bool(probe), 300.0)
            resp, err = probe[0]
            if err is not None:
                wrong_hits += 1
                continue
            got = {h["_id"] for h in resp["hits"]["hits"]}
            want = {f"d{i}" for i in range(docs)}
            if got != want or \
                    resp["hits"]["total"]["value"] != expected[tenant]:
                wrong_hits += 1

        # shed / failover / routing accounting across the fleet
        hits_after = copy_hits()
        victim_hits = sum(n - hits_before.get(k, 0)
                          for k, n in hits_after.items()
                          if k[0] == victim)
        sibling_hits = sum(n - hits_before.get(k, 0)
                           for k, n in hits_after.items()
                           if k[0] != victim and
                           (victim, k[1]) in hits_after)
        sheds = sum(n.search_transport.batcher.stats["shard_busy_sheds"]
                    for n in c.nodes.values())
        hwm_over_bound = [
            (nid, n.search_transport.batcher.stats["queued_members_hwm"])
            for nid, n in c.nodes.items()
            if n.search_transport.batcher.stats["queued_members_hwm"]
            > shard_bound]
        failover = {k: sum(n.search_action.shard_busy_stats[k]
                           for n in c.nodes.values())
                    for k in ("sheds_seen", "failovers", "retry_rounds",
                              "all_copies_shed")}
        fallbacks_after = dict(TELEMETRY.fallbacks)
        fallback_deltas = {
            k: fallbacks_after.get(k, 0) - fallbacks_before.get(k, 0)
            for k in set(fallbacks_after) | set(fallbacks_before)
            if fallbacks_after.get(k, 0) != fallbacks_before.get(k, 0)}

        summary.update({
            "seed": seed,
            "tenants": n_tenants,
            "coordinators": len(coordinators),
            "victim": victim,
            "shard_bound": shard_bound,
            "unloaded_p99_s": unloaded_p99,
            "p99_factor_vs_unloaded": round(
                summary["admitted_p99_s"] / max(unloaded_p99, 1e-9), 2),
            "wrong_hits": wrong_hits,
            "shard_busy_sheds": sheds,
            "queued_hwm_over_bound": hwm_over_bound,
            "failover": failover,
            "victim_copy_hits": victim_hits,
            "sibling_copy_hits": sibling_hits,
            "fallback_deltas": fallback_deltas,
            "unknown_fallbacks": fallbacks_after.get("unknown", 0)
            - fallbacks_before.get("unknown", 0),
        })
        return summary
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# recovery-under-load chaos scenarios (ROADMAP: ops-based catch-up)
# ---------------------------------------------------------------------------

def _merged_recovery_stats(c: "InProcessCluster") -> Dict[str, Any]:
    """Fleet view of the reconcilers' recovery accounting — the same
    merge the ``_cluster/stats`` recovery section performs, fed straight
    from the node objects (no REST round-trip in a chaos assert path)."""
    from elasticsearch_tpu import monitor
    from elasticsearch_tpu.indices.cluster_state_service import (
        merge_recovery_sections)
    sections = []
    for node in c.nodes.values():
        try:
            sections.append(monitor.recovery_stats(
                node.reconciler, node.indices_service))
        except Exception:
            continue
    return merge_recovery_sections(sections)


def rolling_restart_recovery_scenario(seed: int, data_path: str, *,
                                      n_tenants: int = 3,
                                      n_nodes: int = 5, docs: int = 6,
                                      writes: int = 18,
                                      total_searches: int = 120,
                                      duration_s: float = 1.2
                                      ) -> Dict[str, Any]:
    """THE recovery tentpole scenario, one seed: a rolling restart of
    replica-holding nodes under live search + write traffic. Every
    restarted copy comes back with a fresh commit, a retained node-keyed
    retention lease on its primary, and complete op history from its
    local checkpoint — so every one of them must recover **ops-based**
    (replay the missed tail) or by segment reuse, never wipe-and-copy.

    Asserts per seed: zero ``peer`` (wipe) recoveries on restarted
    nodes, at least one ``ops_based`` catch-up, the typed file-fallback
    ``unknown`` bucket pinned at zero, no acked write lost, and the
    known-answer query exact after the storm. Returns the measured
    invariants; bench.py emits them as the ``recovery`` config line."""
    c = InProcessCluster(n_nodes=n_nodes, seed=seed, data_path=data_path)
    c.start()
    try:
        import numpy as np
        tenants = [f"t{i}" for i in range(n_tenants)]
        client = c.client()
        rng = np.random.default_rng(seed)
        box: List[Any] = []

        def wait(n: int) -> None:
            c.run_until(lambda: len(box) >= n, 300.0)

        for tenant in tenants:
            n0 = len(box)
            client.create_index(tenant, {
                "settings": {"number_of_shards": 1,
                             "number_of_replicas": 1},
                "mappings": {"properties": {"body": {"type": "text"}}}},
                lambda r, e=None: box.append(1))
            wait(n0 + 1)
            c.ensure_green(tenant)
            for i in range(docs):
                n0 = len(box)
                client.index_doc(
                    tenant, f"d{i}",
                    {"body": "common " + " ".join(
                        f"w{int(x)}" for x in rng.integers(0, 8, 4))},
                    lambda r, e=None: box.append(1))
                wait(n0 + 1)
            n0 = len(box)
            client.refresh(tenant, lambda r, e=None: box.append(1))
            wait(n0 + 1)
        # flush everywhere: every copy gets a hole-free commit carrying
        # the primary's retention leases — the restart's starting point
        n0 = len(box)
        client.flush("t*", lambda r, e=None: box.append(1))
        wait(n0 + 1)

        # reboot targets: nodes holding ONLY replica copies. Rebooting a
        # primary holder forces a term bump, and a copy committed under
        # the old term is *correctly* refused ops-based catch-up
        # (term_mismatch) — a different scenario. Master stays up so
        # membership churn doesn't stack on top of recovery.
        master_id = c.master().node_id
        state = c.master().coordinator.applied_state
        primary_nodes, copy_nodes = set(), set()
        for tenant in tenants:
            for sr in state.routing_table.index(tenant).shard_group(0):
                if sr.node_id is None:
                    continue
                copy_nodes.add(sr.node_id)
                if sr.primary:
                    primary_nodes.add(sr.node_id)
        reboot_targets = [nid for nid in c._node_ids
                          if nid in copy_nodes and
                          nid not in primary_nodes and
                          nid != master_id][:2]
        coordinators = [nid for nid in c._node_ids
                        if nid not in reboot_targets][:3]

        harness = FleetTrafficHarness(c, tenants, coordinators, seed)

        # live writes across the whole window: each reboot's downtime
        # overlaps acked writes, so returning replicas are genuinely
        # behind and must replay the tail (not just reuse segments)
        acked: Dict[str, set] = {t: set() for t in tenants}
        attempted: Dict[str, set] = {t: set() for t in tenants}
        writes_done = {"n": 0}
        writer = c.nodes[coordinators[0]].client

        def submit_write(k: int) -> None:
            tenant = tenants[k % n_tenants]
            doc_id = f"w{k}"
            attempted[tenant].add(doc_id)

            def on_write(r, e=None, t=tenant, d=doc_id) -> None:
                writes_done["n"] += 1
                if e is None:
                    acked[t].add(d)
            writer.index_doc(tenant, doc_id,
                             {"body": f"common live{k}"}, on_write)

        events: List[Tuple[float, Callable[[], None]]] = []
        for k in range(writes):
            events.append((duration_s * (0.05 + 0.9 * k / max(writes, 1)),
                           lambda kk=k: submit_write(kk)))
        # the rolling restart itself: full process reboots (in-memory
        # state gone, same data path), one node after another
        win0, win1 = 0.3 * duration_s, 0.85 * duration_s
        slot = (win1 - win0) / max(len(reboot_targets), 1)
        for k, nid in enumerate(reboot_targets):
            events.append((win0 + k * slot,
                           lambda n=nid: c.reboot_node(n)))

        harness.run(duration_s, total_searches, events=events)
        summary = harness.summary()
        restart_p99 = summary["admitted_p99_s"]

        # every write must RESOLVE before the post-run refresh, or the
        # last acks race the refresh broadcast and an acked-but-not-yet-
        # segmented doc reads as a false loss
        c.run_until(lambda: writes_done["n"] >= writes, 300.0)

        # let every recovery land, then judge. Routing-green is not
        # enough: after a fast reboot the master can still route a copy
        # STARTED at a node that hasn't rebuilt it locally (the
        # re-asserted shard-failed -> reassign -> recover cycle takes
        # failure-detection rounds of virtual time) — wait until every
        # STARTED copy really exists where it is routed.
        from elasticsearch_tpu.cluster.routing import ShardState

        def settled() -> bool:
            master = c.master()
            if master is None:
                return False
            st = master.coordinator.applied_state
            for tenant in tenants:
                for sr in st.routing_table.index(tenant).shard_group(0):
                    if sr.state != ShardState.STARTED or \
                            sr.node_id not in c.nodes:
                        return False
                    if not c.nodes[sr.node_id].indices_service.has_shard(
                            tenant, 0):
                        return False
            return True
        c.run_until(settled, 900.0)
        for tenant in tenants:
            c.ensure_green(tenant, max_time=600.0)
        n0 = len(box)
        client.refresh("t*", lambda r, e=None: box.append(1))
        wait(n0 + 1)

        # per-restarted-node recovery kinds, from the fresh reconcilers
        restarted_kinds: Dict[str, List[str]] = {}
        wipe_recoveries = 0
        ops_based = 0
        ops_replayed = 0
        for nid in reboot_targets:
            log = c.nodes[nid].reconciler.recovery_log()
            kinds = [e["kind"] for e in log if e["index"] in tenants]
            restarted_kinds[nid] = kinds
            wipe_recoveries += sum(1 for k in kinds if k == "peer")
            ops_based += sum(1 for k in kinds if k == "ops_based")
            ops_replayed += sum(e.get("ops_replayed", 0) for e in log
                                if e["index"] in tenants)

        # zero lost acked docs + known-answer exactness per tenant
        lost_acked = 0
        wrong_hits = 0
        for tenant in tenants:
            probe: List[Any] = []
            client.search(tenant, {
                "query": {"match": {"body": "common"}},
                "size": docs + writes + 8, "track_total_hits": True},
                lambda r, e=None: probe.append((r, e)))
            c.run_until(lambda: bool(probe), 300.0)
            resp, err = probe[0]
            if err is not None:
                wrong_hits += 1
                continue
            got = {h["_id"] for h in resp["hits"]["hits"]}
            must = {f"d{i}" for i in range(docs)} | acked[tenant]
            may = must | attempted[tenant]
            lost_acked += len(must - got)
            if not got <= may:
                wrong_hits += 1

        fleet = _merged_recovery_stats(c)
        master_node = c.master()
        lease_covered = (master_node.gateway_allocator.stats.get(
            "lease_covered_allocations", 0) if master_node else 0)

        summary.update({
            "seed": seed,
            "rebooted": reboot_targets,
            "restarted_replica_kinds": restarted_kinds,
            "wipe_recoveries_on_restarted": wipe_recoveries,
            "ops_based_recoveries": ops_based,
            "ops_replayed_on_restarted": ops_replayed,
            "acked_writes": sum(len(s) for s in acked.values()),
            "lost_acked_docs": lost_acked,
            "wrong_hits": wrong_hits,
            "restart_p99_s": restart_p99,
            "fleet_recovery": fleet,
            "unknown_fallbacks": (fleet.get("file_fallback_reasons") or
                                  {}).get("unknown", 0),
            "lease_covered_allocations": lease_covered,
        })
        return summary
    finally:
        c.stop()


def failover_under_live_writes_scenario(seed: int, data_path: str, *,
                                        n_tenants: int = 3,
                                        n_nodes: int = 3, docs: int = 6,
                                        writes: int = 24,
                                        total_searches: int = 100,
                                        duration_s: float = 1.4
                                        ) -> Dict[str, Any]:
    """THE failover tentpole scenario, one seed: kill the node holding
    primaries mid-flood, with 2 replicas per shard so every node holds
    every copy. The master detects the death, promotes a surviving
    replica (term bump + tracker seeding + post-promotion resync), the
    other survivor rolls its deposed-term tail back to the global
    checkpoint and replays the new primacy's history, and the DEPOSED
    primary later reboots into a cross-term commit whose tail the new
    primary reconciles by rollback+replay — the ops path, not a wipe.

    Asserts per seed: zero lost acked docs, zero wrong hits, at least
    one resync ran (started or noop), the deposed copy rejoined without
    a ``peer`` wipe, and the typed fallback ``unknown`` bucket stays
    pinned at zero. Returns the measured invariants; bench.py emits
    them as the ``recovery`` config's failover line."""
    c = InProcessCluster(n_nodes=n_nodes, seed=seed, data_path=data_path)
    c.start()
    try:
        import numpy as np
        tenants = [f"t{i}" for i in range(n_tenants)]
        client = c.client()
        rng = np.random.default_rng(seed)
        box: List[Any] = []

        def wait(n: int) -> None:
            c.run_until(lambda: len(box) >= n, 300.0)

        for tenant in tenants:
            n0 = len(box)
            client.create_index(tenant, {
                "settings": {"number_of_shards": 1,
                             "number_of_replicas": n_nodes - 1},
                "mappings": {"properties": {"body": {"type": "text"}}}},
                lambda r, e=None: box.append(1))
            wait(n0 + 1)
            c.ensure_green(tenant)
            for i in range(docs):
                n0 = len(box)
                client.index_doc(
                    tenant, f"d{i}",
                    {"body": "common " + " ".join(
                        f"w{int(x)}" for x in rng.integers(0, 8, 4))},
                    lambda r, e=None: box.append(1))
                wait(n0 + 1)
            n0 = len(box)
            client.refresh(tenant, lambda r, e=None: box.append(1))
            wait(n0 + 1)
        # flush everywhere so every copy — including the one about to be
        # deposed — holds a commit with its learned global checkpoint:
        # the cross-term recovery gate keys on that persisted value
        n0 = len(box)
        client.flush("t*", lambda r, e=None: box.append(1))
        wait(n0 + 1)

        # the victim: a PRIMARY-holding node (prefer a non-master one so
        # the failover is a clean promotion, not promotion + election)
        master_id = c.master().node_id
        state = c.master().coordinator.applied_state
        primaries_on: Dict[str, int] = {}
        for tenant in tenants:
            for sr in state.routing_table.index(tenant).shard_group(0):
                if sr.primary and sr.node_id is not None:
                    primaries_on[sr.node_id] = \
                        primaries_on.get(sr.node_id, 0) + 1
        candidates = sorted(
            primaries_on, key=lambda n: (n == master_id,
                                         -primaries_on[n], n))
        victim = candidates[0]
        affected = [t for t in tenants
                    if state.routing_table.index(t).primary(0).node_id
                    == victim]

        coordinators = [nid for nid in c._node_ids if nid != victim][:2]
        harness = FleetTrafficHarness(c, tenants, coordinators, seed)

        # live writes across the whole window: some land before the
        # kill (and may sit unacked on the doomed primary), some hit
        # the promotion gap, some land on the new primacy
        acked: Dict[str, set] = {t: set() for t in tenants}
        attempted: Dict[str, set] = {t: set() for t in tenants}
        writes_done = {"n": 0}
        writer = c.nodes[coordinators[0]].client

        def submit_write(k: int) -> None:
            tenant = tenants[k % n_tenants]
            doc_id = f"w{k}"
            attempted[tenant].add(doc_id)

            def on_write(r, e=None, t=tenant, d=doc_id) -> None:
                writes_done["n"] += 1
                if e is None:
                    acked[t].add(d)
            writer.index_doc(tenant, doc_id,
                             {"body": f"common live{k}"}, on_write)

        events: List[Tuple[float, Callable[[], None]]] = []
        for k in range(writes):
            events.append((duration_s * (0.05 + 0.9 * k / max(writes, 1)),
                           lambda kk=k: submit_write(kk)))
        events.append((0.35 * duration_s, lambda: c.kill_node(victim)))

        harness.run(duration_s, total_searches, events=events)
        summary = harness.summary()

        c.run_until(lambda: writes_done["n"] >= writes, 300.0)

        # wait for the failover to actually land: every affected tenant
        # must have a STARTED primary on a SURVIVING node (the master's
        # failure detection + promotion takes fault-detection rounds of
        # virtual time — the victim stays down until this is proven)
        def promoted() -> bool:
            master = c.master()
            if master is None:
                return False
            st = master.coordinator.applied_state
            for tenant in affected:
                sr = st.routing_table.index(tenant).primary(0)
                sr_ok = sr.node_id is not None and sr.node_id != victim \
                    and sr.node_id in c.nodes and \
                    sr.state == ShardState.STARTED
                if not sr_ok:
                    return False
            return True
        from elasticsearch_tpu.cluster.routing import ShardState
        c.run_until(promoted, 900.0)

        # writes into the NEW primacy: the deposed copy's commit is now
        # genuinely behind a different term's history, so its rejoin
        # must take the cross-term rollback+replay path, not reuse
        post_writes = max(4, writes // 4)
        for k in range(post_writes):
            submit_write(writes + k)
        c.run_until(lambda: writes_done["n"] >= writes + post_writes,
                    300.0)

        # the deposed primary reboots over its old data path
        fresh = c._build_node(victim)
        c.nodes[victim] = fresh
        fresh.start()

        # settle: every STARTED copy must really exist where routed —
        # including the deposed primary's rebuilt replica copy
        from elasticsearch_tpu.cluster.routing import ShardState

        def settled() -> bool:
            master = c.master()
            if master is None:
                return False
            st = master.coordinator.applied_state
            for tenant in tenants:
                for sr in st.routing_table.index(tenant).shard_group(0):
                    if sr.state != ShardState.STARTED or \
                            sr.node_id not in c.nodes:
                        return False
                    if not c.nodes[sr.node_id].indices_service.has_shard(
                            tenant, 0):
                        return False
            return True
        c.run_until(settled, 900.0)
        for tenant in tenants:
            c.ensure_green(tenant, max_time=600.0)
        n0 = len(box)
        client.refresh("t*", lambda r, e=None: box.append(1))
        wait(n0 + 1)

        # how the deposed primary's copies came back (its fresh
        # reconciler's log): the cross-term gate must have reconciled
        # them ops-based (rollback+replay) or reused them — never wiped
        deposed_log = c.nodes[victim].reconciler.recovery_log()
        deposed_kinds = [e["kind"] for e in deposed_log
                         if e["index"] in tenants]
        deposed_wipes = sum(1 for k in deposed_kinds if k == "peer")
        deposed_ops_based = sum(1 for k in deposed_kinds
                                if k == "ops_based")

        # fleet resync + rollback accounting (riding _nodes/stats paths)
        resync = {"resyncs_started": 0, "resyncs_completed": 0,
                  "resyncs_noop": 0, "resync_failures": 0,
                  "resync_ops_sent": 0, "resync_ops_applied": 0,
                  "resync_targets": 0}
        for node in c.nodes.values():
            for key, n in node.reconciler.resyncer.stats.items():
                resync[key] = resync.get(key, 0) + n

        # zero lost acked docs + known-answer exactness per tenant
        lost_acked = 0
        wrong_hits = 0
        for tenant in tenants:
            probe: List[Any] = []
            client.search(tenant, {
                "query": {"match": {"body": "common"}},
                "size": docs + writes + 8, "track_total_hits": True},
                lambda r, e=None: probe.append((r, e)))
            c.run_until(lambda: bool(probe), 300.0)
            resp, err = probe[0]
            if err is not None:
                wrong_hits += 1
                continue
            got = {h["_id"] for h in resp["hits"]["hits"]}
            must = {f"d{i}" for i in range(docs)} | acked[tenant]
            may = must | attempted[tenant]
            lost_acked += len(must - got)
            if not got <= may:
                wrong_hits += 1

        fleet = _merged_recovery_stats(c)
        summary.update({
            "seed": seed,
            "victim": victim,
            "victim_was_master": victim == master_id,
            "affected_tenants": affected,
            "deposed_recovery_kinds": deposed_kinds,
            "deposed_wipe_recoveries": deposed_wipes,
            "deposed_ops_based": deposed_ops_based,
            "resync": resync,
            "rollbacks": fleet.get("rollbacks", 0),
            "ops_rolled_back": fleet.get("ops_rolled_back", 0),
            "acked_writes": sum(len(s) for s in acked.values()),
            "lost_acked_docs": lost_acked,
            "wrong_hits": wrong_hits,
            "fleet_recovery": fleet,
            "unknown_fallbacks": (fleet.get("file_fallback_reasons") or
                                  {}).get("unknown", 0),
        })
        return summary
    finally:
        c.stop()


def duplicate_flood_cache_shed_scenario(seed: int, *, n_tenants: int = 3,
                                        n_nodes: int = 5, docs: int = 8,
                                        hot_searches: int = 90,
                                        distinct_searches: int = 240,
                                        duration_s: float = 1.0,
                                        shard_bound: int = 2,
                                        slow_delay_s: float = 0.08,
                                        admission: Tuple[int, int] = (3, 10)
                                        ) -> Dict[str, Any]:
    """Shed plane × request cache composition, one seed: a zipf-style
    duplicate flood (one EXACT body repeated from every coordinator)
    must be absorbed by the two-tier request cache — hot head served
    from cache with ZERO sheds — while a second flood of all-distinct
    bodies (cache-proof) overflows the same constrained admission plane
    and is shed CLEANLY (429 + Retry-After, typed busy failover, no
    unclean rejection). The two planes must compose: caching absorbs
    duplicates without disabling shedding for the traffic it cannot
    absorb."""
    c = InProcessCluster(n_nodes=n_nodes, seed=seed)
    c.start()
    try:
        import numpy as np
        tenants = [f"t{i}" for i in range(n_tenants)]
        coordinators = [f"node{i}" for i in range(min(3, n_nodes))]
        client = c.client()
        rng = np.random.default_rng(seed)
        box: List[Any] = []

        def wait(n: int) -> None:
            c.run_until(lambda: len(box) >= n, 300.0)

        for tenant in tenants:
            n0 = len(box)
            client.create_index(tenant, {
                "settings": {"number_of_shards": 1,
                             "number_of_replicas": 1},
                "mappings": {"properties": {"body": {"type": "text"}}}},
                lambda r, e=None: box.append(1))
            wait(n0 + 1)
            c.ensure_green(tenant)
            for i in range(docs):
                n0 = len(box)
                client.index_doc(
                    tenant, f"d{i}",
                    {"body": "common " + " ".join(
                        f"w{int(x)}" for x in rng.integers(0, 8, 4))},
                    lambda r, e=None: box.append(1))
                wait(n0 + 1)
            n0 = len(box)
            client.refresh(tenant, lambda r, e=None: box.append(1))
            wait(n0 + 1)

        c.constrain_search_admission(*admission)
        n0 = len(box)
        client.cluster_update_settings(
            {"persistent":
             {"search.shard.max_queued_members": shard_bound,
              "search.request_cache.topk": True}},
            lambda r, e=None: box.append(1))
        wait(n0 + 1)

        # a slow holder of the hot tenant's shard, slow for BOTH phases:
        # the same saturated plane absorbs the duplicate flood through
        # the cache (zero sheds) and sheds the distinct flood cleanly —
        # the composition claim, not two unrelated configurations
        state = c.nodes[coordinators[0]].coordinator.applied_state
        holders = [sr.node_id for sr in
                   state.routing_table.index(tenants[0]).shard_group(0)
                   if sr.node_id is not None]
        victim = holders[-1]
        c.slow_node_drains(victim, slow_delay_s)

        def cache_counters() -> Dict[str, int]:
            shard_hits = intake_hits = fused_hits = sheds = 0
            for node in c.nodes.values():
                shard_hits += node.search_transport.request_cache.stats[
                    "hits"]
                intake_hits += node.search_transport.batcher.stats.get(
                    "request_cache_intake_hits", 0)
                fused = getattr(node.search_action, "fused_cache", None)
                if fused is not None:
                    fused_hits += fused.stats.get("hits", 0)
                sheds += node.search_transport.batcher.stats[
                    "shard_busy_sheds"]
            return {"shard_hits": shard_hits, "intake_hits": intake_hits,
                    "fused_hits": fused_hits, "sheds": sheds}

        hot_body = {"query": {"match": {"body": "common"}}, "size": 5,
                    "request_cache": True, "track_total_hits": True}

        # phase A — the duplicate flood: the same body, hammered from
        # every coordinator at a rate the constrained admission plane
        # could not possibly serve uncached
        before_a = cache_counters()
        harness = FleetTrafficHarness(c, tenants, coordinators, seed)
        harness.run(duration_s, hot_searches, hot_tenant=tenants[0],
                    hot_window=(0.2 * duration_s, 0.9 * duration_s),
                    hot_factor=10.0, body_fn=lambda t: dict(hot_body))
        summary_a = harness.summary()
        after_a = cache_counters()

        # phase B — the cache-proof flood: every body distinct (a unique
        # marker term defeats both cache tiers), same admission plane
        marker = {"n": 0}

        def distinct_body(tenant: str) -> Dict[str, Any]:
            marker["n"] += 1
            return {"query": {"match": {
                "body": f"common u{marker['n']}x{seed}"}},
                "size": 5, "request_cache": True}

        failover_before = {
            k: sum(n.search_action.shard_busy_stats[k]
                   for n in c.nodes.values())
            for k in ("sheds_seen", "failovers", "all_copies_shed")}
        harness_b = FleetTrafficHarness(c, tenants, coordinators,
                                        seed + 1)
        harness_b.run(duration_s, distinct_searches,
                      hot_tenant=tenants[0],
                      hot_window=(0.2 * duration_s, 0.9 * duration_s),
                      hot_factor=10.0, body_fn=distinct_body)
        summary_b = harness_b.summary()
        after_b = cache_counters()
        failover = {
            k: sum(n.search_action.shard_busy_stats[k]
                   for n in c.nodes.values()) - failover_before[k]
            for k in failover_before}
        c.slow_node_drains(victim, 0.0)

        # post-storm exactness
        wrong_hits = 0
        for tenant in tenants:
            probe: List[Any] = []
            client.search(tenant, {
                "query": {"match": {"body": "common"}},
                "size": docs, "track_total_hits": True},
                lambda r, e=None: probe.append((r, e)))
            c.run_until(lambda: bool(probe), 300.0)
            resp, err = probe[0]
            if err is not None or \
                    {h["_id"] for h in resp["hits"]["hits"]} != \
                    {f"d{i}" for i in range(docs)}:
                wrong_hits += 1

        return {
            "seed": seed,
            "victim": victim,
            "hot": summary_a,
            "distinct": summary_b,
            "distinct_failover": failover,
            "hot_cache_hits": (after_a["shard_hits"]
                               - before_a["shard_hits"]
                               + after_a["intake_hits"]
                               - before_a["intake_hits"]
                               + after_a["fused_hits"]
                               - before_a["fused_hits"]),
            "hot_sheds": after_a["sheds"] - before_a["sheds"],
            "distinct_sheds": after_b["sheds"] - after_a["sheds"],
            "distinct_clean_429": summary_b["rejected_clean_429"],
            "distinct_unclean": summary_b["unclean_rejections"],
            "wrong_hits": wrong_hits,
        }
    finally:
        c.stop()


def disk_full_mid_flush_scenario(seed: int, data_path: str, *,
                                 n_nodes: int = 5, docs: int = 8,
                                 total_searches: int = 100,
                                 duration_s: float = 1.0
                                 ) -> Dict[str, Any]:
    """Disk-full mid-flush under live traffic, one seed: ENOSPC is armed
    on the primary holder's data path in the middle of the run, then a
    flush lands on it — the commit write faults, the engine fails
    tragically with a typed reason, the shard is failed to the master,
    and the surviving replica is promoted and keeps serving. Asserts:
    the failure reason is typed (flush + disk-full), at least one
    injected I/O error actually fired, searches stay exact (zero wrong
    hits), and the cluster returns to green once the disk 'recovers'
    (fault disarmed)."""
    c = InProcessCluster(n_nodes=n_nodes, seed=seed, data_path=data_path)
    c.start()
    try:
        import numpy as np
        tenant = "t0"
        client = c.client()
        rng = np.random.default_rng(seed)
        box: List[Any] = []

        def wait(n: int) -> None:
            c.run_until(lambda: len(box) >= n, 300.0)

        n0 = len(box)
        client.create_index(tenant, {
            "settings": {"number_of_shards": 1,
                         "number_of_replicas": 1},
            "mappings": {"properties": {"body": {"type": "text"}}}},
            lambda r, e=None: box.append(1))
        wait(n0 + 1)
        c.ensure_green(tenant)
        for i in range(docs):
            n0 = len(box)
            client.index_doc(
                tenant, f"d{i}",
                {"body": "common " + " ".join(
                    f"w{int(x)}" for x in rng.integers(0, 8, 4))},
                lambda r, e=None: box.append(1))
            wait(n0 + 1)
        n0 = len(box)
        client.refresh(tenant, lambda r, e=None: box.append(1))
        wait(n0 + 1)

        master_id = c.master().node_id
        state = c.master().coordinator.applied_state
        group = state.routing_table.index(tenant).shard_group(0)
        victim = next(sr.node_id for sr in group if sr.primary)
        survivor = next(sr.node_id for sr in group
                        if not sr.primary and sr.node_id is not None)
        coordinators = [nid for nid in c._node_ids
                        if nid not in (victim,)][:3]
        victim_shard = c.nodes[victim].indices_service.shard(tenant, 0)
        victim_engine = victim_shard.engine

        io_before = c.disk_io.stats["io_errors"]
        captured: Dict[str, Any] = {"reason": None, "rule": None}

        def arm_and_flush() -> None:
            # the disk fills exactly as the commit write starts: armed
            # write-path ENOSPC on the victim's data path only (translog
            # appends keep succeeding — acks don't fault, the commit does)
            captured["rule"] = c.disk_io.arm(
                "enospc", match=f"/{victim}/", op="write")
            client.flush(tenant, lambda r, e=None: None)

        def capture_and_heal() -> None:
            captured["reason"] = victim_engine.failure_reason
            c.disk_io.disarm(captured["rule"])

        events: List[Tuple[float, Callable[[], None]]] = [
            (0.4 * duration_s, arm_and_flush),
            (0.85 * duration_s, capture_and_heal),
        ]

        harness = FleetTrafficHarness(c, [tenant], coordinators, seed)
        harness.run(duration_s, total_searches, events=events)
        summary = harness.summary()
        if captured["reason"] is None:     # flush landed after the probe
            captured["reason"] = victim_engine.failure_reason
        c.disk_io.disarm()

        # the failed primary's copy is gone from the group; the survivor
        # must now hold the primary and the answer must be exact
        c.ensure_yellow(tenant, max_time=600.0)
        probe: List[Any] = []
        client.search(tenant, {
            "query": {"match": {"body": "common"}},
            "size": docs, "track_total_hits": True},
            lambda r, e=None: probe.append((r, e)))
        c.run_until(lambda: bool(probe), 300.0)
        resp, err = probe[0]
        wrong_hits = 0
        if err is not None or \
                {h["_id"] for h in resp["hits"]["hits"]} != \
                {f"d{i}" for i in range(docs)} or \
                resp["hits"]["total"]["value"] != docs:
            wrong_hits += 1

        # disk 'replaced': the copy comes back and the index goes green
        c.ensure_green(tenant, max_time=600.0)
        promoted = next(
            sr.node_id for sr in c.master().coordinator.applied_state
            .routing_table.index(tenant).shard_group(0) if sr.primary)

        summary.update({
            "seed": seed,
            "victim": victim,
            "survivor": survivor,
            "master": master_id,
            "promoted_primary": promoted,
            "failure_reason": captured["reason"],
            "typed_failure": bool(
                captured["reason"] and
                "flush failed" in captured["reason"] and
                "disk-full" in captured["reason"]),
            "injected_io_errors": c.disk_io.stats["io_errors"]
            - io_before,
            "wrong_hits": wrong_hits,
        })
        return summary
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# mixed read/write workload under chaos (write-path pressure plane)
# ---------------------------------------------------------------------------

def _merged_indexing_pressure(c: "InProcessCluster") -> Dict[str, Any]:
    """Fleet view of the three-stage write-pressure accounting — the
    same merge the ``_cluster/stats`` indexing_pressure section performs,
    fed straight from the node objects (no REST round-trip in a chaos
    assert path)."""
    from elasticsearch_tpu.utils.threadpool import (
        merge_indexing_pressure_sections)
    return merge_indexing_pressure_sections(
        [n.thread_pool.indexing_pressure.stats()
         for n in c.nodes.values()])


def mixed_read_write_scenario(seed: int, data_path: str, *,
                              n_tenants: int = 3, n_nodes: int = 5,
                              docs: int = 6,
                              write_bursts: int = 8,
                              bulks_per_burst: int = 10,
                              items_per_bulk: int = 3,
                              pressure_limit: int = 700,
                              total_searches: int = 100,
                              duration_s: float = 1.4,
                              slow_delay_s: float = 0.004
                              ) -> Dict[str, Any]:
    """THE write-path pressure tentpole scenario, one seed: a live bulk
    flood offered ~10:1 over the shrunken ``indexing_pressure.memory.
    limit`` (each burst's bytes are ~10x what admission can hold in
    flight), concurrent multi-coordinator search traffic, a slow-disk
    victim whose translog appends charge real virtual-time latency
    (FaultyDiskIO 'slow'), and a rolling restart of a replica-holding
    node mid-ingest.

    Asserts per seed (the chaos suite and bench judge these): zero
    acked docs lost, zero wrong hits, every write shed a CLEAN typed
    ``es_rejected_execution_exception`` 429 with a computed Retry-After,
    the per-stage rejection ``unknown`` bucket pinned at zero, admitted
    search p99 bounded vs the unloaded baseline, and ingest goodput
    preserved (accepted bulks keep landing through the whole storm).
    Returns the measured invariants; bench.py emits them as the
    ``mixed_rw`` config line."""
    c = InProcessCluster(n_nodes=n_nodes, seed=seed, data_path=data_path)
    c.start()
    try:
        import numpy as np
        tenants = [f"t{i}" for i in range(n_tenants)]
        client = c.client()
        rng = np.random.default_rng(seed)
        box: List[Any] = []

        def wait(n: int) -> None:
            c.run_until(lambda: len(box) >= n, 300.0)

        for tenant in tenants:
            n0 = len(box)
            client.create_index(tenant, {
                "settings": {"number_of_shards": 1,
                             "number_of_replicas": 1},
                "mappings": {"properties": {"body": {"type": "text"}}}},
                lambda r, e=None: box.append(1))
            wait(n0 + 1)
            c.ensure_green(tenant)
            for i in range(docs):
                n0 = len(box)
                client.index_doc(
                    tenant, f"d{i}",
                    {"body": "common " + " ".join(
                        f"w{int(x)}" for x in rng.integers(0, 8, 4))},
                    lambda r, e=None: box.append(1))
                wait(n0 + 1)
            n0 = len(box)
            client.refresh(tenant, lambda r, e=None: box.append(1))
            wait(n0 + 1)

        # chaos cast: the slow-disk victim holds a replica (so fan-out
        # crosses its degraded translog); the reboot target is another
        # replica-only holder; master and both stay out of the
        # coordinator set so in-flight searches aren't stranded
        master_id = c.master().node_id
        state = c.master().coordinator.applied_state
        primary_nodes, copy_nodes = set(), set()
        for tenant in tenants:
            for sr in state.routing_table.index(tenant).shard_group(0):
                if sr.node_id is None:
                    continue
                copy_nodes.add(sr.node_id)
                if sr.primary:
                    primary_nodes.add(sr.node_id)
        replica_only = [nid for nid in c._node_ids
                        if nid in copy_nodes and
                        nid not in primary_nodes and nid != master_id]
        slow_victim = replica_only[0] if replica_only else None
        reboot_target = replica_only[1] if len(replica_only) > 1 else None
        coordinators = [nid for nid in c._node_ids
                        if nid != reboot_target][:3]
        writer = c.nodes[coordinators[0]].client

        # slow disk armed for the WHOLE run, baseline included — the
        # bound the flood is judged by already contains the brownout
        # (the fleet_overload_scenario precedent)
        slow_rule = None
        if slow_victim is not None:
            slow_rule = c.disk_io.arm("slow", match=f"/{slow_victim}/",
                                      op="append", delay_s=slow_delay_s)
        slow_before = c.disk_io.stats["slow_ops"]

        # the write-pressure plane shrunk to test scale through the
        # DYNAMIC setting (the satellite under test): one burst offers
        # bulks_per_burst x ~bulk_bytes against this in-flight budget
        n0 = len(box)
        client.cluster_update_settings(
            {"persistent":
             {"indexing_pressure.memory.limit": str(pressure_limit)}},
            lambda r, e=None: box.append(1))
        wait(n0 + 1)

        harness = FleetTrafficHarness(c, tenants, coordinators, seed)

        # unloaded p99: sequential searches against the same (already
        # slow-disked) cluster, each alongside one small live write so
        # the baseline absorbs representative disk-latency charges
        for k in range(3 * n_tenants):
            writer.bulk([{"action": "index",
                          "index": tenants[k % n_tenants],
                          "id": f"base{k}",
                          "source": {"body": f"common base{k}"}}],
                        lambda r, e=None: None)
            harness.submit_one(tenants[k % n_tenants],
                               coordinators[k % len(coordinators)],
                               {"query": {"match": {"body": "common"}},
                                "size": 5})
            c.run_until(
                lambda: all(r["t1"] is not None for r in harness.records),
                300.0)
        unloaded_p99 = _p99([r["t1"] - r["t0"] for r in harness.records
                             if r["err"] is None])
        harness.records.clear()
        harness._expected["n"] = 0

        # the live bulk flood: each burst submits its bulks back-to-back
        # (their coordinating charges overlap by construction), offered
        # bytes per burst ~= bulks_per_burst x bulk_bytes >> limit
        acked: Dict[str, set] = {t: set() for t in tenants}
        attempted: Dict[str, set] = {t: set() for t in tenants}
        writes_done = {"n": 0}
        shed_records: List[Dict[str, Any]] = []
        total_bulks = write_bursts * bulks_per_burst

        def classify(resp: Dict[str, Any], tenant: str) -> None:
            writes_done["n"] += 1
            for wrapped in resp.get("items", []):
                result = next(iter(wrapped.values()))
                doc_id = result.get("id") or result.get("_id")
                if "error" not in result:
                    if doc_id is not None:
                        acked[tenant].add(doc_id)
                    continue
                if result.get("status") == 429:
                    err = result["error"]
                    shed_records.append({
                        "type": err.get("type"),
                        "retry_after": err.get("retry_after"),
                        "clean": bool(
                            err.get("type") ==
                            "es_rejected_execution_exception" and
                            int(err.get("retry_after") or 0) >= 1)})

        def submit_bulk(burst: int, b: int) -> None:
            tenant = tenants[(burst * bulks_per_burst + b) % n_tenants]
            items = []
            for i in range(items_per_bulk):
                doc_id = f"w{burst}_{b}_{i}"
                attempted[tenant].add(doc_id)
                items.append({"action": "index", "index": tenant,
                              "id": doc_id,
                              "source": {"body": f"common live{burst}"}})
            writer.bulk(items,
                        lambda r, e=None, t=tenant: classify(r or {}, t))

        events: List[Tuple[float, Callable[[], None]]] = []
        for burst in range(write_bursts):
            t = duration_s * (0.15 + 0.75 * burst / max(write_bursts, 1))
            events.append((t, lambda bb=burst: [
                submit_bulk(bb, b) for b in range(bulks_per_burst)]))
        # rolling restart mid-ingest: a replica holder reboots while
        # acked writes are still landing — returning copies must catch
        # up (and replica-stage pressure retries must never turn a
        # transient reject into a lost ack)
        if reboot_target is not None:
            events.append((0.55 * duration_s,
                           lambda: c.reboot_node(reboot_target)))

        harness.run(duration_s, total_searches, hot_tenant=tenants[0],
                    hot_window=(0.3 * duration_s, 0.8 * duration_s),
                    hot_factor=4.0, events=events)
        summary = harness.summary()

        # every bulk must resolve (replica-pressure retries can run past
        # the traffic window) before the flood is judged
        c.run_until(lambda: writes_done["n"] >= total_bulks, 900.0)
        if slow_rule is not None:
            c.disk_io.disarm(slow_rule)

        # let the rebooted copy land where it is routed, then refresh
        from elasticsearch_tpu.cluster.routing import ShardState

        def settled() -> bool:
            master = c.master()
            if master is None:
                return False
            st = master.coordinator.applied_state
            for tenant in tenants:
                for sr in st.routing_table.index(tenant).shard_group(0):
                    if sr.state != ShardState.STARTED or \
                            sr.node_id not in c.nodes:
                        return False
                    if not c.nodes[sr.node_id].indices_service.has_shard(
                            tenant, 0):
                        return False
            return True
        c.run_until(settled, 900.0)
        for tenant in tenants:
            c.ensure_green(tenant, max_time=600.0)
        n0 = len(box)
        client.refresh("t*", lambda r, e=None: box.append(1))
        wait(n0 + 1)

        # zero lost acked docs + zero wrong hits, per tenant: everything
        # acked (plus the seed docs and baseline writes) must be found,
        # nothing outside attempted∪acked may appear
        lost_acked = 0
        wrong_hits = 0
        size = docs + 3 * n_tenants + \
            write_bursts * bulks_per_burst * items_per_bulk + 8
        for tenant in tenants:
            probe: List[Any] = []
            client.search(tenant, {
                "query": {"match": {"body": "common"}},
                "size": size, "track_total_hits": True},
                lambda r, e=None: probe.append((r, e)))
            c.run_until(lambda: bool(probe), 300.0)
            resp, err = probe[0]
            if err is not None:
                wrong_hits += 1
                continue
            got = {h["_id"] for h in resp["hits"]["hits"]}
            must = {f"d{i}" for i in range(docs)} | acked[tenant]
            may = must | attempted[tenant] | \
                {f"base{k}" for k in range(3 * n_tenants)}
            lost_acked += len(must - got)
            if not got <= may:
                wrong_hits += 1

        ip = _merged_indexing_pressure(c)
        replica_retries = {
            k: sum(n.shard_bulk.write_pressure_stats.get(k, 0)
                   for n in c.nodes.values())
            for k in ("replica_pressure_rejections",
                      "replica_pressure_recoveries",
                      "replica_pressure_exhausted")}
        acked_docs = sum(len(s) for s in acked.values())
        attempted_docs = sum(len(s) for s in attempted.values())

        summary.update({
            "seed": seed,
            "slow_victim": slow_victim,
            "reboot_target": reboot_target,
            "pressure_limit": pressure_limit,
            "unloaded_p99_s": unloaded_p99,
            "p99_factor_vs_unloaded": round(
                summary["admitted_p99_s"] / max(unloaded_p99, 1e-9), 2),
            "wrong_hits": wrong_hits,
            "lost_acked_docs": lost_acked,
            "acked_docs": acked_docs,
            "attempted_docs": attempted_docs,
            "write_goodput_fraction": round(
                acked_docs / max(attempted_docs, 1), 3),
            "write_sheds": len(shed_records),
            "clean_write_sheds": sum(
                1 for s in shed_records if s["clean"]),
            "unclean_write_sheds": sum(
                1 for s in shed_records if not s["clean"]),
            "slow_ops": c.disk_io.stats["slow_ops"] - slow_before,
            "indexing_pressure": ip,
            "unknown_stage_rejections":
                (ip.get("rejections") or {}).get("unknown", 0),
            "replica_retries": replica_retries,
        })
        return summary
    finally:
        c.stop()
