"""Generic persistent tasks: cluster-state-backed jobs that survive
node loss and master failover.

Reference: persistent/PersistentTasksClusterService.java:50 +
PersistentTasksNodeService — ONE reusable framework for long-lived jobs:
tasks are registered in cluster-state metadata, the elected master
assigns each to a live node, the assigned node runs the registered
executor, and reassignment happens automatically when the assignee
leaves. Round 3's features (transforms, watcher, CCR, ML jobs) each
hand-rolled this pattern; this module is the generic service they (and
new features) can build on.

Task lifecycle:
  submit(id, name, params)     -> stored unassigned in custom metadata
  master tick                  -> assignment {node_id} written to state
  assignee tick                -> registered executor(name) instantiated
                                  and start()ed locally
  update_state(id, body)       -> arbitrary progress state replicated
  complete(id)                 -> entry removed; every node stop()s it
  assignee leaves the cluster  -> master reassigns; the new node starts
                                  from the replicated task state
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

SECTION = "persistent_tasks"
POLL_INTERVAL = 2.0


class PersistentTasksService:
    """Master-side assignment + node-side execution, one service."""

    def __init__(self, node) -> None:
        self.node = node
        self._running = False
        self._timer = None
        # task_name -> factory(task_id, params, service) -> runner with
        # start()/stop() (the PersistentTasksExecutor registry)
        self._executors: Dict[str, Callable] = {}
        # task_id -> runner instances running on THIS node
        self.local_running: Dict[str, Any] = {}
        self._rr = 0

    # -- SPI ---------------------------------------------------------------

    def register_executor(self, task_name: str, factory: Callable) -> None:
        if task_name in self._executors:
            raise ValueError(
                f"executor already registered for [{task_name}]")
        self._executors[task_name] = factory

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._schedule()

    def stop(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()
        for task_id in list(self.local_running):
            self._stop_local(task_id)

    def _schedule(self) -> None:
        if not self._running:
            return
        self._timer = self.node.scheduler.schedule(POLL_INTERVAL,
                                                   self._tick)

    def _tick(self) -> None:
        if not self._running:
            return
        try:
            if self.node.coordinator.mode == "LEADER":
                self.assign_pass()
            self.reconcile_local()
        except Exception:  # noqa: BLE001 — the loop must survive anything
            logger.exception("persistent tasks tick failed")
        self._schedule()

    # -- state access ------------------------------------------------------

    def tasks(self) -> Dict[str, Any]:
        return dict(self.node._applied_state()
                    .metadata.custom.get(SECTION, {}))

    # -- API ---------------------------------------------------------------

    def submit(self, task_id: str, task_name: str,
               params: Optional[Dict[str, Any]], on_done) -> None:
        """Register a task; the master assigns it on its next pass. The
        duplicate check happens master-side against authoritative state
        (create-only semantics), so retries cannot clobber a live task."""
        from elasticsearch_tpu.action.admin import PERSISTENT_UPDATE
        if task_name not in self._executors:
            on_done(None, ValueError(
                f"no executor registered for task type [{task_name}]"))
            return
        self.node.master_client.execute(PERSISTENT_UPDATE, {
            "task_id": task_id,
            "create": {"task_name": task_name,
                       "params": dict(params or {}),
                       "assignment": None, "state": {}}}, on_done)

    def complete(self, task_id: str, on_done) -> None:
        from elasticsearch_tpu.action.admin import DELETE_CUSTOM
        self.node.master_client.execute(
            DELETE_CUSTOM, {"section": SECTION, "name": task_id}, on_done)

    def update_state(self, task_id: str, state: Dict[str, Any],
                     on_done) -> None:
        """Replicate task progress (PersistentTaskState analog) so a
        reassigned runner resumes from it. Field-level merge on the
        master (PERSISTENT_UPDATE): a caller-side read-modify-write
        would race a concurrent reassignment and clobber it."""
        from elasticsearch_tpu.action.admin import PERSISTENT_UPDATE
        self.node.master_client.execute(PERSISTENT_UPDATE, {
            "task_id": task_id, "set": {"state": dict(state)}}, on_done)

    # -- master: assignment ------------------------------------------------

    def assign_pass(self) -> None:
        """Assign unassigned tasks; reassign tasks whose node left
        (PersistentTasksClusterService.shouldReassign)."""
        state = self.node._applied_state()
        live = sorted(state.nodes)
        if not live:
            return
        for task_id, entry in self.tasks().items():
            assignment = entry.get("assignment")
            if assignment is not None and assignment in live:
                continue
            blocked = set(entry.get("blocked_nodes") or [])
            eligible = [n for n in live if n not in blocked]
            if not eligible:
                # every live node has bounced this task: start-failures
                # are often transient, so RESET the block list and retry
                # the full rotation next pass instead of stranding the
                # task forever
                if blocked:
                    logger.warning(
                        "persistent task [%s]: all nodes blocked, "
                        "resetting for retry", task_id)
                    self._merge(task_id, {"blocked_nodes": []})
                continue
            self._rr += 1
            node_id = eligible[self._rr % len(eligible)]
            logger.info("persistent task [%s] -> node [%s]", task_id,
                        node_id)
            self._merge(task_id, {"assignment": node_id})

    def _merge(self, task_id: str, fields: Dict[str, Any]) -> None:
        from elasticsearch_tpu.action.admin import PERSISTENT_UPDATE
        self.node.master_client.execute(
            PERSISTENT_UPDATE, {"task_id": task_id, "set": fields},
            lambda _r, _e: None)

    # -- node: execution ---------------------------------------------------

    def reconcile_local(self) -> None:
        """Start tasks assigned to this node; stop ones that moved away
        or completed (PersistentTasksNodeService.startTask/cancel)."""
        tasks = self.tasks()
        for task_id, entry in tasks.items():
            mine = entry.get("assignment") == self.node.node_id
            running = task_id in self.local_running
            if mine and not running:
                factory = self._executors.get(entry.get("task_name"))
                if factory is None:
                    # this node cannot run the task (executor not
                    # registered here): hand the assignment back and
                    # record the gap so the master's next pass picks a
                    # DIFFERENT node instead of stalling forever
                    blocked = sorted(set(entry.get("blocked_nodes")
                                         or []) | {self.node.node_id})
                    self._merge(task_id, {"assignment": None,
                                          "blocked_nodes": blocked})
                    continue
                try:
                    runner = factory(task_id,
                                     dict(entry.get("params") or {}),
                                     self)
                    self.local_running[task_id] = runner
                    start = getattr(runner, "start", None)
                    if start is not None:
                        start()
                except Exception:  # noqa: BLE001
                    logger.exception("persistent task [%s] failed to "
                                     "start", task_id)
                    self.local_running.pop(task_id, None)
                    # a node-local start failure pins nothing: hand the
                    # assignment back like the missing-executor case so
                    # the master tries a DIFFERENT node next pass
                    blocked = sorted(set(entry.get("blocked_nodes")
                                         or []) | {self.node.node_id})
                    self._merge(task_id, {"assignment": None,
                                          "blocked_nodes": blocked})
            elif running and not mine:
                self._stop_local(task_id)
        for task_id in [t for t in self.local_running if t not in tasks]:
            self._stop_local(task_id)

    def _stop_local(self, task_id: str) -> None:
        runner = self.local_running.pop(task_id, None)
        stop = getattr(runner, "stop", None)
        if stop is not None:
            try:
                stop()
            except Exception:  # noqa: BLE001
                logger.exception("persistent task [%s] failed to stop",
                                 task_id)
