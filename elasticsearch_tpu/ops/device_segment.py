"""Device mirrors of segment data, and the packed multi-segment plane.

Each searchable segment gets lazily-built, cached device arrays with
power-of-two padded shapes (bucketing keeps the jit cache warm across
segment growth/merge — SURVEY.md §7 hard part #3). The host Segment stays
the source of truth; device mirrors are pure caches.

The second half of this module is the **shard plane** (ROADMAP item 1):
a shard's live segments concatenated along the docs axis into ONE
device-resident padded plane per (kind, field) — postings blocks,
dense-vector matrices and rank_features blocks with per-segment base
offsets — so a whole shard's kNN / IVF probe / sparse scoring / WAND
recount is one device program regardless of segment count. The
per-segment boundary is an indexing artifact, not a scoring one (the
reference's shard-level reader over per-segment Lucene leaves); here it
survives only as a host-side offset translation (``PlanePart.demux``).
Planes rebuild incrementally on refresh: per-segment rebased arrays are
cached by segment uid, so an append-only refresh recomputes just the new
segment and re-packs; a merge (prefix change) pays a full rebuild.
Residency is budgeted: every plane charges the ``device`` breaker before
upload and the registry LRU-evicts cold planes, so a shard whose plane
cannot fit degrades to the per-segment path instead of OOMing.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.index.segment import (
    BLOCK, FeaturesField, PostingsField, Segment, VectorField, next_pow2,
)
from elasticsearch_tpu.utils.errors import CircuitBreakingError


class DevicePostings:
    """Device-resident postings for one text field of one segment."""

    def __init__(self, pf: PostingsField, n_docs: int):
        self.n_docs = n_docs
        self.n_docs_pad = next_pow2(max(n_docs, 1), minimum=BLOCK)
        n_blocks = pf.block_docs.shape[0]
        self.n_blocks_pad = next_pow2(n_blocks)
        # pad blocks with an empty sentinel block (all -1 docs)
        pad = self.n_blocks_pad - n_blocks
        block_docs = np.pad(pf.block_docs, ((0, pad), (0, 0)), constant_values=-1)
        block_tfs = np.pad(pf.block_tfs, ((0, pad), (0, 0)))
        doc_lens = np.zeros(self.n_docs_pad, np.float32)
        doc_lens[: len(pf.doc_lens)] = pf.doc_lens
        block_max_tf = np.pad(pf.block_max_tf, (0, pad))
        # budget check BEFORE the HBM upload (breaker must gate, not observe)
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        account_device_arrays(
            self, (block_docs, block_tfs, doc_lens, block_max_tf),
            "postings")
        self.block_docs = jnp.asarray(block_docs)
        self.block_tfs = jnp.asarray(block_tfs)
        self.doc_lens = jnp.asarray(doc_lens)
        self.avgdl = float(pf.sum_doc_len / max(1, (pf.doc_lens > 0).sum()))
        self.block_max_tf = jnp.asarray(block_max_tf)

    @staticmethod
    def for_segment(seg: Segment, field_name: str) -> Optional["DevicePostings"]:
        pf = seg.postings.get(field_name)
        if pf is None:
            return None
        return seg.device(("postings", field_name),
                          lambda: DevicePostings(pf, seg.n_docs))


class DeviceVectors:
    """Device-resident dense-vector matrix for one field of one segment."""

    def __init__(self, vf: VectorField, n_docs: int):
        self.n_docs = n_docs
        self.n_docs_pad = next_pow2(max(n_docs, 1), minimum=BLOCK)
        self.dims = vf.dims
        pad = self.n_docs_pad - vf.matrix.shape[0]
        matrix = np.pad(vf.matrix, ((0, pad), (0, 0)))
        norms = np.pad(vf.norms, (0, pad))
        exists = np.zeros(self.n_docs_pad, bool)
        exists[: len(vf.exists)] = vf.exists
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        account_device_arrays(self, (matrix, norms, exists), "vectors")
        self.matrix = jnp.asarray(matrix)
        self.norms = jnp.asarray(norms)
        self.exists = jnp.asarray(exists)
        self.similarity = vf.similarity

    @staticmethod
    def for_segment(seg: Segment, field_name: str) -> Optional["DeviceVectors"]:
        vf = seg.vectors.get(field_name)
        if vf is None:
            return None
        return seg.device(("vectors", field_name),
                          lambda: DeviceVectors(vf, seg.n_docs))


class DeviceFeatures:
    """Device-resident rank_features blocks for one field of one segment."""

    def __init__(self, ff: FeaturesField, n_docs: int):
        self.n_docs = n_docs
        self.n_docs_pad = next_pow2(max(n_docs, 1), minimum=BLOCK)
        n_blocks = ff.block_docs.shape[0]
        self.n_blocks_pad = next_pow2(n_blocks)
        pad = self.n_blocks_pad - n_blocks
        block_docs = np.pad(ff.block_docs, ((0, pad), (0, 0)),
                            constant_values=-1)
        block_weights = np.pad(ff.block_weights, ((0, pad), (0, 0)))
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        account_device_arrays(self, (block_docs, block_weights), "features")
        self.block_docs = jnp.asarray(block_docs)
        self.block_weights = jnp.asarray(block_weights)

    @staticmethod
    def for_segment(seg: Segment, field_name: str) -> Optional["DeviceFeatures"]:
        ff = seg.features.get(field_name)
        if ff is None:
            return None
        return seg.device(("features", field_name),
                          lambda: DeviceFeatures(ff, seg.n_docs))


def device_live_mask(seg: Segment) -> jnp.ndarray:
    """Live mask padded to the doc bucket (True = scoreable)."""
    n_pad = next_pow2(max(seg.n_docs, 1), minimum=BLOCK)

    def build():
        m = np.zeros(n_pad, bool)
        m[: seg.n_docs] = seg.live
        return jnp.asarray(m)

    return seg.device("live", build)


def gather_query_blocks(pf: PostingsField, terms_with_weights, n_blocks_bucket_min: int = 8
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side prep for a query: list every posting block of every query
    term, with its per-block weight (e.g. idf). Returns (block_indices int32
    [QB_pad], block_weights float32 [QB_pad]) padded to a pow2 bucket so the
    device gather has a bucketed static shape. Padding uses block 0 with
    weight 0 (contributes nothing). Per-term block lists come from the
    field's immutable cache (PostingsField.term_block_idx), so repeat terms
    across the query stream pay the list construction once per refresh."""
    idx_parts: list = []
    w_parts: list = []
    for term, weight in terms_with_weights:
        t_idx = pf.term_block_idx(term)
        if not len(t_idx):
            continue
        idx_parts.append(t_idx)
        w_parts.append(np.full(len(t_idx), weight, np.float32))
    n = sum(len(p) for p in idx_parts)
    qb_pad = next_pow2(max(n, 1), minimum=n_blocks_bucket_min)
    out_idx = np.zeros(qb_pad, np.int32)
    out_w = np.zeros(qb_pad, np.float32)
    if idx_parts:
        out_idx[:n] = np.concatenate(idx_parts)
        out_w[:n] = np.concatenate(w_parts)
    return out_idx, out_w


# ---------------------------------------------------------------------------
# packed multi-segment device plane
# ---------------------------------------------------------------------------

class PlaneUnavailable(Exception):
    """The field has no data in any of the shard's segments — there is
    nothing to plane; callers take the per-segment path."""


class PlanePart:
    """Base of one (kind, field) plane over one ordered segment set.

    ``doc_base[i]`` is the plane doc offset of segment i (reader order,
    ALL segments, field-less ones included), so plane doc ids are stable
    across kinds and map 1:1 onto (segment_idx, local_doc)."""

    kind = "?"

    def __init__(self, field: str, segments: List[Segment]):
        self.field = field
        self.segments = list(segments)
        self.uids = tuple(s.uid for s in segments)
        counts = np.asarray([s.n_docs for s in segments], np.int64)
        self.doc_base = np.zeros(max(len(segments), 1), np.int64)
        if len(counts) > 1:
            self.doc_base[1: len(counts)] = np.cumsum(counts)[:-1]
        self.n_docs_total = int(counts.sum()) if len(counts) else 0
        self.n_docs_pad = next_pow2(max(self.n_docs_total, 1), minimum=BLOCK)
        # per-segment rebased host arrays keyed by uid: the incremental
        # refresh path copies matching entries from the previous
        # generation and recomputes only appended segments
        self._seg_cache: Dict[int, Any] = {}
        self.nbytes = 0
        # DeviceCharge handles for everything this part pinned on device;
        # eviction releases them ahead of GC so the breaker-pressure
        # retry can actually free budget
        self._charges: List[Any] = []

    def release(self) -> None:
        for charge in self._charges:
            charge.release()

    def demux(self, plane_docs: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
        """plane doc ids -> (segment positions, local doc ids)."""
        docs = np.asarray(plane_docs, np.int64)
        si = np.searchsorted(self.doc_base[: len(self.segments)], docs,
                             side="right") - 1
        si = np.maximum(si, 0)
        return si, docs - self.doc_base[si]

    def live_mask(self, live_masks) -> jnp.ndarray:
        """Reader-snapshot live masks stacked into plane doc space (padding
        False). Built per query, like the per-segment snapshot uploads —
        deletes therefore never invalidate the plane itself."""
        out = np.zeros(self.n_docs_pad, bool)
        off = 0
        for m in live_masks:
            out[off: off + len(m)] = m
            off += len(m)
        return jnp.asarray(out)

    # subclasses: build(prev) -> host arrays tuple (breaker-checked by the
    # registry BEFORE upload), then upload(host) pins them on device.


def _seg_ids_host(doc_base, n_segs: int, length: int) -> np.ndarray:
    """[length] int32: owning segment POSITION per plane doc (searchsorted
    right - 1 over the first n_segs doc bases, clamped at 0). Docs past
    the packed corpus clamp to the last segment — they are padding, never
    live. The one attribution rule shared by the single-shard plane's
    counting channel and the mesh stacking pass."""
    ids = np.searchsorted(np.asarray(doc_base[:n_segs]),
                          np.arange(length, dtype=np.int64),
                          side="right") - 1
    return np.maximum(ids, 0).astype(np.int32)


class PlanePostings(PlanePart):
    """All segments' posting blocks for one text field, doc ids rebased.

    ``block_avgdl`` (host) carries each block's OWNING SEGMENT avgdl, so
    the flat BM25 kernel computes the exact per-segment length norm the
    solo path uses — plane scores match per-segment scores, not a blended
    shard-wide normalization."""

    kind = "postings"

    def build(self, prev: Optional["PlanePostings"]):
        refs = []           # (seg_pos, PostingsField, block_base, avgdl)
        blocks_docs, blocks_tfs, block_avg = [], [], []
        doc_lens_parts = []
        nb = 0
        for pos, seg in enumerate(self.segments):
            pf = seg.postings.get(self.field)
            n = seg.n_docs
            if pf is None:
                doc_lens_parts.append(np.zeros(n, np.float32))
                continue
            cached = prev._seg_cache.get(seg.uid) if prev is not None \
                else None
            if cached is None:
                base = int(self.doc_base[pos])
                r_docs = np.where(pf.block_docs >= 0,
                                  pf.block_docs + base, -1).astype(np.int32)
                avgdl = float(pf.sum_doc_len
                              / max(1, (pf.doc_lens > 0).sum()))
                dl = np.zeros(n, np.float32)
                dl[: min(n, len(pf.doc_lens))] = pf.doc_lens[:n]
                cached = (r_docs, pf.block_tfs, dl, avgdl)
            self._seg_cache[seg.uid] = cached
            r_docs, r_tfs, dl, avgdl = cached
            refs.append((pos, pf, nb, avgdl))
            blocks_docs.append(r_docs)
            blocks_tfs.append(r_tfs)
            block_avg.append(np.full(r_docs.shape[0], avgdl, np.float32))
            doc_lens_parts.append(dl)
            nb += r_docs.shape[0]
        if not refs:
            raise PlaneUnavailable(self.field)
        self.refs = refs
        self.n_blocks = nb
        nb_pad = next_pow2(max(nb, 1))
        bd = np.full((nb_pad, BLOCK), -1, np.int32)
        bt = np.zeros((nb_pad, BLOCK), np.float32)
        ba = np.ones(nb_pad, np.float32)
        bd[:nb] = np.concatenate(blocks_docs)
        bt[:nb] = np.concatenate(blocks_tfs)
        ba[:nb] = np.concatenate(block_avg)
        dl_all = np.zeros(self.n_docs_pad, np.float32)
        off = 0
        for p in doc_lens_parts:
            dl_all[off: off + len(p)] = p
            off += len(p)
        # block_avgdl stays HOST-side: the flat dispatch gathers it per
        # plan into the [FB] kernel argument
        self.block_avgdl = ba
        self._q_dev: Optional[Tuple] = None
        self._q_failed = False
        return (bd, bt, dl_all)

    def upload(self, host) -> None:
        bd, bt, dl = host
        self.block_docs = jnp.asarray(bd)
        self.block_tfs = jnp.asarray(bt)
        self.doc_lens = jnp.asarray(dl)

    def quantized_mirror(self) -> Optional[Tuple]:
        """(block_tfs bf16 [NB, BLOCK] device, doc_lens bf16 [N_pad]
        device) — the coarse tier's reduced-precision gather operands,
        following the PlaneVectors.quantized_mirror precedent: built
        lazily on the FIRST coarse query, cached per plane generation,
        separately breaker-charged, and a budget refusal is remembered so
        a starved node never re-quantizes per query. Doc ids stay int32
        (they are gather indices, shared with the exact arrays)."""
        if self._q_dev is not None:
            return self._q_dev
        if self._q_failed:
            return None
        tf16 = np.asarray(self.block_tfs).astype(jnp.bfloat16)
        dl16 = np.asarray(self.doc_lens).astype(jnp.bfloat16)
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        try:
            charge = account_device_arrays(
                self, (tf16, dl16), f"plane_postings_q:{self.field}",
                return_charge=True)
        except CircuitBreakingError:
            self._q_failed = True
            return None
        self._charges.append(charge)
        self.nbytes += charge.n_bytes
        self._q_dev = (jnp.asarray(tf16), jnp.asarray(dl16))
        return self._q_dev

    def seg_ids(self) -> jnp.ndarray:
        """[n_docs_pad] int32: each plane doc's owning segment POSITION
        (reader order) — the per-segment counting channel of the
        totals-disabled plane path. Padding docs never match (live is
        False there), so their attribution is irrelevant; they clamp to
        the last segment."""
        cached = getattr(self, "_seg_ids_dev", None)
        if cached is None:
            cached = jnp.asarray(_seg_ids_host(
                self.doc_base, len(self.segments), self.n_docs_pad))
            self._seg_ids_dev = cached
        return cached


class PlaneVectors(PlanePart):
    """All segments' dense-vector rows for one field, stacked [N_pad, D],
    plus an int8 symmetric-quantized mirror (built host-side at pack time,
    uploaded lazily) for the coarse scoring pass."""

    kind = "vectors"

    def build(self, prev: Optional["PlaneVectors"]):
        dims, similarity = None, "cosine"
        for seg in self.segments:
            vf = seg.vectors.get(self.field)
            if vf is not None:
                dims, similarity = vf.dims, vf.similarity
                break
        if dims is None:
            raise PlaneUnavailable(self.field)
        self.dims, self.similarity = dims, similarity
        matrix = np.zeros((self.n_docs_pad, dims), np.float32)
        norms = np.zeros(self.n_docs_pad, np.float32)
        exists = np.zeros(self.n_docs_pad, bool)
        for pos, seg in enumerate(self.segments):
            vf = seg.vectors.get(self.field)
            if vf is None:
                continue
            cached = prev._seg_cache.get(seg.uid) if prev is not None \
                else None
            if cached is None:
                n = seg.n_docs
                ex = np.zeros(n, bool)
                ex[: min(n, len(vf.exists))] = vf.exists[:n]
                cached = (vf.matrix, vf.norms, ex)
            self._seg_cache[seg.uid] = cached
            m, nr, ex = cached
            base = int(self.doc_base[pos])
            matrix[base: base + len(ex)] = m[: len(ex)]
            norms[base: base + len(ex)] = nr[: len(ex)]
            exists[base: base + len(ex)] = ex
        self._q_dev: Optional[Tuple] = None
        self._q_failed = False
        self._ivf = None
        # warm-start seed for this generation's k-means: the previous
        # generation's trained centroids (an append-only refresh barely
        # moves them, so Lloyd's converges in a fraction of the cold
        # iterations instead of retraining from scratch)
        self._ivf_seed = None
        if prev is not None:
            prev_ivf = getattr(prev, "_ivf", None)
            if prev_ivf is not None and prev_ivf[0] is not None:
                self._ivf_seed = np.asarray(prev_ivf[0].centroids,
                                            np.float32)
        self.rows = np.nonzero(exists[: self.n_docs_total])[0] \
            .astype(np.int64)
        return (matrix, norms, exists)

    def upload(self, host) -> None:
        matrix, norms, exists = host
        self.matrix = jnp.asarray(matrix)
        self.norms = jnp.asarray(norms)
        self.exists = jnp.asarray(exists)

    def quantized_mirror(self) -> Optional[Tuple]:
        """(q8 [N_pad, D] int8 device, scales [N_pad] f32 device) — int8
        symmetric per-row quantization, built lazily on the FIRST
        quantized query (planes served exact/IVF-only never pay the
        quantization or its residency) and cached per plane generation.
        None when the upload would trip the device breaker — the exact
        plane path still serves, and the refusal is remembered so a
        budget-starved node doesn't re-quantize per query."""
        if self._q_dev is not None:
            return self._q_dev
        if self._q_failed:
            return None
        matrix = np.asarray(self.matrix)   # one D2H per plane generation
        amax = np.abs(matrix).max(axis=1)
        scales = np.maximum(amax / 127.0, 1e-30).astype(np.float32)
        q8 = np.clip(np.round(matrix / scales[:, None]),
                     -127, 127).astype(np.int8)
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        try:
            charge = account_device_arrays(
                self, (q8, scales), f"plane_vectors_q:{self.field}",
                return_charge=True)
        except CircuitBreakingError:
            self._q_failed = True
            return None
        self._charges.append(charge)
        self.nbytes += charge.n_bytes   # residency stats see the mirror
        self._q_dev = (jnp.asarray(q8), jnp.asarray(scales))
        return self._q_dev

    def ivf_index(self, nlist: Optional[int]):
        """Shard-level IVF over the plane's vectors (rows = plane doc ids
        holding a vector), built once per plane generation and shared by
        the solo rewrite and the batched executor so their ANN results
        cannot diverge. A breaker-refused build is memoized for the
        plane's lifetime (a new generation retries) — re-running the full
        k-means per query just to trip the breaker again would be the
        worst possible degradation."""
        if self._ivf is None:
            if getattr(self, "_ivf_failed", False):
                raise CircuitBreakingError(
                    f"[device] ivf index for [{self.field}] was refused "
                    f"by the HBM budget")
            if not len(self.rows):
                self._ivf = (None, self.rows)
            else:
                from elasticsearch_tpu.ops.ivf import IVFIndex
                host = np.asarray(self.matrix)[self.rows]
                try:
                    index = IVFIndex.build(
                        host, nlist=nlist, similarity=self.similarity,
                        init_centroids=getattr(self, "_ivf_seed", None))
                except CircuitBreakingError:
                    self._ivf_failed = True
                    raise
                if getattr(index, "warm_started", False):
                    PLANES.stats["ivf_warm_starts"] += 1
                # the index's HBM is part of this plane's residency:
                # eviction must release its charge early too, and stats
                # must count it
                charge = getattr(index, "_charge", None)
                if charge is not None:
                    self._charges.append(charge)
                    self.nbytes += charge.n_bytes
                self._ivf = (index, self.rows)
        return self._ivf


class PlaneFeatures(PlanePart):
    """All segments' rank_features blocks for one field, doc ids rebased."""

    kind = "features"

    def build(self, prev: Optional["PlaneFeatures"]):
        refs = []           # (seg_pos, FeaturesField, block_base)
        blocks_docs, blocks_w = [], []
        nb = 0
        for pos, seg in enumerate(self.segments):
            ff = seg.features.get(self.field)
            if ff is None:
                continue
            cached = prev._seg_cache.get(seg.uid) if prev is not None \
                else None
            if cached is None:
                base = int(self.doc_base[pos])
                r_docs = np.where(ff.block_docs >= 0,
                                  ff.block_docs + base, -1).astype(np.int32)
                cached = (r_docs, ff.block_weights)
            self._seg_cache[seg.uid] = cached
            r_docs, r_w = cached
            refs.append((pos, ff, nb))
            blocks_docs.append(r_docs)
            blocks_w.append(r_w)
            nb += r_docs.shape[0]
        if not refs:
            raise PlaneUnavailable(self.field)
        self.refs = refs
        self.n_blocks = nb
        nb_pad = next_pow2(max(nb, 1))
        bd = np.full((nb_pad, BLOCK), -1, np.int32)
        bw = np.zeros((nb_pad, BLOCK), np.float32)
        bd[:nb] = np.concatenate(blocks_docs)
        bw[:nb] = np.concatenate(blocks_w)
        self._q_dev: Optional[Any] = None
        self._q_failed = False
        return (bd, bw)

    def upload(self, host) -> None:
        bd, bw = host
        self.block_docs = jnp.asarray(bd)
        self.block_weights = jnp.asarray(bw)

    def quantized_mirror(self) -> Optional[Any]:
        """block_weights bf16 [NB, BLOCK] device — the sparse coarse
        tier's reduced-precision gather operand; same lazy-build /
        per-generation cache / refusal-memo contract as the postings and
        vector mirrors."""
        if self._q_dev is not None:
            return self._q_dev
        if self._q_failed:
            return None
        w16 = np.asarray(self.block_weights).astype(jnp.bfloat16)
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        try:
            charge = account_device_arrays(
                self, (w16,), f"plane_features_q:{self.field}",
                return_charge=True)
        except CircuitBreakingError:
            self._q_failed = True
            return None
        self._charges.append(charge)
        self.nbytes += charge.n_bytes
        self._q_dev = jnp.asarray(w16)
        return self._q_dev


class PlaneColumns(PlanePart):
    """All segments' aggregation columns for one field, concatenated into
    plane doc space: the numeric/date doc-values column (int32 + exists)
    and/or the keyword ordinal occurrence table with ordinals remapped to
    GLOBAL (plane-wide, sorted term union) space at pack time. One field
    commonly has only one side; the other uploads as zero-length arrays
    and costs nothing.

    The numeric side keeps the per-segment device collector's exactness
    gates (single-valued, integral dtype, |v| < 2^24 so the fused f32
    sum/min/max stay exact) — a segment that fails them poisons only the
    numeric side, with the TYPED reason kept on the part so the agg
    planner can record why it fell back to the host collector while the
    keyword side keeps serving."""

    kind = "columns"

    def _pack_segment(self, seg: Segment):
        """(numeric_entry, keyword_entry) host cache for one segment,
        keyed by uid so incremental append reuses it verbatim. Keyword
        ordinals stay LOCAL here — the global remap depends on the whole
        segment set and is recomputed per pack (cheap host work)."""
        num = None
        dv = seg.doc_values.get(self.field)
        if dv is not None:
            if dv.multi:
                num = ("ineligible", "multi_valued")
            elif dv.values.dtype.kind != "i":
                num = ("ineligible", "non_integer")
            else:
                docs = np.nonzero(dv.exists)[0]
                vmin = int(dv.values[docs].min()) if len(docs) else None
                vmax = int(dv.values[docs].max()) if len(docs) else None
                if vmax is not None and \
                        max(abs(vmin), abs(vmax)) >= 2 ** 24:
                    # same gate as the per-segment device histogram:
                    # int32-safe AND f32-exact (epoch-millis dates land
                    # here and keep the host path)
                    num = ("ineligible", "magnitude")
                else:
                    n = seg.n_docs
                    ex = np.zeros(n, bool)
                    ex[: min(n, len(dv.exists))] = dv.exists[:n]
                    vals = np.zeros(n, np.int32)
                    m = min(n, len(dv.values))
                    vals[:m] = np.where(ex[:m], dv.values[:m],
                                        0).astype(np.int32)
                    num = ("ok", vals, ex, vmin, vmax)
        kw = None
        kf = seg.keywords.get(self.field) if \
            hasattr(seg, "keywords") else None
        if kf is not None:
            counts = np.diff(kf.ord_offsets)
            owners = np.repeat(np.arange(len(counts), dtype=np.int32),
                               counts)
            ords = kf.ord_values.astype(np.int32)
            if len(owners):
                # dedup (doc, ord) at pack time — same rule as the
                # per-segment occurrence table (_dedup_doc_ord): a doc
                # counts once per term even when the stored array
                # repeats a value
                pair = owners.astype(np.int64) \
                    * max(len(kf.term_list), 1) + ords
                _, first = np.unique(pair, return_index=True)
                owners, ords = owners[first], ords[first]
            kw = (owners, ords, list(kf.term_list))
        return (num, kw)

    def build(self, prev: Optional["PlaneColumns"]):
        values = np.zeros(self.n_docs_pad, np.int32)
        exists = np.zeros(self.n_docs_pad, bool)
        have_num, num_reason = False, None
        vmin, vmax = None, None
        kw_parts = []     # (base, owners_local, ords_local, term_list)
        for pos, seg in enumerate(self.segments):
            cached = prev._seg_cache.get(seg.uid) if prev is not None \
                else None
            if cached is None:
                cached = self._pack_segment(seg)
            self._seg_cache[seg.uid] = cached
            num, kw = cached
            base = int(self.doc_base[pos])
            if num is not None:
                have_num = True
                if num[0] == "ineligible":
                    num_reason = num_reason or num[1]
                else:
                    _, vals, ex, s_min, s_max = num
                    values[base: base + len(ex)] = vals[: len(ex)]
                    exists[base: base + len(ex)] = ex
                    if s_min is not None:
                        vmin = s_min if vmin is None else min(vmin, s_min)
                        vmax = s_max if vmax is None else max(vmax, s_max)
            if kw is not None:
                kw_parts.append((base,) + kw)
        if not have_num and not kw_parts:
            raise PlaneUnavailable(self.field)
        self.has_numeric = have_num and num_reason is None
        self.num_reason = num_reason
        self.vmin, self.vmax = vmin, vmax
        # global-ordinal remap: plane term space is the SORTED union of
        # the segment term lists, so bucket keys come straight off the
        # global ordinal and per-segment ords never leak upward
        term_list: List = sorted({t for p in kw_parts for t in p[3]})
        gid = {t: i for i, t in enumerate(term_list)}
        self.has_keyword = bool(kw_parts)
        self.term_list = term_list
        self.n_terms = len(term_list)
        own_parts, ord_parts = [], []
        for base, owners, ords, terms in kw_parts:
            if not len(owners):
                continue
            lookup = np.asarray([gid[t] for t in terms], np.int32) \
                if terms else np.empty(0, np.int32)
            own_parts.append(owners.astype(np.int64) + base)
            ord_parts.append(lookup[ords])
        n_occ = sum(len(p) for p in own_parts)
        self.n_occurrences = n_occ
        if kw_parts:
            e_pad = next_pow2(max(n_occ, 1), minimum=8)
            kw_owners = np.zeros(e_pad, np.int32)
            kw_ords = np.full(e_pad, -1, np.int32)
            if n_occ:
                kw_owners[:n_occ] = np.concatenate(own_parts)
                kw_ords[:n_occ] = np.concatenate(ord_parts)
        else:
            kw_owners = np.empty(0, np.int32)
            kw_ords = np.empty(0, np.int32)
        if not self.has_numeric:
            values = np.empty(0, np.int32)
            exists = np.empty(0, bool)
        return (values, exists, kw_owners, kw_ords)

    def upload(self, host) -> None:
        values, exists, kw_owners, kw_ords = host
        self.values = jnp.asarray(values)
        self.exists = jnp.asarray(exists)
        self.kw_owners = jnp.asarray(kw_owners)
        self.kw_ords = jnp.asarray(kw_ords)


_PART_CLASSES = {"postings": PlanePostings, "vectors": PlaneVectors,
                 "features": PlaneFeatures, "columns": PlaneColumns}


def _count_reason(reason: str) -> None:
    """Typed data-plane routing record, shared by both plane registries
    (search/telemetry.py taxonomy — the telemetry suite pins the
    "unknown" bucket at zero, so a drifted literal here fails CI); lazy
    import: ops must not import the search package at load time."""
    from elasticsearch_tpu.search.telemetry import TELEMETRY
    TELEMETRY.count_fallback(reason)


class PlaneRegistry:
    """Process-global plane residency manager: build-on-demand keyed by
    (kind, field, segment uid tuple), incremental append across refresh
    generations, LRU + breaker-aware eviction. ``get`` returning None
    means "serve this query per-segment" — the plane is an optimization,
    never a correctness gate."""

    MAX_PARTS = 64
    MAX_REFUSALS = 128

    def __init__(self):
        self._parts: "OrderedDict[Tuple, PlanePart]" = OrderedDict()
        # keys refused by the budget, with the budget "token" they were
        # refused under: an over-budget shard must fast-miss (no per-query
        # host re-pack, no shedding every other shard's hot planes) until
        # either a refresh changes its key or the budget itself changes
        self._refused: "OrderedDict[Tuple, Tuple]" = OrderedDict()
        # dynamic config (search.plane.* cluster settings; applied via
        # configure_from_state on nodes, directly in unit tests/bench)
        self.enabled = True
        self.min_segments = 2
        self.rerank_depth = 128
        self.rerank_depth_max = 1024
        self.quantized = True
        self.max_bytes = 0          # 0 = breaker-only budgeting
        self.stats: Dict[str, int] = {
            "plane_builds": 0,
            "plane_full_rebuilds": 0,
            "plane_incremental_appends": 0,
            "plane_evictions": 0,
            "plane_miss_fallbacks": 0,
            "quantized_queries": 0,
            "rerank_escalations": 0,
            "quantized_exact_fallbacks": 0,
            "quantized_disengaged_slow": 0,
            "ivf_warm_starts": 0,
            "plane_aggs_queries": 0,
            "plane_aggs_fallbacks": 0,
        }
        # measured-latency engage rule for the quantized coarse tier:
        # per-(query class, tier) EWMAs of observed per-query serve
        # latency. On backends where bf16 is emulated (the CPU-fallback
        # box) the coarse pass can measure SLOWER than exact — the
        # corpus-size gate alone cannot see that, so the registry
        # compares what it actually measured and disengages the tier,
        # still probing occasionally so a backend change can re-engage.
        self._lat_ewma: Dict[Tuple[str, str], float] = {}
        self._lat_n: Dict[Tuple[str, str], int] = {}
        self._probe_counter: Dict[str, int] = {}
        # adaptive re-rank depth histogram: served depth -> query count
        # (the k' each query's margin actually settled at — the coarse
        # tier's observability surface, next to quantized_queries)
        self.rerank_depth_hist: Dict[int, int] = {}
        # device-observatory residency record: monotonically stamped
        # generations, the resident-bytes high-water mark, and WHY each
        # plane left HBM (the "device_profile" stats section)
        self._gen = 0
        self.hbm_high_water = 0
        self.evictions_by_cause: Dict[str, int] = {}

    # -- config ---------------------------------------------------------

    def configure_from_state(self, state) -> None:
        """Refresh config from committed cluster settings. Re-parsing per
        query would tax the very hot path this module shrinks, so the
        parse is memoized on the state version (settings only change
        through a committed state)."""
        version = getattr(state, "version", None)
        if version is not None and \
                version == getattr(self, "_cfg_version", None):
            return
        self._cfg_version = version
        from elasticsearch_tpu.utils.settings import (
            SEARCH_PLANE_ENABLED, SEARCH_PLANE_MAX_BYTES,
            SEARCH_PLANE_MIN_SEGMENTS, SEARCH_PLANE_QUANTIZED,
            SEARCH_PLANE_RERANK_DEPTH, SEARCH_PLANE_RERANK_DEPTH_MAX,
            setting_from_state,
        )
        self.enabled = setting_from_state(state, SEARCH_PLANE_ENABLED)
        self.min_segments = setting_from_state(state,
                                               SEARCH_PLANE_MIN_SEGMENTS)
        self.rerank_depth = setting_from_state(state,
                                               SEARCH_PLANE_RERANK_DEPTH)
        self.rerank_depth_max = setting_from_state(
            state, SEARCH_PLANE_RERANK_DEPTH_MAX)
        self.quantized = setting_from_state(state, SEARCH_PLANE_QUANTIZED)
        self.max_bytes = setting_from_state(state, SEARCH_PLANE_MAX_BYTES)

    def note_quantized(self, depth: int, n_queries: int,
                       mesh: bool = False) -> None:
        """A coarse+re-rank pass SERVED ``n_queries`` at re-rank depth
        ``depth`` (post-escalation). The adaptive-depth histogram covers
        every coarse tier; ``quantized_queries`` counts only the
        single-shard plane's serves — mesh serves have their own
        ``mesh_quantized_queries`` in the mesh section, and one query
        must not appear under both."""
        if not mesh:
            self.stats["quantized_queries"] += int(n_queries)
        self.rerank_depth_hist[int(depth)] = \
            self.rerank_depth_hist.get(int(depth), 0) + int(n_queries)

    # -- measured-latency engage rule (quantized coarse tier) -----------

    LAT_ALPHA = 0.3          # EWMA smoothing
    LAT_MIN_SAMPLES = 5      # per tier before the comparison may fire
    LAT_SLOW_MARGIN = 1.25   # coarse must be this much slower to lose
    LAT_PROBE_EVERY = 32     # disengaged tier still probes occasionally

    def note_tier_latency(self, cls: str, tier: str,
                          seconds: float) -> None:
        """Record one observed per-query serve latency for a (query
        class, tier) pair; tier is "coarse" or "exact"."""
        key = (cls, tier)
        prev = self._lat_ewma.get(key)
        self._lat_ewma[key] = float(seconds) if prev is None else \
            prev + self.LAT_ALPHA * (float(seconds) - prev)
        self._lat_n[key] = self._lat_n.get(key, 0) + 1

    def quantized_slow(self, cls: str) -> bool:
        """True when the measured coarse EWMA for this class is decisively
        slower than the exact EWMA (both with enough samples)."""
        if self._lat_n.get((cls, "coarse"), 0) < self.LAT_MIN_SAMPLES or \
                self._lat_n.get((cls, "exact"), 0) < self.LAT_MIN_SAMPLES:
            return False
        return self._lat_ewma[(cls, "coarse")] > \
            self._lat_ewma[(cls, "exact")] * self.LAT_SLOW_MARGIN

    def quantized_engaged(self, cls: str) -> bool:
        """Should this query attempt the coarse tier? The corpus-size
        gate still applies downstream; this adds the observed-latency
        comparison. A disengaged class lets every LAT_PROBE_EVERY-th
        query through so the coarse EWMA keeps tracking the backend —
        without the probe a one-time slow measurement would disengage
        the tier forever."""
        if not self.quantized_slow(cls):
            return True
        n = self._probe_counter.get(cls, 0) + 1
        self._probe_counter[cls] = n
        if n % self.LAT_PROBE_EVERY == 0:
            return True
        self.stats["quantized_disengaged_slow"] += 1
        return False

    # -- lookup / build -------------------------------------------------

    def _budget_token(self) -> Tuple:
        from elasticsearch_tpu.indices.breaker import BREAKERS
        return (int(self.max_bytes), int(BREAKERS.breaker("device").limit))

    def _refuse(self, key: Tuple) -> None:
        self.stats["plane_miss_fallbacks"] += 1
        _count_reason("plane_budget_refused")
        self._refused[key] = self._budget_token()
        while len(self._refused) > self.MAX_REFUSALS:
            self._refused.popitem(last=False)

    def get(self, segments, kind: str, field: str) -> Optional[PlanePart]:
        if not self.enabled:
            _count_reason("plane_disabled")
            return None
        segments = list(segments)
        if len(segments) < max(1, self.min_segments):
            _count_reason("plane_too_few_segments")
            return None
        key = (kind, field) + tuple(s.uid for s in segments)
        part = self._parts.get(key)
        if part is not None:
            self._parts.move_to_end(key)
            return part
        refused_under = self._refused.get(key)
        if refused_under is not None:
            if refused_under == self._budget_token():
                self.stats["plane_miss_fallbacks"] += 1
                _count_reason("plane_budget_refused")
                return None
            self._refused.pop(key, None)   # budget changed: try again
        return self._build(segments, kind, field, key)

    def _build(self, segments, kind: str, field: str, key: Tuple
               ) -> Optional[PlanePart]:
        uids = tuple(s.uid for s in segments)
        prev = None
        for k2, p2 in reversed(self._parts.items()):
            if k2[0] == kind and k2[1] == field and \
                    len(p2.uids) < len(uids) and \
                    uids[: len(p2.uids)] == p2.uids:
                prev = p2
                break
        part = _PART_CLASSES[kind](field, segments)
        try:
            host = part.build(prev)
        except PlaneUnavailable:
            _count_reason("plane_field_absent")
            return None
        part.nbytes = sum(int(a.nbytes) for a in host)
        if self.max_bytes and part.nbytes > int(self.max_bytes):
            self._refuse(key)
            return None
        from elasticsearch_tpu.indices.breaker import (
            BREAKERS, account_device_arrays,
        )
        label = f"plane_{kind}:{field}"
        charge = None
        try:
            charge = account_device_arrays(part, host, label,
                                           return_charge=True)
        except CircuitBreakingError:
            device_limit = BREAKERS.breaker("device").limit
            if 0 < device_limit < part.nbytes:
                # can NEVER fit: don't shed anyone's planes for it
                self._refuse(key)
                return None
            # evict in LRU order, ONE plane at a time, releasing each
            # charge immediately (not at GC) and retrying — so a budget
            # that fits both hot shards after dropping one cold plane
            # keeps the other hot plane resident instead of ping-ponging
            while self._parts:
                self._drop(next(iter(self._parts)),
                           cause="breaker_pressure")
                try:
                    charge = account_device_arrays(part, host, label,
                                                   return_charge=True)
                    break
                except CircuitBreakingError:
                    continue
            if charge is None:
                self._refuse(key)
                return None
        part._charges.append(charge)
        part.upload(host)
        self.stats["plane_builds"] += 1
        part.generation = self._gen
        part.built_at = time.monotonic()
        self._gen += 1
        if prev is not None:
            self.stats["plane_incremental_appends"] += 1
            # the superseded generation is NOT dropped eagerly: a
            # point-in-time reader (scroll) acquired before the refresh
            # still queries the old segment set, and dropping it here
            # would force a full re-pack on its next query. It ages out
            # via LRU, merge invalidation, or the breaker-pressure shed.
        else:
            self.stats["plane_full_rebuilds"] += 1
        self._parts[key] = part
        self.hbm_high_water = max(
            self.hbm_high_water,
            sum(p.nbytes for p in self._parts.values()))
        while len(self._parts) > self.MAX_PARTS:
            self._drop(next(iter(self._parts)), cause="lru")
        return part

    # -- eviction / lifecycle -------------------------------------------

    def _drop(self, key: Tuple, count_eviction: bool = True,
              cause: str = "lru") -> None:
        part = self._parts.pop(key, None)
        if part is None:
            return
        part.release()      # budget back NOW; GC finalizers then no-op
        self.evictions_by_cause[cause] = \
            self.evictions_by_cause.get(cause, 0) + 1
        if count_eviction:
            self.stats["plane_evictions"] += 1

    def evict_cold(self) -> int:
        """Drop every resident plane (LRU pressure valve for a breaker
        trip), releasing their breaker charges immediately. In-flight
        queries keep their part's ARRAYS alive through their own
        references until they finish — the transient undercount is the
        eviction working as intended."""
        n = len(self._parts)
        for key in list(self._parts):
            self._drop(key, cause="breaker_pressure")
        return n

    def drop_segments(self, uids) -> None:
        """Invalidate every plane touching any of these segment uids —
        the merge path: merged-away segments are dead weight on device
        and their planes can never be requested again (a merge changes
        the uid tuple), so free them eagerly instead of waiting for LRU."""
        uids = set(uids)
        for key in [k for k, p in self._parts.items()
                    if uids.intersection(p.uids)]:
            self._drop(key, count_eviction=False,
                       cause="merge_invalidated")

    def clear(self) -> None:
        for key in list(self._parts):
            self._drop(key, count_eviction=False, cause="clear")
        self._refused.clear()
        self._lat_ewma.clear()
        self._lat_n.clear()
        self._probe_counter.clear()

    def on_refresh(self, segments) -> None:
        """Refresh publication: eagerly re-pack any resident plane whose
        segment set is a strict prefix of the new set (the append-only
        refresh case), so the refresh pays the upload instead of the next
        query. Merges (prefix broken) rebuild lazily on demand."""
        if not self.enabled:
            return
        uids = tuple(s.uid for s in segments)
        todo = set()
        for key, part in list(self._parts.items()):
            if part.uids != uids and len(part.uids) < len(uids) and \
                    uids[: len(part.uids)] == part.uids:
                todo.add((key[0], key[1]))
        for kind, field in todo:
            self.get(segments, kind, field)

    def stats_snapshot(self) -> Dict[str, Any]:
        by_kind = {"postings": 0, "vectors": 0, "features": 0,
                   "columns": 0}
        for p in self._parts.values():
            by_kind[p.kind] = by_kind.get(p.kind, 0) + p.nbytes
        return {**self.stats,
                "planes_resident": len(self._parts),
                "resident_bytes": by_kind,
                "rerank_depth": int(self.rerank_depth),
                "rerank_depth_max": int(self.rerank_depth_max),
                "rerank_depth_histogram": {
                    str(d): n for d, n
                    in sorted(self.rerank_depth_hist.items())},
                "quantized": bool(self.quantized)}

    def residency_snapshot(self) -> Dict[str, Any]:
        """The device observatory's HBM residency timeline: every
        resident plane with its bytes, generation stamp and age, plus
        the high-water mark and the eviction-cause breakdown — WHERE the
        HBM went and WHY it left, from the stats surface alone."""
        now = time.monotonic()
        total = 0
        planes = []
        for p in self._parts.values():
            total += p.nbytes
            planes.append({
                "kind": p.kind, "field": p.field,
                "bytes": int(p.nbytes),
                "generation": int(getattr(p, "generation", 0)),
                "age_s": round(now - getattr(p, "built_at", now), 3),
            })
        planes.sort(key=lambda e: -e["age_s"])
        self.hbm_high_water = max(self.hbm_high_water, total)
        return {
            "resident_bytes_total": total,
            "high_water_bytes": int(self.hbm_high_water),
            "generations_built": int(self._gen),
            "planes": planes,
            "evictions_by_cause": dict(
                sorted(self.evictions_by_cause.items())),
        }


# one accelerator per process -> one plane residency manager per process
# (the same reasoning as indices/breaker.py's BREAKERS)
PLANES = PlaneRegistry()


# ---------------------------------------------------------------------------
# mesh-sharded device plane: co-located shards stacked over a device mesh
# ---------------------------------------------------------------------------

class MeshPlanePart:
    """One (kind, field) plane over a SET of co-located shards, laid out
    for SPMD scoring: each shard's packed plane occupies one slot of a
    ``[S, ...]`` stack device_put with ``NamedSharding`` over the
    ``shard`` mesh axis (parallel/mesh.py mesh_layout), so one compiled
    program scores every (shard, query) pair and the per-shard RPC
    fan-out of TransportSearchAction collapses to ONE dispatch per phase.

    ``subs[i]`` is shard i's host-level PlanePart (refs / doc_base /
    block_avgdl / demux — the same per-shard planning surfaces the
    single-shard plane executors use), or None when the field has no
    data in that shard (its slot scores nothing and the executors emit
    the per-segment path's empty result for it)."""

    def __init__(self, kind: str, field: str, shard_keys: Tuple,
                 subs: List[Optional[PlanePart]], segments_by_shard,
                 mesh, n_slots: int):
        self.kind = kind
        self.field = field
        self.shard_keys = shard_keys          # ordered (index, shard_id)
        self.subs = subs
        self.segments_by_shard = segments_by_shard
        self.mesh = mesh
        self.n_slots = n_slots
        self.n_shards = len(shard_keys)
        self.nbytes = 0
        self.per_device_bytes = 0
        self._charges: List[Any] = []
        # filled by the registry's stacking pass
        self.n_docs_pad = BLOCK
        self.n_segs_max = 1
        # lazily-built per-slot quantized mirrors (the PlaneVectors
        # precedent, stacked): built on the first quantized mesh query,
        # cached for the part's lifetime, refusal memoized
        self._q_dev: Optional[Tuple] = None
        self._q_failed = False

    def release(self) -> None:
        for charge in self._charges:
            charge.release()

    def uids_of(self, shard_key) -> Tuple:
        i = self.shard_keys.index(shard_key)
        return tuple(s.uid for s in self.segments_by_shard[i])

    def quantized_mirror(self) -> Optional[Tuple]:
        """Per-slot quantized mirrors of this mesh plane's scoring
        arrays, device_put with the SAME shard sharding as the exact
        stacks (each slot's mirror lives on that slot's chip):

        - postings: (block_tfs bf16 [S, NB, B], doc_lens bf16 [S, N])
        - vectors:  (q8 int8 [S, N, D], scales f32 [S, N]) — per-row
          symmetric, so each row quantizes exactly as it would in that
          shard's single-plane mirror
        - features: (block_weights bf16 [S, NB, B],)

        Breaker-charged PER DEVICE like the exact stacks; a refused
        upload is memoized so a starved node serves the exact mesh path
        without re-quantizing per fan-out. None = serve exact."""
        if self._q_dev is not None:
            return self._q_dev
        if self._q_failed:
            return None
        if self.kind == "postings":
            host = (np.asarray(self.block_tfs).astype(jnp.bfloat16),
                    np.asarray(self.doc_lens).astype(jnp.bfloat16))
        elif self.kind == "vectors":
            matrix = np.asarray(self.matrix)
            amax = np.abs(matrix).max(axis=2)
            scales = np.maximum(amax / 127.0, 1e-30).astype(np.float32)
            q8 = np.clip(np.round(matrix / scales[:, :, None]),
                         -127, 127).astype(np.int8)
            host = (q8, scales)
        else:   # features
            host = (np.asarray(self.block_weights).astype(jnp.bfloat16),)
        n_bytes = sum(int(a.nbytes) for a in host)
        d_used = max(1, int(self.mesh.shape["shard"]))
        from elasticsearch_tpu.indices.breaker import charge_device
        try:
            charge = charge_device(
                self, -(-n_bytes // d_used),
                f"mesh_plane_{self.kind}_q:{self.field}",
                return_charge=True)
        except CircuitBreakingError:
            self._q_failed = True
            return None
        self._charges.append(charge)
        self.nbytes += n_bytes
        self.per_device_bytes += -(-n_bytes // d_used)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        out = tuple(
            jax.device_put(a, NamedSharding(
                self.mesh, P(*(["shard"] + [None] * (a.ndim - 1)))))
            for a in host)
        self._q_dev = out
        MESH_PLANES.stats["mesh_quantized_mirror_builds"] += 1
        return out


class MeshPlaneRegistry:
    """Process-global residency manager for mesh-sharded planes, keyed by
    (kind, field, ((index, shard), segment-uid tuple) ...). Same contract
    as PlaneRegistry: ``get`` returning None means "serve this fan-out
    per shard" — the mesh is an optimization, never a correctness gate.
    Planes charge the ``device`` breaker PER DEVICE (each mesh slot's
    share of the stacked arrays actually lives on one chip), LRU-evict
    under pressure, and re-pack incrementally when a member shard's
    refresh appends segments."""

    MAX_PARTS = 16
    MAX_REFUSALS = 64

    def __init__(self):
        self._parts: "OrderedDict[Tuple, MeshPlanePart]" = OrderedDict()
        self._refused: "OrderedDict[Tuple, Tuple]" = OrderedDict()
        # dynamic config (search.mesh.* cluster settings)
        self.enabled = True
        self.min_shards = 2
        self.dp = 1
        # multi-host fleet topology (search.mesh.hosts, parsed to a
        # parallel.mesh.HostTopology); None = single-host
        self.hosts = None
        # test/bench knob (not a cluster setting): bound the device
        # subset — max_devices=1 is the byte-identity baseline layout
        self.max_devices = 0
        self.stats: Dict[str, int] = {
            "mesh_plane_builds": 0,
            "mesh_plane_full_rebuilds": 0,
            "mesh_plane_incremental_appends": 0,
            "mesh_plane_evictions": 0,
            "mesh_plane_miss_fallbacks": 0,
            "mesh_plane_warmups": 0,
            "mesh_quantized_queries": 0,
            "mesh_quantized_mirror_builds": 0,
            "mesh_quantized_fallbacks": 0,
        }
        # device-observatory residency record (the PlaneRegistry shape)
        self._gen = 0
        self.hbm_high_water = 0
        self.evictions_by_cause: Dict[str, int] = {}

    # -- config ---------------------------------------------------------

    def configure_from_state(self, state) -> None:
        version = getattr(state, "version", None)
        if version is not None and \
                version == getattr(self, "_cfg_version", None):
            return
        self._cfg_version = version
        from elasticsearch_tpu.utils.settings import (
            SEARCH_MESH_DP, SEARCH_MESH_ENABLED, SEARCH_MESH_HOSTS,
            SEARCH_MESH_MIN_SHARDS, setting_from_state,
        )
        self.enabled = setting_from_state(state, SEARCH_MESH_ENABLED)
        self.min_shards = setting_from_state(state,
                                             SEARCH_MESH_MIN_SHARDS)
        self.dp = setting_from_state(state, SEARCH_MESH_DP)
        spec = setting_from_state(state, SEARCH_MESH_HOSTS)
        try:
            from elasticsearch_tpu.parallel.mesh import (
                mesh_ready, parse_host_topology,
            )
            # never pay backend first-init here (the topology parse
            # needs the device count): an uninitialized backend keeps
            # the prior (None) topology until the mesh is warm
            self.hosts = (parse_host_topology(spec)
                          if spec and mesh_ready() else None)
        except Exception:     # noqa: BLE001 — a bad spec disables the
            self.hosts = None  # hosts layer, never serving

    def available(self, n_shards: int) -> bool:
        if not self.enabled or n_shards < max(1, self.min_shards):
            return False
        from elasticsearch_tpu.parallel.mesh import mesh_ready
        return mesh_ready()

    def warmup(self) -> bool:
        """Pay backend first-init NOW (the ``search.mesh.warmup_at_boot``
        setting, the legacy mesh plane's boot-time warmup): ``mesh_ready``
        refuses to pay first-init inside a search, so without this the
        FIRST eligible search per process always takes the RPC detour.
        True (and counted) when this call actually initialized the
        backend; False when it was already up or no backend exists."""
        from elasticsearch_tpu.parallel.mesh import mesh_ready
        import sys
        if sys.modules.get("jax") is not None and mesh_ready():
            return False
        try:
            import jax
            jax.devices()
        except Exception:  # noqa: BLE001 — no backend: stay on RPC
            return False
        self.stats["mesh_plane_warmups"] += 1
        return True

    # -- lookup / build -------------------------------------------------

    def _budget_token(self) -> Tuple:
        from elasticsearch_tpu.indices.breaker import BREAKERS
        return (int(BREAKERS.breaker("device").limit), self.dp,
                self.max_devices, self.hosts)

    def _refuse(self, key: Tuple) -> None:
        self.stats["mesh_plane_miss_fallbacks"] += 1
        _count_reason("mesh_plane_budget_refused")
        self._refused[key] = self._budget_token()
        while len(self._refused) > self.MAX_REFUSALS:
            self._refused.popitem(last=False)

    @staticmethod
    def _key(shard_segments, kind: str, field: str) -> Tuple:
        return (kind, field) + tuple(
            (skey, tuple(s.uid for s in segments))
            for skey, segments in shard_segments)

    def get(self, shard_segments, kind: str,
            field: str) -> Optional[MeshPlanePart]:
        """``shard_segments``: ordered [((index, shard_id), [segments])]
        — one entry per co-located target shard, reader order inside."""
        if not self.available(len(shard_segments)):
            return None
        shard_segments = sorted(
            ((skey, list(segments)) for skey, segments in shard_segments),
            key=lambda e: e[0])
        key = self._key(shard_segments, kind, field)
        part = self._parts.get(key)
        if part is not None:
            self._parts.move_to_end(key)
            return part
        refused_under = self._refused.get(key)
        if refused_under is not None:
            if refused_under == self._budget_token():
                self.stats["mesh_plane_miss_fallbacks"] += 1
                _count_reason("mesh_plane_budget_refused")
                return None
            self._refused.pop(key, None)
        return self._build(shard_segments, kind, field, key)

    def _find_prev(self, shard_segments, kind, field
                   ) -> Optional[MeshPlanePart]:
        """Most recent resident part over the SAME shard set whose every
        shard's segment-uid tuple is a prefix of (or equal to) the new
        one — the append-only refresh case; its subs' per-segment caches
        seed the incremental rebuild."""
        keys = tuple(skey for skey, _ in shard_segments)
        for _k, part in reversed(self._parts.items()):
            if part.kind != kind or part.field != field or \
                    part.shard_keys != keys:
                continue
            ok = True
            for i, (_skey, segments) in enumerate(shard_segments):
                uids = tuple(s.uid for s in segments)
                prev_uids = tuple(
                    s.uid for s in part.segments_by_shard[i])
                if uids[: len(prev_uids)] != prev_uids:
                    ok = False
                    break
            if ok:
                return part
        return None

    def _build(self, shard_segments, kind: str, field: str,
               key: Tuple) -> Optional[MeshPlanePart]:
        from elasticsearch_tpu.parallel.mesh import mesh_layout
        mesh, n_slots, _spd = mesh_layout(
            len(shard_segments), dp=self.dp, max_devices=self.max_devices,
            hosts=self.hosts)
        prev = self._find_prev(shard_segments, kind, field)
        subs: List[Optional[PlanePart]] = []
        hosts: List[Optional[Tuple]] = []
        for i, (skey, segments) in enumerate(shard_segments):
            sub = _PART_CLASSES[kind](field, segments)
            prev_sub = prev.subs[i] if prev is not None else None
            try:
                hosts.append(sub.build(prev_sub))
                subs.append(sub)
            except PlaneUnavailable:
                hosts.append(None)
                subs.append(None)
        if all(s is None for s in subs):
            return None
        part = MeshPlanePart(
            kind, field, tuple(skey for skey, _ in shard_segments),
            subs, [segments for _skey, segments in shard_segments],
            mesh, n_slots)
        stacked = self._stack(part, hosts)
        part.nbytes = sum(int(a.nbytes) for a in stacked.values())
        d_used = int(mesh.shape["shard"])
        part.per_device_bytes = -(-part.nbytes // d_used)
        from elasticsearch_tpu.indices.breaker import (
            BREAKERS, charge_device,
        )
        from elasticsearch_tpu.utils.errors import CircuitBreakingError
        label = f"mesh_plane_{kind}:{field}"
        charge = None
        try:
            charge = charge_device(part, part.per_device_bytes, label,
                                   return_charge=True)
        except CircuitBreakingError:
            device_limit = BREAKERS.breaker("device").limit
            if 0 < device_limit < part.per_device_bytes:
                self._refuse(key)
                return None
            while self._parts:
                self._drop(next(iter(self._parts)),
                           cause="breaker_pressure")
                try:
                    charge = charge_device(part, part.per_device_bytes,
                                           label, return_charge=True)
                    break
                except CircuitBreakingError:
                    continue
            if charge is None:
                self._refuse(key)
                return None
        part._charges.append(charge)
        self._upload(part, stacked)
        self.stats["mesh_plane_builds"] += 1
        part.generation = self._gen
        part.built_at = time.monotonic()
        self._gen += 1
        if prev is not None:
            self.stats["mesh_plane_incremental_appends"] += 1
        else:
            self.stats["mesh_plane_full_rebuilds"] += 1
        self._parts[key] = part
        self.hbm_high_water = max(
            self.hbm_high_water,
            sum(p.nbytes for p in self._parts.values()))
        while len(self._parts) > self.MAX_PARTS:
            self._drop(next(iter(self._parts)), cause="lru")
        return part

    # -- stacking -------------------------------------------------------

    def _stack(self, part: MeshPlanePart, hosts) -> Dict[str, np.ndarray]:
        """Stack per-shard host planes into common-shaped [n_slots, ...]
        arrays (empty/padding slots score nothing: -1 block docs, zero
        lengths/weights, exists False)."""
        subs = part.subs
        n_slots = part.n_slots
        n_max = max((s.n_docs_pad for s in subs if s is not None),
                    default=BLOCK)
        part.n_docs_pad = n_max
        part.n_segs_max = max(
            (len(s.segments) for s in subs if s is not None), default=1)
        part.n_segs_max = max(part.n_segs_max, 1)
        out: Dict[str, np.ndarray] = {}
        if part.kind == "postings":
            nb_max = max(h[0].shape[0] for h in hosts if h is not None)
            nb_max = next_pow2(max(nb_max, 1))
            bd = np.full((n_slots, nb_max, BLOCK), -1, np.int32)
            bt = np.zeros((n_slots, nb_max, BLOCK), np.float32)
            dl = np.zeros((n_slots, n_max), np.float32)
            si = np.zeros((n_slots, n_max), np.int32)
            for i, h in enumerate(hosts):
                if h is None:
                    continue
                hbd, hbt, hdl = h
                bd[i, : hbd.shape[0]] = hbd
                bt[i, : hbt.shape[0]] = hbt
                dl[i, : len(hdl)] = hdl
                sub = subs[i]
                si[i] = _seg_ids_host(sub.doc_base, len(sub.segments),
                                      n_max)
            out = {"block_docs": bd, "block_tfs": bt, "doc_lens": dl,
                   "seg_ids": si}
        elif part.kind == "vectors":
            dims = {s.dims for s in subs if s is not None}
            sims = {s.similarity for s in subs if s is not None}
            if len(dims) != 1 or len(sims) != 1:
                raise PlaneUnavailable(part.field)
            part.dims = dims.pop()
            part.similarity = sims.pop()
            matrix = np.zeros((n_slots, n_max, part.dims), np.float32)
            norms = np.zeros((n_slots, n_max), np.float32)
            exists = np.zeros((n_slots, n_max), bool)
            for i, h in enumerate(hosts):
                if h is None:
                    continue
                hm, hn, he = h
                matrix[i, : hm.shape[0]] = hm
                norms[i, : len(hn)] = hn
                exists[i, : len(he)] = he
            out = {"matrix": matrix, "norms": norms, "exists": exists}
        else:   # features
            nb_max = max(h[0].shape[0] for h in hosts if h is not None)
            nb_max = next_pow2(max(nb_max, 1))
            bd = np.full((n_slots, nb_max, BLOCK), -1, np.int32)
            bw = np.zeros((n_slots, nb_max, BLOCK), np.float32)
            for i, h in enumerate(hosts):
                if h is None:
                    continue
                hbd, hbw = h
                bd[i, : hbd.shape[0]] = hbd
                bw[i, : hbw.shape[0]] = hbw
            out = {"block_docs": bd, "block_weights": bw}
        return out

    def _upload(self, part: MeshPlanePart, stacked) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        for name, arr in stacked.items():
            spec = P(*(["shard"] + [None] * (arr.ndim - 1)))
            setattr(part, name, jax.device_put(
                arr, NamedSharding(part.mesh, spec)))

    # -- eviction / lifecycle -------------------------------------------

    def _drop(self, key: Tuple, count_eviction: bool = True,
              cause: str = "lru") -> None:
        part = self._parts.pop(key, None)
        if part is None:
            return
        part.release()
        self.evictions_by_cause[cause] = \
            self.evictions_by_cause.get(cause, 0) + 1
        if count_eviction:
            self.stats["mesh_plane_evictions"] += 1

    def drop_segments(self, uids) -> None:
        """Merge invalidation: any mesh plane touching a merged-away
        segment can never be requested again (the uid tuple changed)."""
        uids = set(uids)
        for key in [k for k, p in self._parts.items()
                    if any(uids.intersection(
                        s.uid for s in segs)
                        for segs in p.segments_by_shard)]:
            self._drop(key, count_eviction=False,
                       cause="merge_invalidated")

    def clear(self) -> None:
        for key in list(self._parts):
            self._drop(key, count_eviction=False, cause="clear")
        self._refused.clear()
        self._cfg_version = object()   # force a settings re-read

    def on_refresh(self, shard_key, segments) -> None:
        """Refresh publication for one member shard: eagerly re-pack any
        resident mesh plane containing it whose recorded uid tuple for
        that shard is a strict prefix of the new one (the append-only
        case), so the refresh pays the upload instead of the next
        fan-out. Other member shards keep their last-published sets —
        their own refreshes publish independently."""
        if not self.enabled:
            return
        uids = tuple(s.uid for s in segments)
        todo = []
        for part in list(self._parts.values()):
            if shard_key not in part.shard_keys:
                continue
            prev_uids = part.uids_of(shard_key)
            if prev_uids != uids and \
                    uids[: len(prev_uids)] == prev_uids:
                todo.append(part)
        for part in todo:
            shard_segments = []
            for i, skey in enumerate(part.shard_keys):
                segs = list(segments) if skey == shard_key \
                    else list(part.segments_by_shard[i])
                shard_segments.append((skey, segs))
            self.get(shard_segments, part.kind, part.field)

    def stats_snapshot(self) -> Dict[str, Any]:
        by_kind = {"postings": 0, "vectors": 0, "features": 0,
                   "columns": 0}
        per_device = 0
        for p in self._parts.values():
            by_kind[p.kind] = by_kind.get(p.kind, 0) + p.nbytes
            per_device += p.per_device_bytes
        out = {**self.stats,
               "mesh_planes_resident": len(self._parts),
               "resident_bytes": by_kind,
               "resident_bytes_per_device": per_device,
               "dp": int(self.dp)}
        if self.hosts is not None:
            out["hosts"] = {"n_hosts": int(self.hosts.n_hosts),
                            "devices_per_host":
                                int(self.hosts.devices_per_host),
                            "spec": self.hosts.spec}
        from elasticsearch_tpu.parallel.mesh import mesh_ready
        if mesh_ready():
            import jax
            out["n_devices"] = len(jax.devices())
        return out

    def residency_snapshot(self) -> Dict[str, Any]:
        """PlaneRegistry.residency_snapshot's mesh counterpart; entries
        carry the slot count and per-device share too (each slot's stack
        share lives on one chip)."""
        now = time.monotonic()
        total = 0
        planes = []
        for p in self._parts.values():
            total += p.nbytes
            planes.append({
                "kind": p.kind, "field": p.field,
                "bytes": int(p.nbytes),
                "bytes_per_device": int(p.per_device_bytes),
                "n_shards": int(p.n_shards),
                "generation": int(getattr(p, "generation", 0)),
                "age_s": round(now - getattr(p, "built_at", now), 3),
            })
        planes.sort(key=lambda e: -e["age_s"])
        self.hbm_high_water = max(self.hbm_high_water, total)
        return {
            "resident_bytes_total": total,
            "high_water_bytes": int(self.hbm_high_water),
            "generations_built": int(self._gen),
            "planes": planes,
            "evictions_by_cause": dict(
                sorted(self.evictions_by_cause.items())),
        }


# the mesh plane shares the process-global residency reasoning of PLANES
MESH_PLANES = MeshPlaneRegistry()
