"""Device mirrors of segment data.

Each searchable segment gets lazily-built, cached device arrays with
power-of-two padded shapes (bucketing keeps the jit cache warm across
segment growth/merge — SURVEY.md §7 hard part #3). The host Segment stays
the source of truth; device mirrors are pure caches.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.index.segment import (
    BLOCK, FeaturesField, PostingsField, Segment, VectorField, next_pow2,
)


class DevicePostings:
    """Device-resident postings for one text field of one segment."""

    def __init__(self, pf: PostingsField, n_docs: int):
        self.n_docs = n_docs
        self.n_docs_pad = next_pow2(max(n_docs, 1), minimum=BLOCK)
        n_blocks = pf.block_docs.shape[0]
        self.n_blocks_pad = next_pow2(n_blocks)
        # pad blocks with an empty sentinel block (all -1 docs)
        pad = self.n_blocks_pad - n_blocks
        block_docs = np.pad(pf.block_docs, ((0, pad), (0, 0)), constant_values=-1)
        block_tfs = np.pad(pf.block_tfs, ((0, pad), (0, 0)))
        doc_lens = np.zeros(self.n_docs_pad, np.float32)
        doc_lens[: len(pf.doc_lens)] = pf.doc_lens
        block_max_tf = np.pad(pf.block_max_tf, (0, pad))
        # budget check BEFORE the HBM upload (breaker must gate, not observe)
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        account_device_arrays(
            self, (block_docs, block_tfs, doc_lens, block_max_tf),
            "postings")
        self.block_docs = jnp.asarray(block_docs)
        self.block_tfs = jnp.asarray(block_tfs)
        self.doc_lens = jnp.asarray(doc_lens)
        self.avgdl = float(pf.sum_doc_len / max(1, (pf.doc_lens > 0).sum()))
        self.block_max_tf = jnp.asarray(block_max_tf)

    @staticmethod
    def for_segment(seg: Segment, field_name: str) -> Optional["DevicePostings"]:
        pf = seg.postings.get(field_name)
        if pf is None:
            return None
        return seg.device(("postings", field_name),
                          lambda: DevicePostings(pf, seg.n_docs))


class DeviceVectors:
    """Device-resident dense-vector matrix for one field of one segment."""

    def __init__(self, vf: VectorField, n_docs: int):
        self.n_docs = n_docs
        self.n_docs_pad = next_pow2(max(n_docs, 1), minimum=BLOCK)
        self.dims = vf.dims
        pad = self.n_docs_pad - vf.matrix.shape[0]
        matrix = np.pad(vf.matrix, ((0, pad), (0, 0)))
        norms = np.pad(vf.norms, (0, pad))
        exists = np.zeros(self.n_docs_pad, bool)
        exists[: len(vf.exists)] = vf.exists
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        account_device_arrays(self, (matrix, norms, exists), "vectors")
        self.matrix = jnp.asarray(matrix)
        self.norms = jnp.asarray(norms)
        self.exists = jnp.asarray(exists)
        self.similarity = vf.similarity

    @staticmethod
    def for_segment(seg: Segment, field_name: str) -> Optional["DeviceVectors"]:
        vf = seg.vectors.get(field_name)
        if vf is None:
            return None
        return seg.device(("vectors", field_name),
                          lambda: DeviceVectors(vf, seg.n_docs))


class DeviceFeatures:
    """Device-resident rank_features blocks for one field of one segment."""

    def __init__(self, ff: FeaturesField, n_docs: int):
        self.n_docs = n_docs
        self.n_docs_pad = next_pow2(max(n_docs, 1), minimum=BLOCK)
        n_blocks = ff.block_docs.shape[0]
        self.n_blocks_pad = next_pow2(n_blocks)
        pad = self.n_blocks_pad - n_blocks
        block_docs = np.pad(ff.block_docs, ((0, pad), (0, 0)),
                            constant_values=-1)
        block_weights = np.pad(ff.block_weights, ((0, pad), (0, 0)))
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        account_device_arrays(self, (block_docs, block_weights), "features")
        self.block_docs = jnp.asarray(block_docs)
        self.block_weights = jnp.asarray(block_weights)

    @staticmethod
    def for_segment(seg: Segment, field_name: str) -> Optional["DeviceFeatures"]:
        ff = seg.features.get(field_name)
        if ff is None:
            return None
        return seg.device(("features", field_name),
                          lambda: DeviceFeatures(ff, seg.n_docs))


def device_live_mask(seg: Segment) -> jnp.ndarray:
    """Live mask padded to the doc bucket (True = scoreable)."""
    n_pad = next_pow2(max(seg.n_docs, 1), minimum=BLOCK)

    def build():
        m = np.zeros(n_pad, bool)
        m[: seg.n_docs] = seg.live
        return jnp.asarray(m)

    return seg.device("live", build)


def gather_query_blocks(pf: PostingsField, terms_with_weights, n_blocks_bucket_min: int = 8
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side prep for a query: list every posting block of every query
    term, with its per-block weight (e.g. idf). Returns (block_indices int32
    [QB_pad], block_weights float32 [QB_pad]) padded to a pow2 bucket so the
    device gather has a bucketed static shape. Padding uses block 0 with
    weight 0 (contributes nothing)."""
    idx: list = []
    w: list = []
    for term, weight in terms_with_weights:
        start, count = pf.term_blocks(term)
        for b in range(start, start + count):
            idx.append(b)
            w.append(weight)
    qb = max(len(idx), 1)
    qb_pad = next_pow2(qb, minimum=n_blocks_bucket_min)
    out_idx = np.zeros(qb_pad, np.int32)
    out_w = np.zeros(qb_pad, np.float32)
    out_idx[: len(idx)] = idx
    out_w[: len(w)] = w
    return out_idx, out_w
