"""IVF approximate nearest neighbour — the TPU-native ANN index.

The reference snapshot has no ANN at all (no HNSW, SURVEY.md version note);
the capability target is BASELINE.json config #3 (HNSW-class recall/QPS).
Graph-walk ANN (HNSW) is hostile to SPMD — data-dependent traversal, scalar
hops, dynamic shapes — so this is an IVF/ScaNN-style design instead, which
maps onto the MXU as two batched matmuls:

  1. score queries against the [nlist, D] centroid matrix, take top-nprobe
  2. gather those lists' padded vector blocks [nprobe, L, D] and score
     exactly, masked top-k over the probed candidates

Everything is static-shape: lists are padded to a common length L with a
validity mask, so XLA compiles one kernel per (nprobe, k) and the cache
stays warm. Build (k-means) also runs on device: Lloyd iterations are a
distance matmul + argmin + segment-sum.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.search.device_profile import profiled_jit
from elasticsearch_tpu.search.telemetry import record_dispatch


# ---------------------------------------------------------------------------
# k-means (device)
# ---------------------------------------------------------------------------

@profiled_jit("ivf_assign", static_argnames=("nlist",))
def _assign(x: jnp.ndarray, centroids: jnp.ndarray, nlist: int
            ) -> jnp.ndarray:
    # ||x-c||^2 = ||x||^2 - 2 x.c + ||c||^2 ; ||x||^2 constant per row
    dots = jax.lax.dot_general(
        x.astype(jnp.bfloat16), centroids.astype(jnp.bfloat16).T,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    c2 = jnp.sum(centroids * centroids, axis=1)
    return jnp.argmin(c2[None, :] - 2.0 * dots, axis=1).astype(jnp.int32)


# rows per assignment dispatch: the [chunk, nlist] distance plane at a
# GIST1M-class build (nlist=4000) is 2GB at 128K rows — an unchunked
# 1M-row assign would materialize 16GB and OOM the chip
ASSIGN_CHUNK = 1 << 17


def assign_chunked(x: jnp.ndarray, centroids: jnp.ndarray, nlist: int
                   ) -> jnp.ndarray:
    """_assign in fixed-size row chunks (tail zero-padded so every
    dispatch reuses one compiled shape)."""
    n = x.shape[0]
    if n <= ASSIGN_CHUNK:
        return _assign(x, centroids, nlist)
    outs = []
    for i in range(0, n, ASSIGN_CHUNK):
        chunk = x[i : i + ASSIGN_CHUNK]
        short = ASSIGN_CHUNK - chunk.shape[0]
        if short > 0:
            chunk = jnp.pad(chunk, ((0, short), (0, 0)))
            outs.append(_assign(chunk, centroids, nlist)[:-short])
        else:
            outs.append(_assign(chunk, centroids, nlist))
    return jnp.concatenate(outs)


@profiled_jit("ivf_update", static_argnames=("nlist",))
def _update(x: jnp.ndarray, assign: jnp.ndarray, centroids: jnp.ndarray,
            nlist: int) -> jnp.ndarray:
    sums = jax.ops.segment_sum(x, assign, num_segments=nlist)
    counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), jnp.float32),
                                 assign, num_segments=nlist)
    fresh = sums / jnp.maximum(counts, 1.0)[:, None]
    # empty clusters keep their previous centroid
    return jnp.where((counts > 0)[:, None], fresh, centroids)


@profiled_jit("ivf_init", static_argnames=("nlist",))
def _farthest_point_init(x: jnp.ndarray, first: jnp.ndarray,
                         nlist: int) -> jnp.ndarray:
    """Deterministic k-center seeding: repeatedly take the point farthest
    from every centroid so far. One fori_loop kernel — n*d work per step —
    and far more robust than random init (random seeds from one dense
    region collapse neighbouring clusters into local optima)."""
    n, d = x.shape
    cents0 = jnp.zeros((nlist, d), x.dtype).at[0].set(x[first])
    d20 = jnp.sum((x - x[first]) ** 2, axis=1)

    def step(i, state):
        cents, d2 = state
        idx = jnp.argmax(d2)
        c = x[idx]
        cents = cents.at[i].set(c)
        return cents, jnp.minimum(d2, jnp.sum((x - c) ** 2, axis=1))
    cents, _ = jax.lax.fori_loop(1, nlist, step, (cents0, d20))
    return cents


def kmeans(vectors: np.ndarray, nlist: int, iters: int = 10,
           seed: int = 17,
           init_centroids: Optional[np.ndarray] = None) -> np.ndarray:
    """Farthest-point init + Lloyd's on device; [nlist, D] f32 centroids.

    ``init_centroids`` [nlist, D] warm-starts Lloyd's from a previous
    generation's solution (the plane registry's incremental-refresh
    case): an append-only refresh barely moves the optimal centroids, so
    seeding from them converges in a fraction of the cold iterations."""
    n, d = vectors.shape
    rng = np.random.default_rng(seed)
    x = jnp.asarray(vectors, jnp.float32)
    if n <= nlist:
        reps = np.resize(vectors.astype(np.float32), (nlist, d))
        return reps
    if init_centroids is not None and \
            init_centroids.shape == (nlist, d):
        c = jnp.asarray(init_centroids, jnp.float32)
        warm_iters = max(2, iters // 3)
        for _ in range(warm_iters):
            c = _update(x, assign_chunked(x, c, nlist), c, nlist)
        return np.asarray(c)
    # seed on a subsample to bound init cost at ~25*nlist points
    cap = min(n, max(25 * nlist, 2 * nlist))
    sample = (np.arange(n) if n <= cap
              else rng.choice(n, size=cap, replace=False))
    c = _farthest_point_init(x[jnp.asarray(sample)],
                             jnp.asarray(rng.integers(len(sample))),
                             nlist)
    for _ in range(iters):
        c = _update(x, assign_chunked(x, c, nlist), c, nlist)
    return np.asarray(c)


# ---------------------------------------------------------------------------
# index build (host packing, device math)
# ---------------------------------------------------------------------------

class IVFIndex:
    """Padded inverted-file index over one vector corpus.

    lists:  [nlist, L, D] float32 (zero-padded)
    valid:  [nlist, L]    bool
    ids:    [nlist, L]    int32 (-1 where padded) — original row indices
    """

    def __init__(self, centroids, lists, valid, ids, similarity: str,
                 norms):
        self.centroids = centroids
        self.lists = lists
        self.valid = valid
        self.ids = ids
        self.similarity = similarity
        self.norms = norms           # [nlist, L] doc norms (cosine/l2)
        self.nlist = int(centroids.shape[0])
        self.list_len = int(lists.shape[1])

    @staticmethod
    def build(vectors: np.ndarray, nlist: Optional[int] = None,
              similarity: str = "cosine", iters: int = 10,
              slack: float = 1.5, seed: int = 17,
              init_centroids: Optional[np.ndarray] = None) -> "IVFIndex":
        n, d = vectors.shape
        if n == 0:
            raise ValueError("cannot build an IVF index over zero vectors")
        if nlist is None:
            nlist = max(1, min(n, int(4 * np.sqrt(n))))
        nlist = max(1, min(nlist, n))
        vectors = np.asarray(vectors, np.float32)
        warm = init_centroids is not None and \
            np.asarray(init_centroids).shape == (nlist, d) and n > nlist
        cents = kmeans(vectors, nlist, iters=iters, seed=seed,
                       init_centroids=(np.asarray(init_centroids,
                                                  np.float32)
                                       if warm else None))
        assign = np.asarray(assign_chunked(jnp.asarray(vectors),
                                           jnp.asarray(cents), nlist))
        cap = max(1, int(np.ceil(n / nlist * slack)))
        # balanced packing: overflow spills to the next-nearest centroid
        order = np.argsort(assign, kind="stable")
        buckets: list = [[] for _ in range(nlist)]
        spilled = []
        for row in order:
            a = assign[row]
            if len(buckets[a]) < cap:
                buckets[a].append(row)
            else:
                spilled.append(row)
        if spilled:
            x = vectors[np.asarray(spilled)]
            dots = x @ cents.T
            c2 = (cents * cents).sum(axis=1)
            dist = c2[None, :] - 2 * dots
            ranked = np.argsort(dist, axis=1)
            for i, row in enumerate(spilled):
                placed = False
                for c_idx in ranked[i]:
                    if len(buckets[c_idx]) < cap:
                        buckets[c_idx].append(row)
                        placed = True
                        break
                if not placed:   # all full (can't happen with slack > 1)
                    buckets[int(ranked[i][0])].append(row)
        L = max(cap, max(len(b) for b in buckets))
        lists = np.zeros((nlist, L, d), np.float32)
        valid = np.zeros((nlist, L), bool)
        ids = np.full((nlist, L), -1, np.int32)
        for ci, rows in enumerate(buckets):
            m = len(rows)
            if m:
                lists[ci, :m] = vectors[rows]
                valid[ci, :m] = True
                ids[ci, :m] = rows
        norms = np.linalg.norm(lists, axis=2).astype(np.float32)
        # budget-gate the HBM residency BEFORE the upload, like every
        # other device-resident structure (indices/breaker.py): an
        # over-budget index build trips the breaker instead of OOMing;
        # the shard-plane route catches the trip and serves exact
        index = IVFIndex(cents, lists, valid, ids, similarity, norms)
        from elasticsearch_tpu.indices.breaker import account_device_arrays
        # the charge handle rides on the index so owners that evict
        # early (the plane registry) can release ahead of GC
        index.warm_started = warm
        index._charge = account_device_arrays(
            index, (cents, lists, valid, ids, norms), "ivf",
            return_charge=True)
        index.centroids = jnp.asarray(cents)
        index.lists = jnp.asarray(lists)
        index.valid = jnp.asarray(valid)
        index.ids = jnp.asarray(ids)
        index.norms = jnp.asarray(norms)
        return index

    # -- search ----------------------------------------------------------

    # HBM budget for the [chunk, nprobe, L, D] gather the probe phase
    # materializes; the query chunk adapts to it (pow-2 so the XLA compile
    # cache stays warm) — big chunks matter because each kernel call pays
    # a dispatch round-trip
    GATHER_BYTES_BUDGET = 1 << 30

    def _chunk_for(self, nprobe: int) -> int:
        dims = int(self.lists.shape[2])
        per_query = nprobe * self.list_len * dims * 4
        chunk = max(1, self.GATHER_BYTES_BUDGET // max(per_query, 1))
        chunk = min(chunk, 256)
        return 1 << (chunk.bit_length() - 1)      # floor to pow-2

    def search_device(self, q_dev: jnp.ndarray, k: int, nprobe: int = 8
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Device-in/device-out single-kernel search (no host sync): the
        serving path — callers pipeline batches without paying a dispatch
        round-trip per batch."""
        record_dispatch()
        nprobe = max(1, min(int(nprobe), self.nlist))
        k = max(1, min(int(k), nprobe * self.list_len))
        return _ivf_search(q_dev, self.centroids, self.lists, self.valid,
                           self.ids, self.norms, k, nprobe,
                           self.similarity)

    def probe_live(self, queries: np.ndarray, k: int, nprobe: int,
                   rows: np.ndarray, live: np.ndarray, segment_idx: int,
                   oversample: int) -> list:
        """Batched nprobe-probe for the serving path: ONE device program
        covers Q queries (centroid scoring + gathered-list scoring +
        top-k), then the host-side demux the per-query ANN path performs —
        list-row ids map back through ``rows`` (the segment's rows that
        actually hold vectors), deleted docs drop out post-probe (the
        Lucene-HNSW-style post-filter the oversample exists for), and each
        query keeps its best ``k``. Returns one
        [(segment_idx, doc, score)] list per query, in score order."""
        scores, ids = self.search(np.asarray(queries, np.float32),
                                  oversample, nprobe=nprobe)
        out = []
        for qi in range(scores.shape[0]):
            hits = []
            for s, i in zip(scores[qi], ids[qi]):
                if i < 0:
                    continue
                doc = int(rows[i])
                if doc < len(live) and live[doc]:
                    hits.append((segment_idx, doc, float(s)))
                if len(hits) >= k:
                    break
            out.append(hits)
        return out

    def search(self, queries: np.ndarray, k: int, nprobe: int = 8
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Batched ANN: (scores [Q, k], ids [Q, k]); ids -1 past matches.
        Scores use the same positive transforms as ops/knn.py."""
        record_dispatch()
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        nprobe = max(1, min(int(nprobe), self.nlist))
        # k cannot exceed the probed candidate pool (top_k width)
        k = max(1, min(int(k), nprobe * self.list_len))
        nq = q.shape[0]
        chunk = self._chunk_for(nprobe)
        if nq <= chunk:
            # pad to the next pow-2 of the ACTUAL batch (<= chunk): a
            # single query must not pay the full budget-sized gather,
            # and pow-2 shapes keep the compile cache to ~9 entries
            width = 1 << max(0, (nq - 1)).bit_length()
            width = min(max(width, 1), chunk)
            padded = np.zeros((width, q.shape[1]), np.float32)
            padded[:nq] = q
            s, i = _ivf_search(jnp.asarray(padded), self.centroids,
                               self.lists, self.valid, self.ids,
                               self.norms, k, nprobe, self.similarity)
            return np.asarray(s)[:nq], np.asarray(i)[:nq]
        out_s = np.empty((nq, k), np.float32)
        out_i = np.empty((nq, k), np.int32)
        for lo in range(0, nq, chunk):
            hi = min(lo + chunk, nq)
            s, i = self.search(q[lo:hi], k, nprobe)
            out_s[lo:hi], out_i[lo:hi] = s, i
        return out_s, out_i


@profiled_jit("ivf_search",
              static_argnames=("k", "nprobe", "similarity"))
def _ivf_search(q, centroids, lists, valid, ids, norms, k: int,
                nprobe: int, similarity: str):
    qb = q.astype(jnp.bfloat16)
    cscores = jax.lax.dot_general(
        qb, centroids.astype(jnp.bfloat16).T,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [Q, nlist]
    if similarity == "l2_norm":
        c2 = jnp.sum(centroids * centroids, axis=1)
        cscores = 2.0 * cscores - c2[None, :]        # -dist^2 + const
    _, probes = jax.lax.top_k(cscores, nprobe)       # [Q, nprobe]

    blocks = lists[probes]                           # [Q, nprobe, L, D]
    dots = jnp.einsum("qd,qpld->qpl", qb, blocks.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    bnorms = norms[probes]                           # [Q, nprobe, L]
    if similarity == "dot_product":
        scores = 0.5 + dots / 2.0
    elif similarity == "cosine":
        qn = jnp.linalg.norm(q, axis=1) + 1e-30      # [Q]
        cos = dots / (bnorms * qn[:, None, None] + 1e-30)
        scores = (1.0 + cos) / 2.0
    else:  # l2_norm
        q2 = jnp.sum(q * q, axis=1)                  # [Q]
        d2 = jnp.maximum(bnorms * bnorms + q2[:, None, None] - 2.0 * dots,
                         0.0)
        scores = 1.0 / (1.0 + jnp.sqrt(d2))
    scores = jnp.where(valid[probes], scores, -jnp.inf)

    flat = scores.reshape(scores.shape[0], -1)
    flat_ids = ids[probes].reshape(scores.shape[0], -1)
    top_s, pos = jax.lax.top_k(flat, k)
    top_i = jnp.take_along_axis(flat_ids, pos, axis=1)
    top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
    top_s = jnp.where(jnp.isfinite(top_s), top_s, 0.0)
    return top_s, top_i
