"""Sparse rank_features / learned-sparse (ELSER-style) scoring on device.

Reference substrate: rank_feature(s) field types scored with saturation /
log / sigmoid / linear functions
(modules/mapper-extras/.../RankFeatureFieldMapper.java, the rank_feature
query) — the storage model ELSER's text_expansion builds on. Query = a bag of
(feature, weight); document score = sum over matching features of
f(doc_weight) * query_weight.

Same block-gather + scatter-add shape as BM25 (ops/bm25.py), with the score
transform selected statically.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.index.segment import FeaturesField, next_pow2
from elasticsearch_tpu.ops.device_segment import DeviceFeatures
from elasticsearch_tpu.search.device_profile import profiled_jit
from elasticsearch_tpu.search.telemetry import record_dispatch


@profiled_jit("sparse_topk",
              static_argnames=("n_docs_pad", "function", "k"))
def sparse_topk(block_docs, block_weights, block_idx, query_weight,
                pivot, exponent, live, n_docs_pad: int, k: int,
                function: str = "saturation") -> Tuple[jnp.ndarray, jnp.ndarray]:
    scores = sparse_scores(block_docs, block_weights, block_idx, query_weight,
                           pivot, exponent, n_docs_pad, function)
    scores = jnp.where(live & (scores > 0.0), scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@profiled_jit("sparse_scores",
              static_argnames=("n_docs_pad", "function"))
def sparse_scores(block_docs,      # [NB, BLOCK] int32
                  block_weights,   # [NB, BLOCK] f32
                  block_idx,       # [QB] int32
                  query_weight,    # [QB] f32 (0 = padding)
                  pivot,           # scalar f32 (saturation/sigmoid pivot; log scaling factor)
                  exponent,        # scalar f32 (sigmoid exponent; unused otherwise)
                  n_docs_pad: int,
                  function: str = "saturation") -> jnp.ndarray:
    docs = block_docs[block_idx]
    w = block_weights[block_idx]
    valid = docs >= 0
    safe_docs = jnp.where(valid, docs, 0)
    if function == "saturation":
        f = w / (w + pivot)
    elif function == "log":
        f = jnp.log(pivot + w)          # reference: log(scaling_factor + S)
    elif function == "sigmoid":
        # reference: S^a / (S^a + pivot^a)
        wa = jnp.power(jnp.maximum(w, 0.0), exponent)
        f = wa / (wa + jnp.power(pivot, exponent))
    else:  # linear
        f = w
    contrib = query_weight[:, None] * f
    contrib = jnp.where(valid, contrib, 0.0)
    scores = jnp.zeros((n_docs_pad,), jnp.float32)
    return scores.at[safe_docs.reshape(-1)].add(contrib.reshape(-1), mode="drop")


@profiled_jit("sparse_topk_batch",
              static_argnames=("n_docs_pad", "k", "function", "counted"))
def sparse_topk_batch(block_docs, block_weights,
                      block_idx,       # [Q, QB] int32
                      query_weight,    # [Q, QB] f32 (0 = padding)
                      pivot, exponent, live, n_docs_pad: int, k: int,
                      function: str = "saturation",
                      counted: bool = False
                      ) -> Tuple[jnp.ndarray, ...]:
    """Batched sparse retrieval: Q expanded queries in ONE dispatch (the
    bm25_topk_batch analog — the sparse path was dispatch-bound at one
    compiled call per query). With ``counted`` also returns hits[Q] =
    #live docs with score > 0 per query, the exact match count the
    counts-then-skip totals contract needs (the dense path's mask sum,
    read off the score vector already computed here)."""

    def one(bi, qw):
        ts, td, hits = sparse_topk_body(block_docs, block_weights, bi, qw,
                                        pivot, exponent, live, n_docs_pad,
                                        k, function)
        if counted:
            return ts, td, hits
        return ts, td

    return jax.vmap(one)(block_idx, query_weight)


def sparse_topk_body(block_docs, block_weights, block_idx, query_weight,
                     pivot, exponent, live, n_docs_pad: int, k: int,
                     function: str = "saturation"):
    """Per-query EXACT top-k + live match count over one rank_features
    plane — the traced body shared VERBATIM by ``sparse_topk_batch``
    and the mesh slot kernel (parallel/mesh.py ``mesh_sparse_topk``),
    the ``bm25_flat_body`` precedent: one trace means a mesh slot's row
    cannot diverge from the single-shard dispatch. Returns
    (scores [k], plane docs [k], hits) — callers that don't need counts
    drop the third element (XLA dead-code-eliminates the sum)."""
    s = sparse_scores(block_docs, block_weights, block_idx, query_weight,
                      pivot, exponent, n_docs_pad, function)
    matched = live & (s > 0.0)
    s = jnp.where(matched, s, -jnp.inf)
    ts, td = jax.lax.top_k(s, k)
    return ts, td, jnp.sum(matched, dtype=jnp.int32)


def sparse_coarse_body(block_docs, block_weights_q, block_idx,
                       query_weight, live, n_docs_pad: int, kprime: int):
    """Quantized COARSE tier of the two-tier sparse path (linear scoring,
    the plane path's function): gather the bf16 weight mirror, compute
    contributions in bf16, accumulate in f32 — the ``bm25_coarse_body``
    shape for rank_features. Per query: (coarse scores [kprime],
    candidate plane docs [kprime], exact match count). Counts stay exact
    under reduced precision: positive contributions stay positive in
    bf16, so ``score > 0`` flags the same doc set as the f32 kernel."""

    def one(bi, qw):
        docs = block_docs[bi]
        w = block_weights_q[bi]                 # [QB, BLOCK] bf16
        valid = docs >= 0
        safe = jnp.where(valid, docs, 0)
        contrib = qw.astype(jnp.bfloat16)[:, None] * w
        contrib = jnp.where(valid, contrib.astype(jnp.float32), 0.0)
        scores = jnp.zeros((n_docs_pad,), jnp.float32)
        scores = scores.at[safe.reshape(-1)].add(contrib.reshape(-1),
                                                 mode="drop")
        matched = live & (scores > 0.0)
        s = jnp.where(matched, scores, -jnp.inf)
        cs, cand = jax.lax.top_k(s, kprime)
        return cs, cand, jnp.sum(matched, dtype=jnp.int32)

    return jax.vmap(one)(block_idx, query_weight)


def sparse_rerank_body(block_docs, block_weights, block_idx, query_weight,
                       live, cand, coarse_s, n_docs_pad: int, kprime: int,
                       k: int):
    """EXACT tier: re-score only the coarse candidates with the f32
    linear arithmetic of ``sparse_scores`` — same gather, same
    contribution formula, same linear scatter-add order — into a compact
    [kprime] candidate vector. Candidates sorted ascending by doc id so
    score-tie breaks match the dense kernel's lower-index-wins order.
    Per query: (scores [k], plane docs [k], eps) with ``eps`` the max
    observed |exact - coarse| among matched candidates."""

    def one(bi, qw, cd, cs):
        order = jnp.argsort(cd)
        cd_s = cd[order]
        cs_s = cs[order]
        slot_of = jnp.full((n_docs_pad,), -1, jnp.int32)
        slot_of = slot_of.at[cd_s].set(
            jnp.arange(kprime, dtype=jnp.int32))
        docs = block_docs[bi]
        w = block_weights[bi]
        valid = docs >= 0
        safe = jnp.where(valid, docs, 0)
        contrib = jnp.where(valid, qw[:, None] * w, 0.0)
        slot = slot_of[safe]
        tgt = jnp.where(slot >= 0, slot, kprime)    # non-candidate: drop
        cscores = jnp.zeros((kprime,), jnp.float32)
        cscores = cscores.at[tgt.reshape(-1)].add(contrib.reshape(-1),
                                                  mode="drop")
        ok = live[cd_s] & (cscores > 0.0)
        masked = jnp.where(ok, cscores, -jnp.inf)
        s, pos = jax.lax.top_k(masked, k)
        d = cd_s[pos]
        both = ok & jnp.isfinite(cs_s)
        eps = jnp.max(jnp.where(both, jnp.abs(cscores - cs_s), 0.0))
        return s, d, eps

    return jax.vmap(one)(block_idx, query_weight, cand, coarse_s)


@profiled_jit("sparse_coarse",
              static_argnames=("n_docs_pad", "kprime"))
def sparse_coarse_kernel(block_docs, block_weights_q, block_idx,
                         query_weight, live, n_docs_pad: int,
                         kprime: int):
    return sparse_coarse_body(block_docs, block_weights_q, block_idx,
                              query_weight, live, n_docs_pad, kprime)


@profiled_jit("sparse_rerank",
              static_argnames=("n_docs_pad", "kprime", "k"))
def sparse_rerank_kernel(block_docs, block_weights, block_idx,
                         query_weight, live, cand, coarse_s,
                         n_docs_pad: int, kprime: int, k: int):
    return sparse_rerank_body(block_docs, block_weights, block_idx,
                              query_weight, live, cand, coarse_s,
                              n_docs_pad, kprime, k)


def gather_feature_blocks(ff: FeaturesField, features_with_weights,
                          bucket_min: int = 8) -> Tuple[np.ndarray, np.ndarray]:
    """Host prep: (block_indices, query_weights) padded to a pow2 bucket.
    Per-feature block lists come from the immutable field's cache
    (FeaturesField.feature_block_idx) — they only change when a refresh
    publishes a new field, so ELSER-style repeat expansions stop paying
    per-query list construction."""
    idx_parts, w_parts = [], []
    for name, weight in features_with_weights:
        f_idx = ff.feature_block_idx(name)
        if not len(f_idx):
            continue
        idx_parts.append(f_idx)
        w_parts.append(np.full(len(f_idx), weight, np.float32))
    n = sum(len(p) for p in idx_parts)
    qb_pad = next_pow2(max(n, 1), minimum=bucket_min)
    out_idx = np.zeros(qb_pad, np.int32)
    out_w = np.zeros(qb_pad, np.float32)
    if idx_parts:
        out_idx[:n] = np.concatenate(idx_parts)
        out_w[:n] = np.concatenate(w_parts)
    return out_idx, out_w


class SparseExecutor:
    """Per-(segment, field) sparse retrieval executor (text_expansion analog)."""

    def __init__(self, device_features: DeviceFeatures, host_features: FeaturesField):
        self.dev = device_features
        self.host = host_features

    def scores(self, features_with_weights, live,
               function: str = "linear", pivot: float = 1.0,
               exponent: float = 1.0) -> jnp.ndarray:
        block_idx, qw = gather_feature_blocks(self.host, features_with_weights)
        s = sparse_scores(self.dev.block_docs, self.dev.block_weights,
                          jnp.asarray(block_idx), jnp.asarray(qw),
                          jnp.float32(pivot), jnp.float32(exponent),
                          self.dev.n_docs_pad, function)
        return jnp.where(live, s, 0.0)

    def top_k(self, features_with_weights, live, k: int,
              function: str = "linear", pivot: float = 1.0,
              exponent: float = 1.0):
        record_dispatch()
        block_idx, qw = gather_feature_blocks(self.host, features_with_weights)
        return sparse_topk(self.dev.block_docs, self.dev.block_weights,
                           jnp.asarray(block_idx), jnp.asarray(qw),
                           jnp.float32(pivot), jnp.float32(exponent),
                           live, self.dev.n_docs_pad, k, function)

    def top_k_batch(self, queries, live, k: int,
                    function: str = "linear", pivot: float = 1.0,
                    exponent: float = 1.0, count_hits: bool = False):
        """``queries``: list of [(feature, weight)] expansions; one device
        dispatch for the whole batch. Per-query gather lists are padded to
        a shared bucket (block 0 / weight 0 pads contribute nothing); the
        query dimension pads to a pow2 bucket so the jit cache stays warm.
        With ``count_hits`` also returns exact per-query match counts."""
        record_dispatch()
        per = [gather_feature_blocks(self.host, q, bucket_min=1)
               for q in queries]
        qb_pad = next_pow2(max((len(i) for i, _ in per), default=1),
                           minimum=8)
        n_real = len(per)
        q_n = next_pow2(max(n_real, 1), minimum=1)
        idx = np.zeros((q_n, qb_pad), np.int32)
        w = np.zeros((q_n, qb_pad), np.float32)
        for i, (bi, bw) in enumerate(per):
            idx[i, : len(bi)] = bi
            w[i, : len(bw)] = bw
        got = sparse_topk_batch(
            self.dev.block_docs, self.dev.block_weights,
            jnp.asarray(idx), jnp.asarray(w),
            jnp.float32(pivot), jnp.float32(exponent),
            live, self.dev.n_docs_pad, k, function, counted=count_hits)
        if count_hits:
            s, d, h = got
            return s[:n_real], d[:n_real], np.asarray(h)[:n_real]
        s, d = got
        return s[:n_real], d[:n_real]
