"""BM25 scoring on device.

Replaces the reference's hot loop — Lucene BulkScorer over postings with
LegacyBM25Similarity (search/query/QueryPhase.java:331,
index/similarity/SimilarityService.java:60) and TopScoreDocCollector top-k
(search/query/TopDocsCollectorContext.java:215) — with a block-at-a-time
device program:

1. host: resolve query terms -> posting-block indices + per-term idf
   (gather_query_blocks);
2. device: gather blocks, compute per-entry BM25 contributions on the VPU,
   scatter-add into a dense per-doc score vector, top-k.

Everything is static-shaped: block count and doc count are padded to pow2
buckets, so one compiled program serves many queries.

idf follows the reference's BM25: ln(1 + (N - df + 0.5) / (df + 0.5)).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.index.segment import PostingsField, next_pow2
from elasticsearch_tpu.ops.device_segment import DevicePostings, gather_query_blocks

DEFAULT_K1 = 1.2
DEFAULT_B = 0.75


def idf(doc_count: int, doc_freq: int) -> float:
    """Reference BM25 idf (Lucene BM25Similarity.idfExplain)."""
    return float(np.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5)))


@partial(jax.jit, static_argnames=("n_docs_pad", "k1", "b"))
def bm25_block_scores(block_docs: jnp.ndarray,     # [NB, BLOCK] int32, -1 pad
                      block_tfs: jnp.ndarray,      # [NB, BLOCK] f32
                      block_idx: jnp.ndarray,      # [QB] int32 gather indices
                      block_weight: jnp.ndarray,   # [QB] f32 (idf * query boost)
                      doc_lens: jnp.ndarray,       # [n_docs_pad] f32
                      avgdl: jnp.ndarray,          # scalar f32
                      n_docs_pad: int,
                      k1: float = DEFAULT_K1,
                      b: float = DEFAULT_B) -> jnp.ndarray:
    """Dense BM25 scores [n_docs_pad] for one query over one segment."""
    docs = block_docs[block_idx]            # [QB, BLOCK]
    tfs = block_tfs[block_idx]              # [QB, BLOCK]
    valid = docs >= 0
    safe_docs = jnp.where(valid, docs, 0)
    dl = doc_lens[safe_docs]                # [QB, BLOCK]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    contrib = block_weight[:, None] * tfs * (k1 + 1.0) / (tfs + norm)
    contrib = jnp.where(valid, contrib, 0.0)
    scores = jnp.zeros((n_docs_pad,), jnp.float32)
    scores = scores.at[safe_docs.reshape(-1)].add(
        contrib.reshape(-1), mode="drop")
    return scores


@partial(jax.jit, static_argnames=("n_docs_pad", "k1", "b", "k"))
def bm25_topk(block_docs, block_tfs, block_idx, block_weight, doc_lens, avgdl,
              live, n_docs_pad: int, k: int,
              k1: float = DEFAULT_K1, b: float = DEFAULT_B
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused BM25 scoring + live-mask + top-k. Returns (scores[k], docs[k]);
    empty slots have score -inf."""
    scores = bm25_block_scores(block_docs, block_tfs, block_idx, block_weight,
                               doc_lens, avgdl, n_docs_pad, k1=k1, b=b)
    scores = jnp.where(live & (scores > 0.0), scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


class Bm25Executor:
    """Per-(segment, field) BM25 query executor with host-side query prep."""

    def __init__(self, device_postings: DevicePostings, host_postings: PostingsField,
                 total_doc_count: Optional[int] = None):
        self.dev = device_postings
        self.host = host_postings
        # doc count for idf; a coordinator may override with corpus-wide
        # counts (the DFS phase analog, search/dfs/DfsPhase.java:43)
        self.doc_count = total_doc_count or device_postings.n_docs

    def query_weights(self, terms, boost: float = 1.0, df_override=None):
        """(term, idf*boost) pairs; df_override maps term -> corpus-wide df
        (the DFS-phase analog). Falls back to segment-local df per term."""
        out = []
        for t in terms:
            tid = self.host.terms.get(t)
            df = None
            if df_override is not None:
                df = df_override.get(t)
            if df is None:
                df = int(self.host.doc_freq[tid]) if tid is not None else 0
            if df <= 0 or tid is None:
                continue  # term absent from this segment: no blocks to score
            out.append((t, idf(self.doc_count, df) * boost))
        return out

    def scores(self, terms, live: jnp.ndarray, boost: float = 1.0,
               df_override=None, k1: float = DEFAULT_K1, b: float = DEFAULT_B
               ) -> jnp.ndarray:
        """Dense masked scores for the query terms (used when composing
        inside bool queries)."""
        tw = self.query_weights(terms, boost, df_override)
        block_idx, block_w = gather_query_blocks(self.host, tw)
        s = bm25_block_scores(self.dev.block_docs, self.dev.block_tfs,
                              jnp.asarray(block_idx), jnp.asarray(block_w),
                              self.dev.doc_lens, jnp.float32(self.dev.avgdl),
                              self.dev.n_docs_pad, k1=k1, b=b)
        return jnp.where(live, s, 0.0)

    def top_k(self, terms, live: jnp.ndarray, k: int, boost: float = 1.0,
              df_override=None, k1: float = DEFAULT_K1, b: float = DEFAULT_B):
        tw = self.query_weights(terms, boost, df_override)
        block_idx, block_w = gather_query_blocks(self.host, tw)
        return bm25_topk(self.dev.block_docs, self.dev.block_tfs,
                         jnp.asarray(block_idx), jnp.asarray(block_w),
                         self.dev.doc_lens, jnp.float32(self.dev.avgdl),
                         live, self.dev.n_docs_pad, k, k1=k1, b=b)
