"""BM25 scoring on device.

Replaces the reference's hot loop — Lucene BulkScorer over postings with
LegacyBM25Similarity (search/query/QueryPhase.java:331,
index/similarity/SimilarityService.java:60) and TopScoreDocCollector top-k
(search/query/TopDocsCollectorContext.java:215) — with a block-at-a-time
device program:

1. host: resolve query terms -> posting-block indices + per-term idf
   (gather_query_blocks);
2. device: gather blocks, compute per-entry BM25 contributions on the VPU,
   scatter-add into a dense per-doc score vector, top-k.

Everything is static-shaped: block count and doc count are padded to pow2
buckets, so one compiled program serves many queries.

idf follows the reference's BM25: ln(1 + (N - df + 0.5) / (df + 0.5)).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.index.segment import PostingsField, next_pow2
from elasticsearch_tpu.ops.device_segment import DevicePostings, gather_query_blocks
from elasticsearch_tpu.search.device_profile import profiled_jit
from elasticsearch_tpu.search.telemetry import record_dispatch

DEFAULT_K1 = 1.2
DEFAULT_B = 0.75


def idf(doc_count: int, doc_freq: int) -> float:
    """Reference BM25 idf (Lucene BM25Similarity.idfExplain)."""
    return float(np.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5)))


@profiled_jit("bm25_block_scores",
              static_argnames=("n_docs_pad", "k1", "b"))
def bm25_block_scores(block_docs: jnp.ndarray,     # [NB, BLOCK] int32, -1 pad
                      block_tfs: jnp.ndarray,      # [NB, BLOCK] f32
                      block_idx: jnp.ndarray,      # [QB] int32 gather indices
                      block_weight: jnp.ndarray,   # [QB] f32 (idf * query boost)
                      doc_lens: jnp.ndarray,       # [n_docs_pad] f32
                      avgdl: jnp.ndarray,          # scalar f32
                      n_docs_pad: int,
                      k1: float = DEFAULT_K1,
                      b: float = DEFAULT_B) -> jnp.ndarray:
    """Dense BM25 scores [n_docs_pad] for one query over one segment."""
    docs = block_docs[block_idx]            # [QB, BLOCK]
    tfs = block_tfs[block_idx]              # [QB, BLOCK]
    valid = docs >= 0
    safe_docs = jnp.where(valid, docs, 0)
    dl = doc_lens[safe_docs]                # [QB, BLOCK]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    contrib = block_weight[:, None] * tfs * (k1 + 1.0) / (tfs + norm)
    contrib = jnp.where(valid, contrib, 0.0)
    scores = jnp.zeros((n_docs_pad,), jnp.float32)
    scores = scores.at[safe_docs.reshape(-1)].add(
        contrib.reshape(-1), mode="drop")
    return scores


@profiled_jit("bm25_topk",
              static_argnames=("n_docs_pad", "k1", "b", "k"))
def bm25_topk(block_docs, block_tfs, block_idx, block_weight, doc_lens, avgdl,
              live, n_docs_pad: int, k: int,
              k1: float = DEFAULT_K1, b: float = DEFAULT_B
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused BM25 scoring + live-mask + top-k. Returns (scores[k], docs[k]);
    empty slots have score -inf."""
    scores = bm25_block_scores(block_docs, block_tfs, block_idx, block_weight,
                               doc_lens, avgdl, n_docs_pad, k1=k1, b=b)
    scores = jnp.where(live & (scores > 0.0), scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


# number of highest-upper-bound blocks scored in phase 1 of the pruned
# path to establish the top-k score floor (theta). Swept on the zipfian
# bench corpus (r4): 64 beats 32 (tighter theta prunes more than the
# extra phase-1 gathers cost) and 128 overshoots.
P1_BUCKET = 64

# per-dispatch ceiling on the FLAT block count: each device temp is
# FB*BLOCK*4 bytes ([FB, 128] f32 gathers), and the program holds ~4 of
# them live — 4M cells = ~2GB/temp, safely inside a 16G HBM chip.
# Larger batches split into query chunks (one compile per chunk shape).
MAX_BATCH_CELLS = 4_000_000


def bm25_flat_body(block_docs, block_tfs,
                   flat_idx,    # [FB] int32 block gather ids (0 pad)
                   flat_w,      # [FB] f32 idf*boost (0 pad)
                   flat_q,      # [FB] int32 query id (0 pad)
                   doc_lens, flat_avgdl, live,
                   n_docs_pad: int, n_q: int,
                   k1: float = DEFAULT_K1, b: float = DEFAULT_B):
    """The ONE traced flat-BM25 body: gather the batch's blocks, compute
    per-entry contributions, scatter-add into a [n_q, n_docs_pad] score
    plane, mask to live matches. Returns (scores, matched) with dead
    slots already at -inf.

    Shared verbatim by ``_bm25_flat_kernel`` (single plane / segment),
    ``_bm25_flat_kernel_seg`` (per-segment counted channel) and the mesh
    kernel's per-slot body (parallel/mesh.py ``mesh_bm25_flat``) — same
    gather order, same f32 scatter-adds — so their scores are
    bit-compatible BY CONSTRUCTION, not by a golden suite catching drift
    after the fact.

    ``flat_avgdl`` [FB] carries each gathered block's avgdl: one scalar
    broadcast for a single-segment dispatch, the owning segment's value
    per block when the gather spans a multi-segment plane — so plane
    scores use exactly the per-segment length norm the per-segment path
    does."""
    docs = block_docs[flat_idx]             # [FB, BLOCK]
    tfs = block_tfs[flat_idx]               # [FB, BLOCK]
    valid = docs >= 0
    safe = jnp.where(valid, docs, 0)
    dl = doc_lens[safe]
    norm = k1 * (1.0 - b + b * dl / flat_avgdl[:, None])
    contrib = flat_w[:, None] * tfs * (k1 + 1.0) / (tfs + norm)
    contrib = jnp.where(valid, contrib, 0.0)
    # scatter into a [n_q, n_docs_pad] score plane via flattened targets
    tgt = flat_q[:, None] * n_docs_pad + safe
    scores = jnp.zeros((n_q * n_docs_pad,), jnp.float32)
    scores = scores.at[tgt.reshape(-1)].add(contrib.reshape(-1),
                                            mode="drop")
    scores = scores.reshape(n_q, n_docs_pad)
    matched = live[None, :] & (scores > 0.0)
    scores = jnp.where(matched, scores, -jnp.inf)
    return scores, matched


def bm25_coarse_body(block_docs, block_tfs_q, flat_idx, flat_w, flat_q,
                     doc_lens_q, flat_avgdl, live, seg_ids,
                     n_docs_pad: int, n_q: int, n_segs: int, kprime: int,
                     k1: float = DEFAULT_K1, b: float = DEFAULT_B):
    """The quantized COARSE tier of the two-tier text path: the same
    gather/scatter shape as ``bm25_flat_body`` but over the plane's bf16
    mirrors (``block_tfs_q`` / ``doc_lens_q`` — half the HBM gather
    traffic, the scatter-bound classes' dominant cost), contributions
    computed in bf16 and accumulated in f32.

    Returns (coarse scores [n_q, kprime], candidate plane docs
    [n_q, kprime], per-segment match counts [n_q, n_segs]). The counts
    are EXACT despite the reduced precision: contributions are strictly
    positive wherever the f32 kernel's are (bf16 rounds positive
    products to positive values), so ``score > 0`` flags the same doc
    set — totals never depend on the re-rank. Candidate RANKING is
    coarse; the exact re-rank (``bm25_rerank_body``) restores golden
    scores, and the k'-th coarse score bounds what any excluded doc
    could have scored (the adaptive-depth margin input)."""
    docs = block_docs[flat_idx]             # [FB, BLOCK]
    tfs = block_tfs_q[flat_idx]             # [FB, BLOCK] bf16
    valid = docs >= 0
    safe = jnp.where(valid, docs, 0)
    dl = doc_lens_q[safe]                   # bf16
    h = jnp.bfloat16
    norm = h(k1) * (h(1.0 - b) + h(b) * dl
                    / flat_avgdl.astype(h)[:, None])
    contrib = flat_w.astype(h)[:, None] * tfs * h(k1 + 1.0) \
        / (tfs + norm)
    contrib = jnp.where(valid, contrib.astype(jnp.float32), 0.0)
    tgt = flat_q[:, None] * n_docs_pad + safe
    scores = jnp.zeros((n_q * n_docs_pad,), jnp.float32)
    scores = scores.at[tgt.reshape(-1)].add(contrib.reshape(-1),
                                            mode="drop")
    scores = scores.reshape(n_q, n_docs_pad)
    matched = live[None, :] & (scores > 0.0)
    scores = jnp.where(matched, scores, -jnp.inf)
    cs, cand = jax.lax.top_k(scores, kprime)
    onehot = jax.nn.one_hot(seg_ids, n_segs, dtype=jnp.int32)
    hits = matched.astype(jnp.int32) @ onehot       # [n_q, n_segs]
    return cs, cand, hits


def bm25_rerank_body(block_docs, block_tfs, flat_idx, flat_w, flat_q,
                     doc_lens, flat_avgdl, live, cand, coarse_s,
                     n_docs_pad: int, n_q: int, kprime: int, k: int,
                     k1: float = DEFAULT_K1, b: float = DEFAULT_B):
    """The EXACT tier: re-score only the coarse candidates with the f32
    arithmetic of ``bm25_flat_body`` — same gather order, same f32
    contribution formula, same linear scatter-add order — but scattered
    into a compact [n_q, kprime] candidate plane instead of the dense
    [n_q, n_docs_pad] one, so the top-k runs over k' slots.

    Candidates are sorted ascending by doc id first, making score-tie
    breaks agree with the dense kernel's lower-index-wins order. Returns
    (scores [n_q, k], plane docs [n_q, k], eps [n_q]) with ``eps`` the
    max observed |exact - coarse| among matched candidates — the
    adaptive margin's empirical error estimate."""
    order = jnp.argsort(cand, axis=1)
    cand_s = jnp.take_along_axis(cand, order, axis=1)
    cs_s = jnp.take_along_axis(coarse_s, order, axis=1)
    rows = jnp.arange(n_q, dtype=jnp.int32)[:, None]
    slot_flat = jnp.full((n_q * n_docs_pad,), -1, jnp.int32)
    slot_flat = slot_flat.at[
        (rows * n_docs_pad + cand_s).reshape(-1)].set(
        jnp.broadcast_to(jnp.arange(kprime, dtype=jnp.int32),
                         (n_q, kprime)).reshape(-1))
    docs = block_docs[flat_idx]
    tfs = block_tfs[flat_idx]
    valid = docs >= 0
    safe = jnp.where(valid, docs, 0)
    dl = doc_lens[safe]
    norm = k1 * (1.0 - b + b * dl / flat_avgdl[:, None])
    contrib = flat_w[:, None] * tfs * (k1 + 1.0) / (tfs + norm)
    contrib = jnp.where(valid, contrib, 0.0)
    slot = slot_flat[
        (flat_q[:, None] * n_docs_pad + safe).reshape(-1)
    ].reshape(safe.shape)
    tgt = jnp.where(slot >= 0, flat_q[:, None] * kprime + slot,
                    n_q * kprime)       # non-candidates: out of bounds
    cscores = jnp.zeros((n_q * kprime,), jnp.float32)
    cscores = cscores.at[tgt.reshape(-1)].add(contrib.reshape(-1),
                                              mode="drop")
    cscores = cscores.reshape(n_q, kprime)
    ok = live[cand_s] & (cscores > 0.0)
    masked = jnp.where(ok, cscores, -jnp.inf)
    s, pos = jax.lax.top_k(masked, k)
    d = jnp.take_along_axis(cand_s, pos, axis=1)
    both = ok & jnp.isfinite(cs_s)
    eps = jnp.max(jnp.where(both, jnp.abs(cscores - cs_s), 0.0), axis=1)
    return s, d, eps


@profiled_jit("bm25_coarse",
              static_argnames=("n_docs_pad", "n_q", "n_segs", "kprime",
                               "k1", "b"))
def _bm25_coarse_kernel(block_docs, block_tfs_q, flat_idx, flat_w, flat_q,
                        doc_lens_q, flat_avgdl, live, seg_ids,
                        n_docs_pad: int, n_q: int, n_segs: int,
                        kprime: int, k1: float = DEFAULT_K1,
                        b: float = DEFAULT_B):
    return bm25_coarse_body(block_docs, block_tfs_q, flat_idx, flat_w,
                            flat_q, doc_lens_q, flat_avgdl, live, seg_ids,
                            n_docs_pad, n_q, n_segs, kprime, k1=k1, b=b)


@profiled_jit("bm25_rerank",
              static_argnames=("n_docs_pad", "n_q", "kprime", "k",
                               "k1", "b"))
def _bm25_rerank_kernel(block_docs, block_tfs, flat_idx, flat_w, flat_q,
                        doc_lens, flat_avgdl, live, cand, coarse_s,
                        n_docs_pad: int, n_q: int, kprime: int, k: int,
                        k1: float = DEFAULT_K1, b: float = DEFAULT_B):
    return bm25_rerank_body(block_docs, block_tfs, flat_idx, flat_w,
                            flat_q, doc_lens, flat_avgdl, live, cand,
                            coarse_s, n_docs_pad, n_q, kprime, k,
                            k1=k1, b=b)


@profiled_jit("bm25_flat",
              static_argnames=("n_docs_pad", "n_q", "k", "k1", "b",
                               "counted"))
def _bm25_flat_kernel(block_docs, block_tfs, flat_idx, flat_w, flat_q,
                      doc_lens, flat_avgdl, live,
                      n_docs_pad: int, n_q: int, k: int,
                      k1: float = DEFAULT_K1, b: float = DEFAULT_B,
                      counted: bool = False):
    """Flat batched BM25 + top-k: the whole batch's blocks in ONE gather +
    scatter-add (``bm25_flat_body``), each block tagged with its query id.

    This replaces the padded [Q, QB] layout whose per-query gather lists
    all padded to the LARGEST plan in the batch — on zipfian query mixes
    that wasted >10x the gather/scatter work (r3 bench: 1,048,576 padded
    cells for 79,743 real survivor blocks). Here device work is
    proportional to the batch's ACTUAL block count, padded only up to one
    pow-ladder bucket.

    With ``counted`` the kernel also returns hits[n_q] = #docs with
    score > 0, read off the score plane it already computed. The count is
    EXACT for the blocks gathered: unpruned dispatches count all hits;
    pruned dispatches yield a LOWER bound (dropped blocks aren't
    observed) — the counts-then-skip collector
    (TopDocsCollectorContext.java:215) uses it to prove
    'total >= track_total_hits' without a dense pass."""
    scores, matched = bm25_flat_body(block_docs, block_tfs, flat_idx,
                                     flat_w, flat_q, doc_lens, flat_avgdl,
                                     live, n_docs_pad, n_q, k1=k1, b=b)
    s, d = jax.lax.top_k(scores, k)
    if counted:
        return s, d, jnp.sum(matched, axis=1, dtype=jnp.int32)
    return s, d


def bm25_topk_flat_counted(*args, **kw):
    return _bm25_flat_kernel(*args, **kw, counted=True)


@profiled_jit("bm25_flat_seg",
              static_argnames=("n_docs_pad", "n_q", "k", "k1", "b",
                               "n_segs"))
def _bm25_flat_kernel_seg(block_docs, block_tfs, flat_idx, flat_w, flat_q,
                          doc_lens, flat_avgdl, live, seg_ids,
                          n_docs_pad: int, n_q: int, k: int,
                          k1: float = DEFAULT_K1, b: float = DEFAULT_B,
                          n_segs: int = 1):
    """_bm25_flat_kernel with PER-SEGMENT match counts.

    ``seg_ids`` [n_docs_pad] maps each plane doc to its owning segment's
    position; hits come back [n_q, n_segs]. This is the plane analog of
    the totals-disabled per-segment contract: each segment reports
    "candidates found" truncated to the collection window (sum of
    min(matches, want) per segment), a number the fused whole-plane count
    cannot reproduce — so the kernel counts where the segments are."""
    scores, matched = bm25_flat_body(block_docs, block_tfs, flat_idx,
                                     flat_w, flat_q, doc_lens, flat_avgdl,
                                     live, n_docs_pad, n_q, k1=k1, b=b)
    s, d = jax.lax.top_k(scores, k)
    onehot = jax.nn.one_hot(seg_ids, n_segs, dtype=jnp.int32)
    hits = matched.astype(jnp.int32) @ onehot       # [n_q, n_segs]
    return s, d, hits


def flatten_plans(plans, fb_pad: int):
    """Concatenate per-query plans into flat (idx, w, qid) arrays of
    length fb_pad (block 0 / weight 0 / query 0 as padding)."""
    idx = np.zeros(fb_pad, np.int32)
    w = np.zeros(fb_pad, np.float32)
    qid = np.zeros(fb_pad, np.int32)
    off = 0
    for i, p in enumerate(plans):
        n = p.n_blocks
        idx[off : off + n] = p.idx
        w[off : off + n] = p.w
        qid[off : off + n] = i
        off += n
    return idx, w, qid


def qb_bucket(n: int, minimum: int = 32) -> int:
    """Gather-list bucket size: a coarse x8 ladder, x2 above 16K.

    Every distinct gather shape costs a full XLA compile (~seconds); pow2
    buckets churn with each query batch. The x8 ladder wastes at most 8x
    gather padding (device cost: <1ms) to cap the shape space at ~4
    compiles; above 16K blocks the padding waste dominates compile
    amortization, so the ladder tightens to x2. (BENCH_r06's bm25_flat
    bucket blow-up — 20 live shapes, 2 warmup recompile storms — was
    investigated as a ladder problem, but widening the x2 region to x4
    measurably HALVED CPU-fallback batch throughput while merging
    almost nothing: the hot sizes sit on shared rung boundaries, and
    the cardinality is really the (FB, n_q, k) cross-product of the
    bench's many traffic patterns. The ladder stays; the per-request
    program-variant churn — the ``counted`` flag flipping with batch
    composition — was removed in ``dispatch_flat`` instead, which is
    what keeps one serving pattern in single-digit buckets.)"""
    b = max(minimum, 1)
    while b < n:
        b *= 8 if b < 16384 else 2
    return b


class QueryPlan:
    """Host-side per-query block plan with block-max upper bounds.

    For each candidate posting block: its gather index, weight (idf*boost),
    and ub = weight*(k1+1)*block_max_impact — the max BM25 contribution any
    doc in the block can receive from its term. other_ub is the sum of the
    OTHER query terms' global per-doc bounds, so ub + other_ub bounds the
    total score of every doc in the block (the WAND invariant)."""

    __slots__ = ("idx", "w", "ub", "other_ub")

    def __init__(self, idx, w, ub, other_ub):
        self.idx = np.asarray(idx, np.int32)
        self.w = np.asarray(w, np.float32)
        self.ub = np.asarray(ub, np.float64)
        self.other_ub = np.asarray(other_ub, np.float64)

    @property
    def n_blocks(self) -> int:
        return len(self.idx)

    def survivors(self, theta: float) -> "QueryPlan":
        """Blocks whose docs could still reach the top-k given score floor
        theta. Sound: a doc in a dropped block scores at most ub + other_ub
        < theta, so it provably cannot enter the final top-k. The small
        slack absorbs f32-vs-f64 rounding between device scores and host
        bounds."""
        if not np.isfinite(theta):
            return self
        # slack scales with |theta| so accumulated f32 scatter-add error on
        # large scores can't unsoundly drop a block holding a true top-k doc
        slack = max(1e-4, 1e-5 * abs(theta))
        keep = (self.ub + self.other_ub) >= (theta - slack)
        return QueryPlan(self.idx[keep], self.w[keep], self.ub[keep],
                         self.other_ub[keep])

    def top_by_ub(self, m: int) -> "QueryPlan":
        if self.n_blocks <= m:
            return self
        order = np.argsort(-self.ub, kind="stable")[:m]
        return QueryPlan(self.idx[order], self.w[order], self.ub[order],
                         self.other_ub[order])

    @staticmethod
    def concat(plans: "list[QueryPlan]",
               idx_offsets=None) -> "QueryPlan":
        """One plan from many (the plane path: per-segment plans joined
        with each segment's block base added to its gather indices).
        Per-block bounds are segment-local and stay valid unchanged."""
        if not plans:
            return QueryPlan([], [], [], [])
        idx_parts = []
        for i, p in enumerate(plans):
            off = 0 if idx_offsets is None else int(idx_offsets[i])
            idx_parts.append(p.idx + np.int32(off))
        return QueryPlan(np.concatenate(idx_parts),
                         np.concatenate([p.w for p in plans]),
                         np.concatenate([p.ub for p in plans]),
                         np.concatenate([p.other_ub for p in plans]))


# doc-space granularity of the range-partitioned WAND bound: other-term
# maxima are tracked per GRID-doc cell, so a stopword block only inherits a
# rare term's bound if the rare term actually has postings in the block's
# doc range (BMW's aligned block maxima, re-expressed on a fixed grid for
# vectorized host planning). Swept on the zipfian bench corpus (r4):
# 64-doc cells prune ~6 points more of the block space than 256 at equal
# host planning cost; 32 pays more planning than it saves.
WAND_GRID = 64


class _RangeMax:
    """Sparse-table max over a per-term coarse doc-range array: build
    O(R log R), vectorized O(1) range-max queries."""

    def __init__(self, cell_ub: np.ndarray):
        self.levels = [cell_ub]
        r = len(cell_ub)
        span = 1
        while span * 2 <= r:
            prev = self.levels[-1]
            self.levels.append(np.maximum(prev[: r - span * 2 + 1],
                                          prev[span : r - span + 1]))
            span *= 2

    def query(self, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
        """Elementwise max over cells [lo_i, hi_i] (inclusive, lo <= hi)."""
        length = hi - lo + 1
        j = np.maximum(np.int64(np.log2(np.maximum(length, 1))), 0)
        out = np.zeros(len(lo), np.float64)
        for jj in np.unique(j):
            lvl = self.levels[min(int(jj), len(self.levels) - 1)]
            m = j == jj
            span = 1 << min(int(jj), len(self.levels) - 1)
            a = lvl[np.minimum(lo[m], len(lvl) - 1)]
            b_ = lvl[np.minimum(np.maximum(hi[m] - span + 1, 0),
                                len(lvl) - 1)]
            out[m] = np.maximum(a, b_)
        return out


class TermCellIndex:
    """Per-term posting-level WAND bound index, built lazily once per term.

    For a term, records which WAND_GRID-doc cells hold any of its postings
    and the max impact within each (exact, from true tfs and doc lengths),
    compressed to touched cells with a sparse table for O(1) range-max.
    Query-independent: multiply by idf*boost at query time."""

    PAIR_CACHE_CAP = 8192

    def __init__(self, block_docs: np.ndarray, block_tfs: np.ndarray,
                 doc_lens: np.ndarray, avgdl: float,
                 k1: float = DEFAULT_K1, b: float = DEFAULT_B):
        self.block_docs = block_docs
        self.block_tfs = block_tfs
        self.doc_lens = doc_lens
        self.avgdl = max(avgdl, 1e-9)
        self.k1 = k1
        self.b = b
        self._cache: dict = {}
        self._range_cache: dict = {}
        self._pair_cache: dict = {}

    def term_cells(self, start: int, count: int):
        """(touched cells ascending [int64], RangeMax over their impacts)."""
        got = self._cache.get(start)
        if got is not None:
            return got
        docs = self.block_docs[start : start + count].reshape(-1)
        tfs = self.block_tfs[start : start + count].reshape(-1)
        valid = docs >= 0
        d = docs[valid].astype(np.int64)
        f = tfs[valid].astype(np.float64)
        dl = self.doc_lens[d]
        norm = self.k1 * (1.0 - self.b + self.b * dl / self.avgdl)
        imp = f / np.maximum(f + norm, 1e-9)
        cells = d // WAND_GRID               # ascending: docs are sorted
        uniq, first = np.unique(cells, return_index=True)
        cmax = np.maximum.reduceat(imp, first) if len(first) else \
            np.zeros(0, np.float64)
        got = (uniq, _RangeMax(cmax))
        self._cache[start] = got
        return got

    def term_cell_ranges(self, start: int, count: int):
        """(c_lo, c_hi) cell range per block of the term at ``start``."""
        got = self._range_cache.get(start)
        if got is None:
            blk = self.block_docs[start : start + count]
            mins = np.maximum(blk[:, 0], 0)
            maxs = np.maximum(blk.max(axis=1), 0)
            got = (mins // WAND_GRID, maxs // WAND_GRID)
            self._range_cache[start] = got
        return got

    def pair_bound(self, start_i: int, count_i: int,
                   start_j: int, count_j: int) -> np.ndarray:
        """Unweighted max impact of term_j's actual postings within each
        of term_i's block doc-ranges (len count_i). Cached per
        (start_i, start_j): zipfian query mixes repeat frequent-term pairs
        constantly, so the per-pair range queries — the dominant host
        planning cost — amortize across the whole query stream."""
        key = (start_i, start_j)
        got = self._pair_cache.get(key)
        if got is not None:
            return got
        c_lo, c_hi = self.term_cell_ranges(start_i, count_i)
        cells_j, table_j = self.term_cells(start_j, count_j)
        lo = np.searchsorted(cells_j, c_lo, side="left")
        hi = np.searchsorted(cells_j, c_hi, side="right") - 1
        has = hi >= lo
        out = np.zeros(count_i, np.float64)
        if has.any():
            out[has] = table_j.query(lo[has], hi[has])
        while len(self._pair_cache) >= self.PAIR_CACHE_CAP:
            self._pair_cache.pop(next(iter(self._pair_cache)))
        self._pair_cache[key] = out
        return out


def build_query_plan(terms_with_weights, term_blocks_fn, block_max_impact,
                     cell_index: Optional[TermCellIndex] = None,
                     k1: float = DEFAULT_K1) -> QueryPlan:
    """Shared host prep for the pruned BM25 path.

    terms_with_weights: [(term, idf*boost)];
    term_blocks_fn(term) -> (start, count) into the block arrays;
    block_max_impact: f32 [n_blocks] (PostingsField.block_max_impact);
    per-block doc ranges come from the cell_index's own cached tables.

    other_ub for a block is the sum, over the query's OTHER terms, of that
    term's max possible contribution among its actual postings within the
    block's doc range (via cell_index) — the aligned block-max WAND bound.
    Cell granularity only loosens the bound (still sound). Without a
    cell_index the bound falls back to the terms' global maxima."""
    per_term = []     # (start, count, weight, bounds)
    for term, weight in terms_with_weights:
        start, count = term_blocks_fn(term)
        if count == 0:
            continue
        impacts = block_max_impact[start : start + count]
        bounds = weight * (k1 + 1.0) * impacts.astype(np.float64)
        per_term.append((start, count, weight, bounds))
    if not per_term:
        return QueryPlan([], [], [], [])

    idx_parts = []
    w_parts = []
    ub_parts = []
    other_parts = []
    for t_i, (start, count, weight, bounds) in enumerate(per_term):
        idx_parts.append(np.arange(start, start + count, dtype=np.int32))
        w_parts.append(np.full(count, weight, np.float32))
        ub_parts.append(bounds)
        o = np.zeros(count, np.float64)
        for t_j, (s_j, cnt_j, w_j, bounds_j) in enumerate(per_term):
            if t_j == t_i:
                continue
            if cell_index is None:
                o += float(bounds_j.max())
                continue
            o += cell_index.pair_bound(start, count, s_j, cnt_j) \
                * (w_j * (k1 + 1.0))
        other_parts.append(o)
    return QueryPlan(np.concatenate(idx_parts), np.concatenate(w_parts),
                     np.concatenate(ub_parts), np.concatenate(other_parts))


class Bm25Executor:
    """Per-(segment, field) BM25 query executor with host-side query prep."""

    def __init__(self, device_postings: DevicePostings, host_postings: PostingsField,
                 total_doc_count: Optional[int] = None):
        self.dev = device_postings
        self.host = host_postings
        # doc count for idf; a coordinator may override with corpus-wide
        # counts (the DFS phase analog, search/dfs/DfsPhase.java:43)
        self.doc_count = total_doc_count or device_postings.n_docs

    def query_weights(self, terms, boost: float = 1.0, df_override=None):
        """(term, idf*boost) pairs; df_override maps term -> corpus-wide df
        (the DFS-phase analog). Falls back to segment-local df per term.
        ``terms`` entries may be plain strings or (term, per_term_boost)
        pairs — the latter carries bool/should per-clause boosts into the
        WAND path."""
        out = []
        for t in terms:
            tb = boost
            if isinstance(t, tuple):
                t, clause_boost = t
                tb = boost * float(clause_boost)
            tid = self.host.terms.get(t)
            df = None
            if df_override is not None:
                df = df_override.get(t)
            if df is None:
                df = int(self.host.doc_freq[tid]) if tid is not None else 0
            if df <= 0 or tid is None:
                continue  # term absent from this segment: no blocks to score
            out.append((t, idf(self.doc_count, df) * tb))
        return out

    def _avgdl(self, avgdl_override=None) -> float:
        """Effective average doc length: a coordinator may override with the
        corpus-wide value (the CollectionStatistics half of the DFS phase —
        search/dfs/DfsPhase.java:43 ships sumTotalTermFreq/docCount so every
        shard norms against the same global avgdl)."""
        if avgdl_override is not None and avgdl_override > 0:
            return float(avgdl_override)
        return float(self.dev.avgdl)

    def scores(self, terms, live: jnp.ndarray, boost: float = 1.0,
               df_override=None, k1: float = DEFAULT_K1, b: float = DEFAULT_B,
               avgdl_override=None) -> jnp.ndarray:
        """Dense masked scores for the query terms (used when composing
        inside bool queries)."""
        tw = self.query_weights(terms, boost, df_override)
        block_idx, block_w = gather_query_blocks(self.host, tw)
        s = bm25_block_scores(self.dev.block_docs, self.dev.block_tfs,
                              jnp.asarray(block_idx), jnp.asarray(block_w),
                              self.dev.doc_lens,
                              jnp.float32(self._avgdl(avgdl_override)),
                              self.dev.n_docs_pad, k1=k1, b=b)
        return jnp.where(live, s, 0.0)

    def top_k(self, terms, live: jnp.ndarray, k: int, boost: float = 1.0,
              df_override=None, k1: float = DEFAULT_K1, b: float = DEFAULT_B,
              avgdl_override=None):
        tw = self.query_weights(terms, boost, df_override)
        block_idx, block_w = gather_query_blocks(self.host, tw)
        return bm25_topk(self.dev.block_docs, self.dev.block_tfs,
                         jnp.asarray(block_idx), jnp.asarray(block_w),
                         self.dev.doc_lens,
                         jnp.float32(self._avgdl(avgdl_override)),
                         live, self.dev.n_docs_pad, k, k1=k1, b=b)

    def top_k_batch(self, queries, live: jnp.ndarray, k: int,
                    boost: float = 1.0, df_override=None,
                    k1: float = DEFAULT_K1, b: float = DEFAULT_B,
                    prune: bool = True, avgdl_override=None,
                    count_hits: bool = False):
        """Batched, block-max-pruned BM25 over Q queries (each a term list).

        Two phases, each ONE device dispatch for the whole batch:
          1. score only each query's P1_BUCKET highest-upper-bound blocks;
             the k-th partial score is a floor (theta) on the true k-th
             score — partial sums only underestimate;
          2. re-score exactly, but only blocks whose WAND bound
             (ub + other-term bounds) clears theta. Zipfian stopword
             blocks never get gathered — this is where the HBM-traffic
             saving is (TopDocsCollectorContext.java:215's block-max WAND
             early termination, re-expressed as static-shape phases).
        Returns (scores [Q, k], doc ids [Q, k]) — plus hits [Q] when
        ``count_hits`` — and records
        last_prune_stats = (blocks_total, blocks_scored)."""
        avgdl = self._avgdl(avgdl_override)
        plans = self.build_plans(queries, boost, df_override, k1, b, avgdl)
        total_blocks = sum(p.n_blocks for p in plans)
        max_blocks = max((p.n_blocks for p in plans), default=1)
        if not prune or max_blocks <= P1_BUCKET:
            # every block is gathered — counts (if asked) are EXACT
            self.last_prune_stats = (total_blocks, total_blocks)
            self.last_hits_exact = True
            return self._dispatch_flat(plans, live, k, k1, b, avgdl,
                                       counted=count_hits)
        p1 = [p.top_by_ub(P1_BUCKET) for p in plans]
        s1, _ = self._dispatch_flat(p1, live, k, k1, b, avgdl)
        theta = np.asarray(s1)[:, k - 1]          # -inf when < k matches
        return self.finish_pruned(plans, theta, live, k, k1, b, avgdl,
                                  count_hits)

    def build_plans(self, queries, boost: float = 1.0, df_override=None,
                    k1: float = DEFAULT_K1, b: float = DEFAULT_B,
                    avgdl: Optional[float] = None):
        """Host planning for a batch: one WAND block plan per query."""
        if avgdl is None:
            avgdl = self._avgdl(None)
        hp = self.host
        # per-term cell index for the aligned WAND bound (within a term,
        # blocks are doc-sorted; entry 0 of every block is always valid).
        # Keyed by (k1, b, avgdl) in a small FIFO-bounded dict so DFS
        # (global avgdl) and plain (segment avgdl) traffic interleave
        # without rebuilding each other's lazily-filled cell tables.
        cells_key = (k1, b, avgdl)
        cells = getattr(self, "_cell_cache", None)
        if cells is None:
            cells = self._cell_cache = {}
        cell_index = cells.get(cells_key)
        if cell_index is None:
            while len(cells) >= 4:
                cells.pop(next(iter(cells)))
            cell_index = cells[cells_key] = TermCellIndex(
                hp.block_docs, hp.block_tfs, hp.doc_lens, avgdl, k1=k1, b=b)
        plans = []
        for terms in queries:
            tw = self.query_weights(terms, boost, df_override)
            plans.append(build_query_plan(
                tw, self.host.term_blocks,
                self.host.block_max_impact(k1, b, avgdl),
                cell_index=cell_index, k1=k1))
        return plans

    def phase1(self, plans, live: jnp.ndarray, k: int,
               k1: float = DEFAULT_K1, b: float = DEFAULT_B,
               avgdl: Optional[float] = None):
        """Dispatch phase 1 (top-ub blocks) and return the DEVICE scores
        [Q, k] without syncing — a multi-segment shard launches every
        segment's phase 1 before blocking once for all thetas."""
        if avgdl is None:
            avgdl = self._avgdl(None)
        p1 = [p.top_by_ub(P1_BUCKET) for p in plans]
        s1, _ = self._dispatch_flat(p1, live, k, k1, b, avgdl)
        return s1

    def finish_pruned(self, plans, theta, live: jnp.ndarray, k: int,
                      k1: float = DEFAULT_K1, b: float = DEFAULT_B,
                      avgdl: Optional[float] = None,
                      count_hits: bool = False):
        """Phase 2: drop blocks whose WAND bound misses theta (one theta
        per query — possibly a shard-global one tighter than this
        segment's own) and score the survivors exactly."""
        if avgdl is None:
            avgdl = self._avgdl(None)
        total_blocks = sum(p.n_blocks for p in plans)
        p2 = [p.survivors(float(t)) for p, t in zip(plans, theta)]
        scored = sum(p.n_blocks for p in p2)
        p1_cost = sum(min(p.n_blocks, P1_BUCKET) for p in plans)
        self.last_prune_stats = (total_blocks,
                                 min(scored + p1_cost, total_blocks))
        # pruned counts observe only survivor blocks: a LOWER bound
        self.last_hits_exact = scored >= total_blocks
        return self._dispatch_flat(p2, live, k, k1, b, avgdl,
                                   counted=count_hits)

    # per-dispatch ceiling on the query dimension: the score plane is
    # n_q * n_docs_pad f32 — 64 queries over a 16M-doc pad is 4GB, so
    # bigger batches split (and phase-1 theta syncs once per chunk)
    MAX_CHUNK_Q = 64

    def _dispatch_flat(self, plans, live, k, k1, b, avgdl, counted=False):
        return dispatch_flat(self.dev.block_docs, self.dev.block_tfs,
                             self.dev.doc_lens, self.dev.n_docs_pad,
                             plans, live, k, k1, b, avgdl=avgdl,
                             counted=counted)


MAX_CHUNK_Q = Bm25Executor.MAX_CHUNK_Q


def dispatch_flat(block_docs, block_tfs, doc_lens, n_docs_pad: int,
                  plans, live, k: int, k1: float, b: float,
                  avgdl: Optional[float] = None,
                  block_avgdl: Optional[np.ndarray] = None,
                  counted: bool = False, counter: Optional[list] = None,
                  count_segments: Optional[Tuple] = None):
    """Flat-dispatch a batch of plans over one block store: device work
    scales with the ACTUAL total block count (one pow-ladder bucket of
    padding), never with Q x max-plan as the padded layout did. Chunks
    bound both the gather temp (MAX_BATCH_CELLS) and the score plane
    (MAX_CHUNK_Q); n_q pads to a pow2 bucket so shapes stay bucketed.

    The block store is either one segment's (scalar ``avgdl``) or a whole
    shard plane's (``block_avgdl`` [NB] host array, gathered per plan so
    every block keeps its owning segment's norm). ``counter``, when given,
    accumulates the number of device programs launched (bench/stats
    observability for dispatches-per-query).

    ``count_segments``: (seg_ids device [n_docs_pad] int32, n_segs) —
    hits come back PER SEGMENT [n_q, n_segs] instead of [n_q] (the
    totals-disabled plane contract); overrides ``counted``."""
    chunks: list = []
    cur: list = []
    cells = 0
    for p in plans:
        nb = max(p.n_blocks, 1)
        if cur and (len(cur) >= MAX_CHUNK_Q
                    or cells + nb > MAX_BATCH_CELLS):
            chunks.append(cur)
            cur, cells = [], 0
        cur.append(p)
        cells += nb
    if cur:
        chunks.append(cur)
    if count_segments is not None:
        counted = True
    out_s, out_d, out_h = [], [], []
    for chunk in chunks:
        n_real = len(chunk)
        n_q = next_pow2(n_real, minimum=1)
        fb = qb_bucket(max(sum(p.n_blocks for p in chunk), 1))
        idx, w, qid = flatten_plans(chunk, fb)
        if block_avgdl is not None:
            flat_avg = block_avgdl[idx].astype(np.float32)
        else:
            flat_avg = np.full(fb, avgdl, np.float32)
        if counter is not None:
            counter.append(1)
        record_dispatch()
        if count_segments is not None:
            seg_ids, n_segs = count_segments
            got = _bm25_flat_kernel_seg(
                block_docs, block_tfs,
                jnp.asarray(idx), jnp.asarray(w), jnp.asarray(qid),
                doc_lens, jnp.asarray(flat_avg), live, seg_ids,
                n_docs_pad, n_q, k, k1=k1, b=b, n_segs=n_segs)
        else:
            # ALWAYS the counted program: hits are one cheap reduction
            # off the score plane the kernel materializes anyway. The
            # counted flag used to flip with BATCH COMPOSITION (phase A
            # counts only when an exact-mode member rides along), so
            # real serving compiled both variants of each (FB, n_q, k)
            # — half of them pure compile-cache waste. One variant
            # keeps a warm serving pattern in single-digit buckets.
            got = bm25_topk_flat_counted(
                block_docs, block_tfs,
                jnp.asarray(idx), jnp.asarray(w), jnp.asarray(qid),
                doc_lens, jnp.asarray(flat_avg), live,
                n_docs_pad, n_q, k, k1=k1, b=b)
        if len(chunks) == 1:
            s, d, h = got
            if counted:
                return s[:n_real], d[:n_real], np.asarray(h)[:n_real]
            return s[:n_real], d[:n_real]
        s, d, h = got
        if counted:
            out_h.append(np.asarray(h)[:n_real])
        out_s.append(np.asarray(s)[:n_real])
        out_d.append(np.asarray(d)[:n_real])
    s = jnp.asarray(np.concatenate(out_s))
    d = jnp.asarray(np.concatenate(out_d))
    if counted:
        return s, d, np.concatenate(out_h)
    return s, d
