"""Device-side aggregation collection: segment-sum kernels.

Reference shape: search/aggregations/AggregationPhase.java:40 collects by
iterating matching docs per segment in Java. Here the per-segment
collection for the bucket workhorses (terms over keyword ordinals,
numeric/date histograms) is ONE scatter-add dispatch over device-resident
columns — the "device partial-agg + host reduce" split (SURVEY §7 step 8):
the device turns [n_docs] masks into [n_buckets] partial count/sum/min/max
vectors, the host keeps the map-shaped merge/finalize it already had.

Bucket-id computation happens on device too (floor((v - base)/interval)),
so the only host↔device traffic per (segment, agg) is the final
[n_buckets] partials.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from elasticsearch_tpu.search.device_profile import profiled_jit

__all__ = ["ordinal_counts", "histogram_partials",
           "ordinal_counts_plane", "histogram_partials_plane"]


@profiled_jit("aggs_ordinal_counts", static_argnames=("n_buckets",))
def ordinal_counts(ords: jnp.ndarray,     # [E] int32 bucket ids (-1 pad)
                   owner_ok: jnp.ndarray,  # [E] bool: owner doc matched
                   n_buckets: int) -> jnp.ndarray:
    """Counts per ordinal from a (doc, ord) occurrence table already
    deduped per doc — the terms-agg device half."""
    valid = owner_ok & (ords >= 0)
    safe = jnp.where(valid, ords, 0)
    return jnp.zeros((n_buckets,), jnp.int32).at[safe].add(
        valid.astype(jnp.int32), mode="drop")


@profiled_jit("aggs_histogram", static_argnames=("n_buckets",))
def histogram_partials(values: jnp.ndarray,   # [N_pad] int32 column
                       exists: jnp.ndarray,   # [N_pad] bool
                       mask: jnp.ndarray,     # [N_pad] bool query matches
                       base: jnp.ndarray,     # scalar int32 (min bucket id)
                       interval: jnp.ndarray,  # scalar int32
                       n_buckets: int
                       ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                  jnp.ndarray, jnp.ndarray]:
    """(counts, sums, mins, maxs) per histogram bucket in one dispatch.

    Bucketing is INTEGER floor-division — exact, so a segment served by
    this kernel and one served by the host collector (float64
    floor(v/interval)) always agree on bucket keys; the caller gates on
    integral columns and intervals. The sum/min/max vectors come free
    with the same scatter pass, so metric sub-aggs on the SAME field
    reduce without a second pass."""
    ok = exists & mask
    ids = jnp.floor_divide(values, interval) - base
    ok = ok & (ids >= 0) & (ids < n_buckets)
    safe = jnp.where(ok, ids, 0)
    vf = values.astype(jnp.float32)          # exact: caller gates |v|<2^24
    counts = jnp.zeros((n_buckets,), jnp.int32).at[safe].add(
        ok.astype(jnp.int32), mode="drop")
    sums = jnp.zeros((n_buckets,), jnp.float32).at[safe].add(
        jnp.where(ok, vf, 0.0), mode="drop")
    mins = jnp.full((n_buckets,), jnp.inf, jnp.float32).at[safe].min(
        jnp.where(ok, vf, jnp.inf), mode="drop")
    maxs = jnp.full((n_buckets,), -jnp.inf, jnp.float32).at[safe].max(
        jnp.where(ok, vf, -jnp.inf), mode="drop")
    return counts, sums, mins, maxs


# ---------------------------------------------------------------------------
# plane-wide batched kernels (PlaneColumns)
#
# The per-segment kernels above take one segment's column and one plan's
# mask; a drain with S segments and P distinct plans pays S*P dispatches.
# The plane variants take the CONCATENATED multi-segment column (a
# PlaneColumns part) and a [P, N_pad] stack of query masks, so one
# dispatch serves P plans x all segments for an agg family. The host
# merge for terms/histogram partials is commutative, so the whole-plane
# scatter IS the merged per-segment result — no per-segment demux is
# needed for these families. When a future family does need per-segment
# attribution, the part's doc_base searchsorted (PlanePart.demux) splits
# plane doc ids back into (segment, local doc) pairs.


@profiled_jit("aggs_ordinal_counts_plane", static_argnames=("n_buckets",))
def ordinal_counts_plane(ords: jnp.ndarray,    # [E_pad] int32 global ords
                         owners: jnp.ndarray,  # [E_pad] int32 plane doc ids
                         masks: jnp.ndarray,   # [P, N_pad] bool query masks
                         n_buckets: int) -> jnp.ndarray:
    """[P, n_buckets] counts: the terms-agg device half for a whole
    shard's plane and a batch of plans in one scatter-add dispatch.

    `ords` carry GLOBAL ordinals (remapped at pack time), -1 padded;
    `owners` index into the plane doc space so each plan's [N_pad] mask
    gathers straight into owner_ok."""
    valid_base = ords >= 0
    safe = jnp.where(valid_base, ords, 0)

    def one(mask):
        valid = mask[owners] & valid_base
        return jnp.zeros((n_buckets,), jnp.int32).at[safe].add(
            valid.astype(jnp.int32), mode="drop")

    return jax.vmap(one)(masks)


@profiled_jit("aggs_histogram_plane", static_argnames=("n_buckets",))
def histogram_partials_plane(values: jnp.ndarray,     # [N_pad] int32 column
                             exists: jnp.ndarray,     # [N_pad] bool
                             masks: jnp.ndarray,      # [P, N_pad] bool
                             bases: jnp.ndarray,      # [P] int32
                             intervals: jnp.ndarray,  # [P] int32
                             n_buckets: int
                             ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                        jnp.ndarray, jnp.ndarray]:
    """[P, n_buckets] (counts, sums, mins, maxs) in one dispatch.

    Per-plan base/interval ride along as traced [P] vectors, so plans
    over the same field with DIFFERENT intervals still share the single
    dispatch; n_buckets is the pow2-padded max over the batch and each
    plan reads back only its own prefix. Same exactness contract as
    histogram_partials: integral values/intervals, |v| < 2^24."""
    vf = values.astype(jnp.float32)

    def one(mask, base, interval):
        ok = exists & mask
        ids = jnp.floor_divide(values, interval) - base
        ok = ok & (ids >= 0) & (ids < n_buckets)
        safe = jnp.where(ok, ids, 0)
        counts = jnp.zeros((n_buckets,), jnp.int32).at[safe].add(
            ok.astype(jnp.int32), mode="drop")
        sums = jnp.zeros((n_buckets,), jnp.float32).at[safe].add(
            jnp.where(ok, vf, 0.0), mode="drop")
        mins = jnp.full((n_buckets,), jnp.inf, jnp.float32).at[safe].min(
            jnp.where(ok, vf, jnp.inf), mode="drop")
        maxs = jnp.full((n_buckets,), -jnp.inf, jnp.float32).at[safe].max(
            jnp.where(ok, vf, -jnp.inf), mode="drop")
        return counts, sums, mins, maxs

    return jax.vmap(one)(masks, bases, intervals)
