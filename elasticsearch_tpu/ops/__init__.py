from elasticsearch_tpu.ops.bm25 import Bm25Executor, bm25_block_scores, bm25_topk, idf
from elasticsearch_tpu.ops.device_segment import (
    PLANES,
    DeviceFeatures,
    DevicePostings,
    DeviceVectors,
    PlaneRegistry,
    device_live_mask,
    gather_query_blocks,
)
from elasticsearch_tpu.ops.fusion import linear_fuse, rrf_fuse
from elasticsearch_tpu.ops.knn import KnnExecutor, knn_topk, knn_topk_batch, vector_scores
from elasticsearch_tpu.ops.sparse import SparseExecutor, sparse_scores, sparse_topk

__all__ = [
    "Bm25Executor",
    "DeviceFeatures",
    "DevicePostings",
    "DeviceVectors",
    "PLANES",
    "PlaneRegistry",
    "KnnExecutor",
    "SparseExecutor",
    "bm25_block_scores",
    "bm25_topk",
    "device_live_mask",
    "gather_query_blocks",
    "idf",
    "knn_topk",
    "knn_topk_batch",
    "linear_fuse",
    "rrf_fuse",
    "sparse_scores",
    "sparse_topk",
    "vector_scores",
]
