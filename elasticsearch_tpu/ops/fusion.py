"""Hybrid rank fusion on device.

RRF (reciprocal rank fusion) is a BASELINE.json capability absent from the
reference snapshot (BASELINE.md config #4 — "RRF not present in reference";
the reference only has query rescoring, search/rescore/QueryRescorer.java).
Designed device-first: each retriever contributes its ranked doc list; RRF
scores are scatter-added into a dense array and re-top-k'd — one fused
program, no host round-trip between retrievers.

Also provides linear score fusion (normalized weighted sum), the other
common hybrid.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from elasticsearch_tpu.search.device_profile import profiled_jit


@profiled_jit("rrf_fuse",
              static_argnames=("n_docs_pad", "k", "rank_constant"))
def rrf_fuse(doc_lists: jnp.ndarray,   # [R, K] int32 per-retriever ranked docs (-1 pad)
             n_docs_pad: int, k: int,
             rank_constant: int = 60) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """score(d) = sum_r 1 / (rank_constant + rank_r(d)); rank is 1-based.
    Returns (scores [k], docs [k])."""
    R, K = doc_lists.shape
    ranks = jnp.arange(1, K + 1, dtype=jnp.float32)[None, :]      # [1, K]
    contrib = 1.0 / (rank_constant + ranks)                       # [1, K]
    contrib = jnp.broadcast_to(contrib, (R, K))
    valid = doc_lists >= 0
    safe = jnp.where(valid, doc_lists, 0)
    contrib = jnp.where(valid, contrib, 0.0)
    scores = jnp.zeros((n_docs_pad,), jnp.float32)
    scores = scores.at[safe.reshape(-1)].add(contrib.reshape(-1), mode="drop")
    top = jnp.where(scores > 0.0, scores, -jnp.inf)
    return jax.lax.top_k(top, k)


@profiled_jit("rrf_fuse_batch",
              static_argnames=("n_docs_pad", "k", "rank_constant"))
def rrf_fuse_batch(doc_lists: jnp.ndarray,   # [B, R, K] int32 (-1 pad)
                   n_docs_pad: int, k: int,
                   rank_constant: int = 60
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """B concurrent RRF fusions in ONE device program: each row carries
    one hybrid query's R ranked lists over its own dense doc-id space
    (ids local to the row; -1 pads both short lists and absent
    retrievers). The serving-path counterpart of ``rrf_fuse`` — the
    coordinator's fusion batcher coalesces concurrent hybrid requests
    into this single dispatch instead of B scatter-add programs.
    Returns (scores [B, k], docs [B, k]); doc -1 past each row's
    matches."""
    def one(row):
        return rrf_fuse(row, n_docs_pad=n_docs_pad, k=k,
                        rank_constant=rank_constant)
    scores, docs = jax.vmap(one)(doc_lists)
    docs = jnp.where(jnp.isfinite(scores), docs, -1)
    return scores, docs


@profiled_jit("linear_fuse", static_argnames=("k", "normalize"))
def linear_fuse(score_arrays: jnp.ndarray,   # [R, N_pad] dense scores per retriever
                weights: jnp.ndarray,        # [R]
                live: jnp.ndarray,           # [N_pad] bool
                k: int,
                normalize: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted sum of (optionally min-max normalized) retriever scores."""
    s = score_arrays
    if normalize:
        mx = jnp.max(s, axis=1, keepdims=True)
        mn = jnp.min(jnp.where(s > 0, s, jnp.inf), axis=1, keepdims=True)
        mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
        rng = jnp.maximum(mx - mn, 1e-9)
        s = jnp.where(s > 0, (s - mn) / rng, 0.0)
    fused = jnp.einsum("rn,r->n", s, weights)
    fused = jnp.where(live & (fused > 0), fused, -jnp.inf)
    return jax.lax.top_k(fused, k)
