"""Dense-vector similarity on device.

Replaces the reference's script_score vector loops —
ScoreScriptUtils.cosineSimilarity / dotProduct / l2norm iterating binary doc
values per document (x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:132,151)
— with a tiled MXU matmul over the HBM-resident, segment-padded vector matrix,
fused with top-k. Scores use the same positive-score transforms ES applies:

  cosine:      (1 + cos) / 2
  dot_product: sigmoid-free 0.5 + dot/2 for normalized vectors is ES 8.x;
               this snapshot's painless returned raw dot — we use the
               standard modern transform for ranking stability
  l2_norm:     1 / (1 + dist)

bf16 is used for the multiply (MXU native) with f32 accumulation.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops.device_segment import DeviceVectors


@partial(jax.jit, static_argnames=("similarity",))
def vector_scores(matrix: jnp.ndarray,     # [N_pad, D] f32
                  norms: jnp.ndarray,      # [N_pad] f32
                  exists: jnp.ndarray,     # [N_pad] bool
                  query: jnp.ndarray,      # [D] f32
                  similarity: str = "cosine") -> jnp.ndarray:
    """Dense similarity scores [N_pad]; missing vectors score 0."""
    q = query.astype(jnp.bfloat16)
    m = matrix.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        m, q[:, None],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0]                                                    # [N_pad] f32
    if similarity == "dot_product":
        scores = 0.5 + dots / 2.0
    elif similarity == "cosine":
        qn = jnp.linalg.norm(query) + 1e-30
        cos = dots / (norms * qn + 1e-30)
        scores = (1.0 + cos) / 2.0
    else:  # l2_norm
        q2 = jnp.sum(query * query)
        d2 = norms * norms + q2 - 2.0 * dots
        d2 = jnp.maximum(d2, 0.0)
        scores = 1.0 / (1.0 + jnp.sqrt(d2))
    return jnp.where(exists, scores, 0.0)


@partial(jax.jit, static_argnames=("similarity", "k"))
def knn_topk(matrix, norms, exists, live, query, k: int,
             similarity: str = "cosine") -> Tuple[jnp.ndarray, jnp.ndarray]:
    scores = vector_scores(matrix, norms, exists, query, similarity)
    scores = jnp.where(live & exists, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _batch_scores(matrix, norms, queries, similarity: str) -> jnp.ndarray:
    """[B, N_pad] similarity plane from one [B, D] x [D, N] MXU matmul
    (bf16 multiply, f32 accumulate) — shared by the masked and unmasked
    batch kernels so their per-row arithmetic cannot diverge."""
    q = queries.astype(jnp.bfloat16)
    m = matrix.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        q, m,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                          # [B, N_pad]
    if similarity == "dot_product":
        return 0.5 + dots / 2.0
    if similarity == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True) + 1e-30
        return (1.0 + dots / (norms[None, :] * qn + 1e-30)) / 2.0
    q2 = jnp.sum(queries * queries, axis=1, keepdims=True)
    d2 = jnp.maximum(norms[None, :] ** 2 + q2 - 2.0 * dots, 0.0)
    return 1.0 / (1.0 + jnp.sqrt(d2))


@partial(jax.jit, static_argnames=("similarity", "k"))
def knn_topk_batch(matrix, norms, exists, live, queries, k: int,
                   similarity: str = "cosine") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched kNN: queries [B, D] -> (scores [B, k], docs [B, k]).

    One big [B, D] x [D, N] MXU matmul — the throughput shape for the
    SIFT1M-style benchmark."""
    scores = _batch_scores(matrix, norms, queries, similarity)
    scores = jnp.where((live & exists)[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("similarity", "k"))
def knn_topk_batch_masked(matrix, norms, exists, live, queries, masks,
                          k: int, similarity: str = "cosine"
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Filtered batched kNN: per-query filter masks [B, N_pad] ride the
    same [B, D] x [D, N] matmul — the filtered-kNN serving shape
    (autocomplete / faceted nav), where Q concurrent queries each carry
    their own filter-context mask but share the corpus scan."""
    scores = _batch_scores(matrix, norms, queries, similarity)
    scores = jnp.where((live & exists)[None, :] & masks, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


class KnnExecutor:
    """Per-(segment, field) exact kNN executor."""

    def __init__(self, device_vectors: DeviceVectors):
        self.dev = device_vectors

    def top_k(self, query, live, k: int):
        q = jnp.asarray(query, jnp.float32)
        return knn_topk(self.dev.matrix, self.dev.norms, self.dev.exists,
                        live, q, k, self.dev.similarity)

    def top_k_batch(self, queries, live, k: int, masks=None):
        """Batched exact kNN over Q query vectors: ONE [Q, D] x [D, N] MXU
        matmul instead of Q matvec dispatches (the serving-path counterpart
        of the bench-only knn_topk_batch shape). The query dimension pads
        to a pow2 bucket so the jit cache stays warm across batch sizes;
        padded rows come back sliced off.

        ``masks`` carries the filter-context of filtered kNN: a single
        [N_pad] bool mask shared by every query (the autocomplete /
        faceted-nav case — it simply folds into ``live``, exactly as the
        solo path's ``live & fmask``), or a [Q, N_pad] stack of per-query
        masks applied inside the one masked matmul dispatch."""
        q_host = np.asarray(queries, np.float32)
        n_real = q_host.shape[0]
        from elasticsearch_tpu.index.segment import next_pow2
        n_pad = next_pow2(max(n_real, 1), minimum=1)
        if n_pad != n_real:
            q_host = np.concatenate(
                [q_host, np.zeros((n_pad - n_real, q_host.shape[1]),
                                  np.float32)])
        if masks is not None and getattr(masks, "ndim", 1) == 2:
            m_host = np.zeros((n_pad, np.asarray(masks).shape[1]), bool)
            m_host[:n_real] = np.asarray(masks)   # padded rows stay False
            s, d = knn_topk_batch_masked(
                self.dev.matrix, self.dev.norms, self.dev.exists, live,
                jnp.asarray(q_host), jnp.asarray(m_host), k,
                self.dev.similarity)
            return s[:n_real], d[:n_real]
        if masks is not None:
            live = live & masks                   # shared filter mask
        s, d = knn_topk_batch(self.dev.matrix, self.dev.norms,
                              self.dev.exists, live,
                              jnp.asarray(q_host), k, self.dev.similarity)
        return s[:n_real], d[:n_real]

    def scores(self, query, live) -> jnp.ndarray:
        q = jnp.asarray(query, jnp.float32)
        s = vector_scores(self.dev.matrix, self.dev.norms, self.dev.exists,
                          q, self.dev.similarity)
        return jnp.where(live, s, 0.0)
