"""Dense-vector similarity on device.

Replaces the reference's script_score vector loops —
ScoreScriptUtils.cosineSimilarity / dotProduct / l2norm iterating binary doc
values per document (x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:132,151)
— with a tiled MXU matmul over the HBM-resident, segment-padded vector matrix,
fused with top-k. Scores use the same positive-score transforms ES applies:

  cosine:      (1 + cos) / 2
  dot_product: sigmoid-free 0.5 + dot/2 for normalized vectors is ES 8.x;
               this snapshot's painless returned raw dot — we use the
               standard modern transform for ranking stability
  l2_norm:     1 / (1 + dist)

bf16 is used for the multiply (MXU native) with f32 accumulation.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops.device_segment import DeviceVectors
from elasticsearch_tpu.search.device_profile import profiled_jit
from elasticsearch_tpu.search.telemetry import record_dispatch


@profiled_jit("knn_vector_scores", static_argnames=("similarity",))
def vector_scores(matrix: jnp.ndarray,     # [N_pad, D] f32
                  norms: jnp.ndarray,      # [N_pad] f32
                  exists: jnp.ndarray,     # [N_pad] bool
                  query: jnp.ndarray,      # [D] f32
                  similarity: str = "cosine") -> jnp.ndarray:
    """Dense similarity scores [N_pad]; missing vectors score 0."""
    q = query.astype(jnp.bfloat16)
    m = matrix.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        m, q[:, None],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0]                                                    # [N_pad] f32
    if similarity == "dot_product":
        scores = 0.5 + dots / 2.0
    elif similarity == "cosine":
        qn = jnp.linalg.norm(query) + 1e-30
        cos = dots / (norms * qn + 1e-30)
        scores = (1.0 + cos) / 2.0
    else:  # l2_norm
        q2 = jnp.sum(query * query)
        d2 = norms * norms + q2 - 2.0 * dots
        d2 = jnp.maximum(d2, 0.0)
        scores = 1.0 / (1.0 + jnp.sqrt(d2))
    return jnp.where(exists, scores, 0.0)


@profiled_jit("knn_topk", static_argnames=("similarity", "k"))
def knn_topk(matrix, norms, exists, live, query, k: int,
             similarity: str = "cosine") -> Tuple[jnp.ndarray, jnp.ndarray]:
    scores = vector_scores(matrix, norms, exists, query, similarity)
    scores = jnp.where(live & exists, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _batch_scores(matrix, norms, queries, similarity: str) -> jnp.ndarray:
    """[B, N_pad] similarity plane from one [B, D] x [D, N] MXU matmul
    (bf16 multiply, f32 accumulate) — shared by the masked and unmasked
    batch kernels so their per-row arithmetic cannot diverge. The
    positive-score transform is _coarse_similarity, the same one the
    quantized coarse pass applies to its rescaled int8 dots."""
    q = queries.astype(jnp.bfloat16)
    m = matrix.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        q, m,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                          # [B, N_pad]
    return _coarse_similarity(dots, norms, queries, similarity)


def knn_topk_body(matrix, norms, allowed, queries, masks, k: int,
                  similarity: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """EXACT batched top-k over one vector plane: the ``_batch_scores``
    matmul + eligibility mask + top_k, shared VERBATIM by the
    single-shard batch kernels and the mesh slot kernel
    (parallel/mesh.py ``mesh_knn_topk``) — the ``bm25_flat_body``
    precedent, so mesh==fanout parity is structural. ``allowed`` [N] is
    the per-plane eligibility row (live & exists, plus a shared filter
    when every member carries the same one); ``masks`` [B, N] is the
    per-query filter stack for heterogeneous filters, or None."""
    scores = _batch_scores(matrix, norms, queries, similarity)
    ok = allowed[None, :] if masks is None else (allowed[None, :] & masks)
    ts, td = jax.lax.top_k(jnp.where(ok, scores, -jnp.inf), k)
    return ts, td


@profiled_jit("knn_topk_batch", static_argnames=("similarity", "k"))
def knn_topk_batch(matrix, norms, exists, live, queries, k: int,
                   similarity: str = "cosine") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched kNN: queries [B, D] -> (scores [B, k], docs [B, k]).

    One big [B, D] x [D, N] MXU matmul — the throughput shape for the
    SIFT1M-style benchmark."""
    return knn_topk_body(matrix, norms, live & exists, queries, None, k,
                         similarity)


@profiled_jit("knn_topk_batch_masked",
              static_argnames=("similarity", "k"))
def knn_topk_batch_masked(matrix, norms, exists, live, queries, masks,
                          k: int, similarity: str = "cosine"
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Filtered batched kNN: per-query filter masks [B, N_pad] ride the
    same [B, D] x [D, N] matmul — the filtered-kNN serving shape
    (autocomplete / faceted nav), where Q concurrent queries each carry
    their own filter-context mask but share the corpus scan."""
    return knn_topk_body(matrix, norms, live & exists, queries, masks, k,
                         similarity)


def pad_queries_pow2(queries) -> Tuple[np.ndarray, int]:
    """Pad the query batch to a pow2 row count (zeros) so the jit cache
    stays warm across batch sizes; returns (padded, n_real). One
    implementation shared by the exact executor and the quantized plane
    pass — their pads must stay in lockstep."""
    from elasticsearch_tpu.index.segment import next_pow2
    q_host = np.asarray(queries, np.float32)
    n_real = q_host.shape[0]
    n_pad = next_pow2(max(n_real, 1), minimum=1)
    if n_pad != n_real:
        q_host = np.concatenate(
            [q_host, np.zeros((n_pad - n_real, q_host.shape[1]),
                              np.float32)])
    return q_host, n_real


def pad_mask_rows_pow2(masks, n_pad: int) -> np.ndarray:
    """Stacked per-query filter masks padded to the query batch's pow2
    row count; padded rows stay False (they match nothing)."""
    m = np.asarray(masks)
    out = np.zeros((n_pad, m.shape[1]), bool)
    out[: m.shape[0]] = m
    return out


def _quantize_queries(queries: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-row int8 quantization of the query batch (the doc
    side is quantized once at plane pack time)."""
    qmax = jnp.max(jnp.abs(queries), axis=1, keepdims=True)
    qscale = jnp.maximum(qmax / 127.0, 1e-30)
    qq = jnp.clip(jnp.round(queries / qscale), -127, 127).astype(jnp.int8)
    return qq, qscale


def _coarse_similarity(dots, norms, queries, similarity: str) -> jnp.ndarray:
    if similarity == "dot_product":
        return 0.5 + dots / 2.0
    if similarity == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True) + 1e-30
        return (1.0 + dots / (norms[None, :] * qn + 1e-30)) / 2.0
    q2 = jnp.sum(queries * queries, axis=1, keepdims=True)
    d2 = jnp.maximum(norms[None, :] ** 2 + q2 - 2.0 * dots, 0.0)
    return 1.0 / (1.0 + jnp.sqrt(d2))


def _coarse_plane(q8, scales, norms, queries, similarity: str
                  ) -> jnp.ndarray:
    """[B, N_pad] coarse similarity: int8 x int8 MXU matmul (int32
    accumulate, rescaled to f32) + the positive-score transform. Shared
    by the masked and unmasked coarse kernels."""
    qq, qscale = _quantize_queries(queries)
    dots = jax.lax.dot_general(
        qq, q8,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32) * (qscale * scales[None, :])     # [B, N_pad]
    return _coarse_similarity(dots, norms, queries, similarity)


@profiled_jit("knn_coarse", static_argnames=("similarity", "kprime"))
def knn_coarse_candidates(q8, scales, norms, allowed, queries,
                          kprime: int, similarity: str = "cosine"
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantized coarse pass over the FULL plane: (coarse scores [B, k'],
    candidate doc ids [B, k']) per query. Ranking-only — the exact f32
    re-rank (knn_rerank_exact) restores golden scores for the survivors;
    the coarse scores feed the adaptive-depth margin check (the k'-th
    coarse score bounds what any EXCLUDED doc could have scored)."""
    s = _coarse_plane(q8, scales, norms, queries, similarity)
    s = jnp.where(allowed[None, :], s, -jnp.inf)
    return jax.lax.top_k(s, kprime)


@profiled_jit("knn_coarse_masked",
              static_argnames=("similarity", "kprime"))
def knn_coarse_candidates_masked(q8, scales, norms, allowed, queries,
                                 masks, kprime: int,
                                 similarity: str = "cosine"
                                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Coarse pass with per-query filter masks [B, N_pad] (filtered kNN)."""
    s = _coarse_plane(q8, scales, norms, queries, similarity)
    s = jnp.where(allowed[None, :] & masks, s, -jnp.inf)
    return jax.lax.top_k(s, kprime)


def _rerank_scores(matrix, norms, queries, cand, similarity: str
                   ) -> jnp.ndarray:
    """Exact f32 scores [B, K'] of the gathered candidate rows, with the
    SAME bf16-multiply/f32-accumulate arithmetic and positive-score
    transforms as _batch_scores — one implementation, so a scoring fix
    cannot diverge between the masked and unmasked re-rank kernels."""
    rows = matrix[cand]                                    # [B, K', D]
    dots = jnp.einsum("bd,bkd->bk", queries.astype(jnp.bfloat16),
                      rows.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    bnorms = norms[cand]                                   # [B, K']
    if similarity == "dot_product":
        return 0.5 + dots / 2.0
    if similarity == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True) + 1e-30
        return (1.0 + dots / (bnorms * qn + 1e-30)) / 2.0
    q2 = jnp.sum(queries * queries, axis=1, keepdims=True)
    d2 = jnp.maximum(bnorms * bnorms + q2 - 2.0 * dots, 0.0)
    return 1.0 / (1.0 + jnp.sqrt(d2))


def knn_rerank_body(matrix, norms, allowed, queries, cand, coarse_s,
                    masks, k: int, similarity: str
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The ONE exact-re-rank body, shared by the single-shard profiled
    kernels below and the mesh per-slot variant (parallel/mesh.py
    ``mesh_knn_rerank``) so their scores cannot diverge.

    Candidates are sorted ascending by doc id first: ``lax.top_k`` breaks
    score ties by LOWER index, so sorting makes re-rank tie-breaks agree
    with the dense exact kernel's lower-doc-id-wins order — quantization
    must not reorder equal-scored docs. Returns (scores [B, k], doc ids
    [B, k], eps [B]) where ``eps`` is the max observed |exact - coarse|
    deviation among the re-ranked candidates — the empirical error
    estimate the adaptive-depth margin check scales from."""
    order = jnp.argsort(cand, axis=1)
    cand_s = jnp.take_along_axis(cand, order, axis=1)
    cs_s = jnp.take_along_axis(coarse_s, order, axis=1)
    s = _rerank_scores(matrix, norms, queries, cand_s, similarity)
    ok = allowed[cand_s]
    if masks is not None:
        ok = ok & jnp.take_along_axis(masks, cand_s, axis=1)
    sm = jnp.where(ok, s, -jnp.inf)
    ts, pos = jax.lax.top_k(sm, k)
    td = jnp.take_along_axis(cand_s, pos, axis=1)
    td = jnp.where(jnp.isfinite(ts), td, -1)
    both = ok & jnp.isfinite(cs_s)
    eps = jnp.max(jnp.where(both, jnp.abs(s - cs_s), 0.0), axis=1)
    return ts, td, eps


@profiled_jit("knn_rerank", static_argnames=("similarity", "k"))
def knn_rerank_exact(matrix, norms, allowed, queries, cand, coarse_s,
                     k: int, similarity: str = "cosine"
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Exact f32 re-rank of the coarse candidates: identical top-k to the
    exact path whenever the true top-k survives the coarse pass — which
    the adaptive-depth margin check (plane_exec) proves per query from
    the returned eps, deepening and re-dispatching when it cannot."""
    return knn_rerank_body(matrix, norms, allowed, queries, cand,
                           coarse_s, None, k, similarity)


@profiled_jit("knn_rerank_masked",
              static_argnames=("similarity", "k"))
def knn_rerank_exact_masked(matrix, norms, allowed, queries, cand,
                            coarse_s, masks, k: int,
                            similarity: str = "cosine"
                            ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                       jnp.ndarray]:
    """knn_rerank_exact with per-query filter masks re-applied to the
    gathered candidates (a masked-out doc must stay out even if the
    coarse pass leaked it in)."""
    return knn_rerank_body(matrix, norms, allowed, queries, cand,
                           coarse_s, masks, k, similarity)


class KnnExecutor:
    """Per-(segment, field) exact kNN executor."""

    def __init__(self, device_vectors: DeviceVectors):
        self.dev = device_vectors

    def top_k(self, query, live, k: int):
        record_dispatch()
        q = jnp.asarray(query, jnp.float32)
        return knn_topk(self.dev.matrix, self.dev.norms, self.dev.exists,
                        live, q, k, self.dev.similarity)

    def top_k_batch(self, queries, live, k: int, masks=None):
        """Batched exact kNN over Q query vectors: ONE [Q, D] x [D, N] MXU
        matmul instead of Q matvec dispatches (the serving-path counterpart
        of the bench-only knn_topk_batch shape). The query dimension pads
        to a pow2 bucket so the jit cache stays warm across batch sizes;
        padded rows come back sliced off.

        ``masks`` carries the filter-context of filtered kNN: a single
        [N_pad] bool mask shared by every query (the autocomplete /
        faceted-nav case — it simply folds into ``live``, exactly as the
        solo path's ``live & fmask``), or a [Q, N_pad] stack of per-query
        masks applied inside the one masked matmul dispatch."""
        record_dispatch()
        q_host, n_real = pad_queries_pow2(queries)
        if masks is not None and getattr(masks, "ndim", 1) == 2:
            m_host = pad_mask_rows_pow2(masks, q_host.shape[0])
            s, d = knn_topk_batch_masked(
                self.dev.matrix, self.dev.norms, self.dev.exists, live,
                jnp.asarray(q_host), jnp.asarray(m_host), k,
                self.dev.similarity)
            return s[:n_real], d[:n_real]
        if masks is not None:
            live = live & masks                   # shared filter mask
        s, d = knn_topk_batch(self.dev.matrix, self.dev.norms,
                              self.dev.exists, live,
                              jnp.asarray(q_host), k, self.dev.similarity)
        return s[:n_real], d[:n_real]

    def scores(self, query, live) -> jnp.ndarray:
        q = jnp.asarray(query, jnp.float32)
        s = vector_scores(self.dev.matrix, self.dev.norms, self.dev.exists,
                          q, self.dev.similarity)
        return jnp.where(live, s, 0.0)
