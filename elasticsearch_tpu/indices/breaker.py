"""Hierarchical circuit breakers with device (HBM) memory accounting.

Reference: common/breaker/ChildMemoryCircuitBreaker.java +
indices/breaker/HierarchyCircuitBreakerService.java:64 — a parent breaker
over child breakers (request, fielddata, ...) that refuses work with 429
before the JVM heap dies. The TPU-native re-design adds the budget the
reference never had to manage: **HBM**. Device-resident segment arrays
(postings/vector/feature blocks) and per-query transients (dense score
vectors, block gathers) are estimated against a ``device`` child breaker,
so an over-budget query degrades to a 429 instead of an XLA OOM that
kills every query on the chip.

The service is process-global because the accelerator is process-global
(one HBM pool per process, shared by every in-process node — the same
reason jax exposes one device runtime). Nodes surface its stats under
``_nodes/stats.breakers``.

Residency is released by GC: device-array owners register a weakref
finalizer, so accounting follows the true lifetime of the HBM allocation
without manual bookkeeping at every drop site.
"""

from __future__ import annotations

import threading
import weakref
from contextlib import contextmanager
from typing import Any, Dict, Optional

from elasticsearch_tpu.utils.errors import CircuitBreakingError

__all__ = ["ChildBreaker", "DeviceCharge", "HierarchyCircuitBreakerService",
           "BREAKERS", "account_device_arrays", "charge_device"]

GB = 1 << 30


class ChildBreaker:
    """One named budget; estimates are added pessimistically and released
    when the work (or the resident object) goes away."""

    def __init__(self, name: str, limit: int, overhead: float = 1.0,
                 parent: Optional["HierarchyCircuitBreakerService"] = None):
        self.name = name
        self.limit = int(limit)
        self.overhead = overhead
        self.used = 0
        self.trip_count = 0
        self._parent = parent
        self._lock = threading.Lock()
        # observe() scopes currently watching this breaker's high-water
        # mark (normally empty — one list check on the charge path)
        self._observers: list = []

    def add_estimate(self, n_bytes: int, label: str = "<unknown>") -> None:
        n_bytes = int(n_bytes)
        with self._lock:
            new_used = self.used + n_bytes
            if new_used * self.overhead > self.limit > 0:
                self.trip_count += 1
                raise CircuitBreakingError(
                    f"[{self.name}] data for [{label}] would be "
                    f"[{new_used}/{_h(new_used)}] which is larger than the "
                    f"limit of [{self.limit}/{_h(self.limit)}]")
            self.used = new_used
            for obs in self._observers:
                if new_used > obs.peak:
                    obs.peak = new_used
        if self._parent is not None:
            try:
                self._parent.check_parent(n_bytes, label)
            except CircuitBreakingError:
                with self._lock:
                    self.used -= n_bytes
                raise

    def release(self, n_bytes: int) -> None:
        with self._lock:
            self.used = max(0, self.used - int(n_bytes))

    @contextmanager
    def limit_scope(self, n_bytes: int, label: str = "<transient>"):
        """Transient accounting for the duration of one operation."""
        self.add_estimate(n_bytes, label)
        try:
            yield
        finally:
            self.release(n_bytes)

    @contextmanager
    def observe(self):
        """Watch the breaker's high-water mark for the duration of one
        operation: ``obs.peak - obs.base`` after the scope is the charge
        the operation actually added (outer transients plus everything
        charged inside them). Pure observation — never refuses work —
        so callers can feed MEASURED costs back into their own
        admission estimates (the shard batcher's per-key cap)."""
        obs = _ChargeObservation(self.used)
        with self._lock:
            self._observers.append(obs)
        try:
            yield obs
        finally:
            with self._lock:
                self._observers.remove(obs)

    def stats(self) -> Dict[str, Any]:
        return {"limit_size_in_bytes": self.limit,
                "estimated_size_in_bytes": self.used,
                "overhead": self.overhead,
                "tripped": self.trip_count}


class _ChargeObservation:
    """One observe() scope's view: ``base`` at entry, ``peak`` high-water."""

    __slots__ = ("base", "peak")

    def __init__(self, base: int):
        self.base = base
        self.peak = base


def _h(n: int) -> str:
    for unit in ("b", "kb", "mb", "gb", "tb"):
        if abs(n) < 1024:
            return f"{n:.1f}{unit}" if unit != "b" else f"{n}{unit}"
        n /= 1024
    return f"{n:.1f}pb"


class HierarchyCircuitBreakerService:
    """Parent limit over {request, fielddata, device} children."""

    def __init__(self, total_limit: int = 12 * GB,
                 request_limit: int = 6 * GB,
                 fielddata_limit: int = 4 * GB,
                 device_limit: int = 12 * GB,
                 request_cache_limit: int = 1 * GB):
        self.parent_limit = int(total_limit)
        self.parent_trip_count = 0
        self._lock = threading.Lock()
        self.breakers: Dict[str, ChildBreaker] = {
            "request": ChildBreaker("request", request_limit, parent=self),
            "fielddata": ChildBreaker("fielddata", fielddata_limit,
                                      parent=self),
            "device": ChildBreaker("device", device_limit, parent=self),
            # resident request-cache entries (indices/request_cache.py):
            # the cache's own max_bytes LRU budget evicts cold entries
            # first; this child is the hard backstop that makes cache
            # memory visible to the parent and lets a starved node
            # refuse NEW entries (typed) while serving uncached
            "request_cache": ChildBreaker("request_cache",
                                          request_cache_limit,
                                          parent=self),
        }

    def breaker(self, name: str) -> ChildBreaker:
        return self.breakers[name]

    def check_parent(self, added: int, label: str) -> None:
        total = sum(b.used for b in self.breakers.values())
        if total > self.parent_limit > 0:
            with self._lock:
                self.parent_trip_count += 1
            raise CircuitBreakingError(
                f"[parent] data for [{label}] would be "
                f"[{total}/{_h(total)}] which is larger than the limit of "
                f"[{self.parent_limit}/{_h(self.parent_limit)}]")

    def configure(self, **limits: int) -> None:
        """configure(device=..., request=..., total=...) — tests and the
        dynamic-settings path resize budgets in place."""
        for name, limit in limits.items():
            if name in ("total", "parent"):
                self.parent_limit = int(limit)
            else:
                self.breakers[name].limit = int(limit)

    def reset(self) -> None:
        for b in self.breakers.values():
            b.used = 0
            b.trip_count = 0
        self.parent_trip_count = 0

    def stats(self) -> Dict[str, Any]:
        out = {name: b.stats() for name, b in self.breakers.items()}
        out["parent"] = {
            "limit_size_in_bytes": self.parent_limit,
            "estimated_size_in_bytes": sum(
                b.used for b in self.breakers.values()),
            "tripped": self.parent_trip_count}
        return out


# one pool of HBM per process -> one breaker service per process
BREAKERS = HierarchyCircuitBreakerService()


class DeviceCharge:
    """One accounted device allocation with an idempotent early release.

    GC-driven release (the weakref finalizer charge_device installs)
    remains the backstop, but an evicting cache (the plane registry's
    breaker-pressure path) must be able to hand the budget back BEFORE
    the last in-flight query drops its reference — otherwise the
    evict-and-retry loop can never free enough to admit the new resident.
    The transient undercount while an evicted-but-referenced array drains
    is the point of eviction, not a leak: the finalizer then no-ops."""

    __slots__ = ("_breaker", "n_bytes", "_released")

    def __init__(self, breaker: ChildBreaker, n_bytes: int):
        self._breaker = breaker
        self.n_bytes = int(n_bytes)
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._breaker.release(self.n_bytes)


def charge_device(owner: Any, n_bytes: int, label: str,
                  service: Optional[HierarchyCircuitBreakerService]
                  = None, return_charge: bool = False):
    """Charge the ``device`` breaker for ``n_bytes`` about to go resident
    on device, tying the release to ``owner``'s lifetime via a weakref
    finalizer. Call BEFORE the upload (sizes are computable from the host
    arrays) — charging after the jnp.asarray would let the very allocation
    that trips the breaker OOM the chip first. ``return_charge=True``
    returns the DeviceCharge handle for callers (eviction-driven caches)
    that need to release ahead of GC."""
    svc = service or BREAKERS
    breaker = svc.breaker("device")
    breaker.add_estimate(int(n_bytes), label)
    charge = DeviceCharge(breaker, n_bytes)
    weakref.finalize(owner, charge.release)
    return charge if return_charge else int(n_bytes)


def account_device_arrays(owner: Any, arrays, label: str,
                          service: Optional[HierarchyCircuitBreakerService]
                          = None, return_charge: bool = False):
    """charge_device() with the byte count summed from host-side arrays
    (numpy ``nbytes``). Pass the HOST arrays before converting."""
    n_bytes = 0
    for a in arrays:
        nb = getattr(a, "nbytes", None)
        if nb is None and hasattr(a, "size") and hasattr(a, "dtype"):
            nb = a.size * a.dtype.itemsize
        n_bytes += int(nb or 0)
    return charge_device(owner, n_bytes, label, service,
                         return_charge=return_charge)
