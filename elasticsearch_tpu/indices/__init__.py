from elasticsearch_tpu.indices.indices_service import IndexService, IndicesService
from elasticsearch_tpu.indices.cluster_state_service import IndicesClusterStateService

__all__ = ["IndexService", "IndicesService", "IndicesClusterStateService"]
