"""Node-level registry of index services and their shards.

Reference analog: indices/IndicesService.java:176 — creates/deletes
``IndexService`` instances as cluster state demands; each IndexService owns
that node's shard copies of one index (index/IndexService.java). Storage
paths hang off the node's data directory (env/NodeEnvironment analog).
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, Iterable, Optional

from elasticsearch_tpu.cluster.metadata import IndexMetadata
from elasticsearch_tpu.index.shard import IndexShard, ShardId
from elasticsearch_tpu.index.store import Store
from elasticsearch_tpu.index.translog import Translog
from elasticsearch_tpu.mapping import MapperService
from elasticsearch_tpu.utils.errors import (
    IndexNotFoundError, ShardNotFoundError,
)
from elasticsearch_tpu.utils.settings import parse_time_to_seconds


def _retention_settings(settings: Dict) -> tuple:
    """(retention ops, lease period seconds) from an index settings dict
    (index.soft_deletes.retention.ops / .retention_lease.period)."""
    raw_ops = settings.get("index.soft_deletes.retention.ops")
    ops = int(raw_ops) if raw_ops is not None else 1024
    raw_period = settings.get("index.soft_deletes.retention_lease.period")
    period = (parse_time_to_seconds(raw_period)
              if raw_period is not None else 12 * 3600.0)
    return max(0, ops), period


class IndexService:
    """This node's view of one index: mapper service + local shard copies."""

    def __init__(self, metadata: IndexMetadata,
                 data_path: Optional[str] = None,
                 disk_io=None, node_id: Optional[str] = None):
        self.metadata = metadata
        self.mapper_service = MapperService(dict(metadata.mappings) or None)
        self.shards: Dict[int, IndexShard] = {}
        self.data_path = data_path
        self.disk_io = disk_io
        self.node_id = node_id

    def _shard_paths(self, shard: int, fresh_store: bool = False):
        if self.data_path is None:
            return None, None
        base = os.path.join(self.data_path, self.metadata.uuid, str(shard))
        if fresh_store:
            # peer recovery builds this copy from scratch off the primary:
            # whatever is on disk (including corruption markers from a
            # previous failed copy) must not leak into the new one
            shutil.rmtree(base, ignore_errors=True)
        os.makedirs(base, exist_ok=True)
        return (Store(os.path.join(base, "index"), disk_io=self.disk_io),
                Translog(os.path.join(base, "translog"),
                         disk_io=self.disk_io))

    def create_shard(self, shard: int, primary: bool, primary_term: int = 1,
                     allocation_id: Optional[str] = None,
                     fresh_store: bool = False) -> IndexShard:
        if shard in self.shards:
            raise ValueError(f"shard [{self.metadata.name}][{shard}] "
                             f"already exists on this node")
        store, translog = self._shard_paths(shard, fresh_store=fresh_store)
        settings = dict(self.metadata.settings or {})
        index_sort = None
        sort_field = settings.get("index.sort.field")
        if sort_field:
            if isinstance(sort_field, list):
                sort_field = sort_field[0]   # one sort key supported
            sort_order = settings.get("index.sort.order", "asc")
            if isinstance(sort_order, list):
                sort_order = sort_order[0]
            index_sort = (str(sort_field), str(sort_order))
        retention_ops, lease_period = _retention_settings(settings)
        index_shard = IndexShard(
            ShardId(self.metadata.name, shard), self.mapper_service,
            primary=primary, primary_term=primary_term,
            allocation_id=allocation_id, store=store, translog=translog,
            index_sort=index_sort,
            check_on_startup=settings.get(
                "index.shard.check_on_startup", False),
            soft_deletes_retention_ops=retention_ops,
            retention_lease_period_s=lease_period,
            node_id=self.node_id)
        self.shards[shard] = index_shard
        return index_shard

    def shard(self, shard: int) -> IndexShard:
        if shard not in self.shards:
            raise ShardNotFoundError(
                f"shard [{self.metadata.name}][{shard}] not on this node")
        return self.shards[shard]

    def remove_shard(self, shard: int, delete_data: bool = False) -> None:
        index_shard = self.shards.pop(shard, None)
        if index_shard is not None:
            index_shard.close()
        if delete_data and self.data_path is not None:
            path = os.path.join(self.data_path, self.metadata.uuid, str(shard))
            shutil.rmtree(path, ignore_errors=True)

    def update_metadata(self, metadata: IndexMetadata) -> None:
        if metadata.mappings and metadata.version > self.metadata.version:
            self.mapper_service.merge(dict(metadata.mappings))
        old_retention = _retention_settings(dict(self.metadata.settings or {}))
        self.metadata = metadata
        new_retention = _retention_settings(dict(metadata.settings or {}))
        if new_retention != old_retention:
            # dynamic soft-deletes settings reach live shards immediately
            retention_ops, lease_period = new_retention
            for index_shard in self.shards.values():
                index_shard.update_retention_settings(
                    retention_ops=retention_ops,
                    lease_period_s=lease_period)

    def close(self) -> None:
        for shard in self.shards.values():
            shard.close()
        self.shards.clear()


class IndicesService:
    def __init__(self, data_path: Optional[str] = None, disk_io=None,
                 node_id: Optional[str] = None):
        self.indices: Dict[str, IndexService] = {}
        self.data_path = data_path
        self.node_id = node_id
        # the DiskIO seam every shard Store/Translog writes through
        # (None = the shared default); the chaos harness injects a faulty
        # implementation here
        self.disk_io = disk_io

    def create_index(self, metadata: IndexMetadata) -> IndexService:
        if metadata.name in self.indices:
            return self.indices[metadata.name]
        service = IndexService(metadata, data_path=self.data_path,
                               disk_io=self.disk_io, node_id=self.node_id)
        self.indices[metadata.name] = service
        return service

    def index_service(self, name: str) -> IndexService:
        if name not in self.indices:
            raise IndexNotFoundError(name)
        return self.indices[name]

    def has_index(self, name: str) -> bool:
        return name in self.indices

    def shard(self, index: str, shard: int) -> IndexShard:
        return self.index_service(index).shard(shard)

    def has_shard(self, index: str, shard: int) -> bool:
        return index in self.indices and shard in self.indices[index].shards

    def has_on_disk_data(self, metadata: IndexMetadata, shard: int) -> bool:
        """True if this node's data path holds a committed store for the
        shard (a commit point exists). Used to prefer in-place store
        recovery over failing a copy whose data is intact on disk."""
        if self.data_path is None:
            return False
        import glob as _glob
        return bool(_glob.glob(os.path.join(
            self.data_path, metadata.uuid, str(shard), "index",
            "commit-*.json")))

    def local_shard_state(self, index_uuid: Optional[str],
                          shard: int) -> Optional[Dict[str, object]]:
        """On-disk metadata of this node's copy of one shard (commit
        watermarks, recorded allocation id, corruption markers) WITHOUT
        instantiating an IndexService — the gateway fetch must answer for
        indices a freshly-rebooted process hasn't applied state for yet.
        None when this node has no directory for the copy at all."""
        if self.data_path is None or not index_uuid:
            return None
        base = os.path.join(self.data_path, index_uuid, str(shard), "index")
        if not os.path.isdir(base):
            return None
        return Store(base, disk_io=self.disk_io).local_shard_state()

    def remove_index(self, name: str, delete_data: bool = False) -> None:
        service = self.indices.pop(name, None)
        if service is None:
            return
        uuid = service.metadata.uuid
        service.close()
        if delete_data and self.data_path is not None:
            shutil.rmtree(os.path.join(self.data_path, uuid),
                          ignore_errors=True)

    def all_shards(self) -> Iterable[IndexShard]:
        for service in self.indices.values():
            yield from service.shards.values()

    def stats(self) -> Dict[str, object]:
        return {
            "indices": {
                name: {str(sid): shard.doc_stats()
                       for sid, shard in svc.shards.items()}
                for name, svc in self.indices.items()
            }
        }

    def close(self) -> None:
        for service in self.indices.values():
            service.close()
        self.indices.clear()
