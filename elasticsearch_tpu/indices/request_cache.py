"""The two-tier request cache for duplicate-heavy traffic.

Reference analog: indices/IndicesRequestCache.java:69 — shard-level
results cached by reader identity and normalized request bytes,
invalidated when the reader changes. This build rebuilds it around the
engine's **search generation stamp** (index/engine.py): every
refresh / delete-visibility / merge / restore bumps a per-shard integer
and records WHY, so

- the hot-path lookup is one attribute read plus one dict probe — no
  engine lock, no O(segments) freshness-tuple build, no reader
  acquisition (the PR 9 intake consult paid a freshness walk per
  lookup);
- every invalidation is **typed**: entries dropped because their
  generation moved count under the cause that moved it
  (refresh / delete / merge / restore — anything else is "unknown",
  which the test suite pins at zero, the telemetry-taxonomy precedent).

Two tiers share the machinery:

- :class:`ShardRequestCache` (one per data node's
  SearchTransportService): response rows keyed by
  (shard, generation, normalized plan). size=0 bodies — counts and the
  aggregation dashboards — cache by default, exactly the reference's
  default coverage; the top-k shapes (text/kNN/sparse hits+totals)
  cache when ``search.request_cache.topk`` is on fleet-wide or the
  request opts in with ``"request_cache": true`` (the reference's
  ``?request_cache=true`` contract for size>0).
- :class:`FusedResultCache` (one per coordinator's
  TransportSearchAction): the FUSED end-to-end response of a whole
  fan-out, keyed by (concrete-indices tenant key, normalized request,
  participating-shard generation **vector**) — a duplicate fan-out
  skips shard dispatch entirely, and the moment ANY member shard's
  generation moves the vector no longer matches. Engages only when
  every target shard is locally present (the mesh co-location shape:
  the coordinator can read every member generation without an RPC).

Memory honesty: entries are charged to the ``request_cache`` breaker
child (indices/breaker.py) and bounded by ``search.request_cache.
max_bytes`` with LRU eviction — cold entries free memory BEFORE a trip,
and a breaker-starved cache refuses new entries (typed
``entries_refused``) while serving every query uncached-identical.
"""

from __future__ import annotations

import json
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

# the typed invalidation taxonomy: every dropped-because-stale entry
# counts under the engine-recorded cause of the generation move; an
# unrecognized cause maps to "unknown", which tests pin at zero (the
# search-telemetry fallback-taxonomy precedent)
INVALIDATION_CAUSES = ("refresh", "delete", "merge", "restore", "clear",
                       "disabled", "rollback", "unknown")


def _typed_cause(raw: Any) -> str:
    return raw if raw in INVALIDATION_CAUSES else "unknown"


def _release_resident(holder: Dict[str, int], breaker_name: str) -> None:
    """GC backstop (the DeviceCharge finalizer precedent): a cache that
    dies with its node (in-process test clusters) hands its whole
    resident charge back to the process-global breaker."""
    try:
        from elasticsearch_tpu.indices.breaker import BREAKERS
        BREAKERS.breaker(breaker_name).release(holder["bytes"])
        holder["bytes"] = 0
    except Exception:  # noqa: BLE001 — teardown must never raise
        pass


class _CacheTier:
    """Shared LRU + breaker accounting: an ordered entry map whose
    resident bytes are charged to the ``request_cache`` breaker child,
    evicted coldest-first against ``max_bytes``, and refused (typed)
    when even a fully-evicted cache cannot fit the budget."""

    BREAKER = "request_cache"

    def __init__(self) -> None:
        # key -> {"stamp": <validity stamp>, "row": <payload>,
        #         "bytes": int}
        self._entries: "OrderedDict[Any, Dict[str, Any]]" = OrderedDict()
        # one mutable holder shared with the GC finalizer so the charge
        # released at teardown is whatever is resident THEN
        self._resident = {"bytes": 0}
        weakref.finalize(self, _release_resident, self._resident,
                         self.BREAKER)
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "puts": 0, "evictions": 0,
            "entries_refused": 0, "oversize_refused": 0,
        }
        self.invalidations_by_cause: Dict[str, int] = {}
        # dynamic settings (search.request_cache.*), applied from
        # committed cluster state via configure_from_state
        self.enabled = True
        self.topk = False
        self.max_bytes = 32 << 20
        self.max_entry_bytes = 1 << 20
        self._cfg_version: Any = object()   # never equals a real version

    # -- config ---------------------------------------------------------

    def configure_from_state(self, state) -> None:
        """Version-memoized read of the ``search.request_cache.*``
        family (the search.plane.* application pattern): one attribute
        compare per request, a real parse only when the committed state
        changed."""
        version = getattr(state, "version", None)
        if version is not None and version == self._cfg_version:
            return
        self._cfg_version = version
        was_enabled = self.enabled
        self._apply_settings(state)
        if was_enabled and not self.enabled:
            self.clear(cause="disabled")
        elif self._resident["bytes"] > self.max_bytes:
            # a shrunk budget applies NOW, not at the next insert
            self._evict_until(self.max_bytes)

    def _apply_settings(self, state) -> None:
        from elasticsearch_tpu.utils.settings import (
            SEARCH_REQUEST_CACHE_ENABLED, SEARCH_REQUEST_CACHE_MAX_BYTES,
            SEARCH_REQUEST_CACHE_MAX_ENTRY_BYTES,
            SEARCH_REQUEST_CACHE_TOPK, setting_from_state,
        )
        self.enabled = setting_from_state(state,
                                          SEARCH_REQUEST_CACHE_ENABLED)
        self.topk = setting_from_state(state, SEARCH_REQUEST_CACHE_TOPK)
        self.max_bytes = setting_from_state(
            state, SEARCH_REQUEST_CACHE_MAX_BYTES)
        self.max_entry_bytes = setting_from_state(
            state, SEARCH_REQUEST_CACHE_MAX_ENTRY_BYTES)

    # which requests may never cache at THIS tier beyond the shared
    # rules: the coordinator tier refuses [timeout]-carrying bodies (a
    # budgeted fan-out's response is legitimately nondeterministic; the
    # shard tier is safe — a member either completes its row or errors,
    # and errors never fill)
    EXCLUDE_BUDGETED = False

    def covers(self, body: Dict[str, Any], window: int) -> bool:
        """THE cacheability predicate, shared by both tiers so coverage
        rules cannot drift between them: the tier must be enabled, the
        request must carry no per-request state a cached row cannot
        reproduce (profile trees, slices), and size>0 top-k shapes need
        the fleet-wide ``search.request_cache.topk`` gate or the
        request's own ``"request_cache": true`` opt-in (the reference's
        size>0 contract). ``"request_cache": false`` always opts out."""
        if not self.enabled:
            return False
        explicit = body.get("request_cache")
        if isinstance(explicit, str):
            # the reference's ?request_cache=false string form: a client
            # sending "false" asked for UNCACHED — bool("false") being
            # truthy must never read as an opt-in
            lowered = explicit.strip().lower()
            explicit = True if lowered in ("true", "1", "yes") else \
                False if lowered in ("false", "0", "no") else None
        if explicit is False:
            return False
        if body.get("slice") or body.get("profile"):
            return False
        if self.EXCLUDE_BUDGETED and body.get("timeout") is not None:
            return False
        if window <= 0:
            return True
        return bool(explicit) or self.topk

    # -- entry lifecycle ------------------------------------------------

    def _breaker(self):
        from elasticsearch_tpu.indices.breaker import BREAKERS
        return BREAKERS.breaker(self.BREAKER)

    def _drop(self, key: Any, counter: Optional[str] = None,
              cause: Optional[str] = None) -> None:
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self._resident["bytes"] -= entry["bytes"]
        self._breaker().release(entry["bytes"])
        if counter is not None:
            self.stats[counter] += 1
        if cause is not None:
            cause = _typed_cause(cause)
            self.invalidations_by_cause[cause] = \
                self.invalidations_by_cause.get(cause, 0) + 1
        self._on_drop(key)

    def _on_drop(self, key: Any) -> None:
        """Subclass hook: secondary indexes forget the key."""

    def _evict_until(self, budget: int) -> None:
        while self._entries and self._resident["bytes"] > budget:
            self._drop(next(iter(self._entries)), counter="evictions")

    def _estimate_bytes(self, row: Any) -> Optional[int]:
        """Host-memory estimate of one stored row (the serialized size —
        what the response costs to hold). Sizing coerces with str so an
        odd value can't fail the estimate; the STORED row is never
        round-tripped. None = unsizable: don't cache."""
        try:
            return len(json.dumps(row, default=str))
        except Exception:  # noqa: BLE001 — unsizable payloads stay out
            return None

    def _probe_is_stale(self, entry_stamp: Any, probe_stamp: Any) -> bool:
        """True when the PROBE carries the older stamp — the entry must
        survive such a mismatch (dropping it would let a straggler evict
        forward state). The base tier's probes always read CURRENT
        stamps, so a mismatch always means a stale entry."""
        return False

    def _get(self, key: Any, stamp: Any, cause: Any) -> Optional[Any]:
        entry = self._entries.get(key)
        if entry is None:
            self.stats["misses"] += 1
            return None
        if entry["stamp"] != stamp:
            if self._probe_is_stale(entry["stamp"], stamp):
                # a lagging observer (a drain whose reader pre-dates a
                # refresh) misses without touching the newer entry
                self.stats["misses"] += 1
                return None
            # the generation (vector) moved: typed invalidation, and the
            # probe is a miss
            self._drop(key, cause=cause() if callable(cause) else cause)
            self.stats["misses"] += 1
            return None
        self._entries.move_to_end(key)
        self.stats["hits"] += 1
        return entry["row"]

    def _put(self, key: Any, stamp: Any, row: Any) -> None:
        nbytes = self._estimate_bytes(row)
        if nbytes is None or nbytes > self.max_entry_bytes:
            self.stats["oversize_refused"] += 1
            return
        self._drop(key)   # supersede an existing (stale) entry in place
        # LRU eviction BEFORE the charge: cold entries free budget ahead
        # of any breaker trip
        self._evict_until(max(self.max_bytes - nbytes, 0))
        breaker = self._breaker()
        try:
            breaker.add_estimate(nbytes, self.BREAKER)
        except Exception:  # noqa: BLE001 — CircuitBreakingError: a
            # starved breaker means the CACHE gives way, never the query
            # — evict everything resident and retry once
            self._evict_until(0)
            try:
                breaker.add_estimate(nbytes, self.BREAKER)
            except Exception:  # noqa: BLE001
                self.stats["entries_refused"] += 1
                return
        self._entries[key] = {"stamp": stamp, "row": row, "bytes": nbytes}
        self._resident["bytes"] += nbytes
        self.stats["puts"] += 1

    def clear(self, cause: str = "clear") -> None:
        for key in list(self._entries):
            self._drop(key, cause=cause)

    # -- surfaces -------------------------------------------------------

    def snapshot(self, prefix: str = "") -> Dict[str, Any]:
        out: Dict[str, Any] = {
            f"{prefix}{name}": count for name, count in self.stats.items()}
        out[f"{prefix}entries"] = len(self._entries)
        out[f"{prefix}resident_bytes"] = self._resident["bytes"]
        out[f"{prefix}invalidations_by_cause"] = dict(
            sorted(self.invalidations_by_cause.items()))
        return out


class ShardRequestCache(_CacheTier):
    """Per-data-node tier: response rows keyed by (shard, generation,
    normalized plan). A per-shard key index makes a generation move an
    O(shard entries) purge the first time the new generation is
    observed, so stale entries stop holding breaker budget the moment
    the shard serves again."""

    def __init__(self) -> None:
        super().__init__()
        self._shard_keys: Dict[Tuple[str, int], set] = {}
        self._shard_gens: Dict[Tuple[str, int], int] = {}

    def _on_drop(self, key: Any) -> None:
        keys = self._shard_keys.get(key[0])
        if keys is not None:
            keys.discard(key)
            if not keys:
                self._shard_keys.pop(key[0], None)

    def _probe_is_stale(self, entry_stamp: Any, probe_stamp: Any) -> bool:
        # generation stamps are globally monotonic ints: a probe below
        # the entry's stamp is the lagging observer, not the entry
        return probe_stamp < entry_stamp

    def note_generation(self, shard_key: Tuple[str, int], generation: int,
                        cause: Any) -> bool:
        """First observation of a MOVED (strictly newer) generation
        purges the shard's entries under the engine-recorded cause.
        Returns False for a STALE observation — a drain whose reader
        pre-dates a refresh that other drains have already published
        past; purging (or regressing the recorded stamp) on its behalf
        would let one straggler wipe the hot set filled after the
        refresh."""
        recorded = self._shard_gens.get(shard_key)
        if recorded == generation:
            return True
        if recorded is not None and generation < recorded:
            return False
        self._shard_gens[shard_key] = generation
        if recorded is None:
            return True
        typed = cause() if callable(cause) else cause
        for key in list(self._shard_keys.get(shard_key, ())):
            self._drop(key, cause=typed)
        return True

    def get(self, shard_key: Tuple[str, int], generation: int,
            norm_key: str, cause: Any) -> Optional[Dict[str, Any]]:
        self.note_generation(shard_key, generation, cause)
        return self._get((shard_key, norm_key), generation, cause)

    def put(self, shard_key: Tuple[str, int], generation: int,
            norm_key: str, row: Dict[str, Any], cause: Any) -> None:
        if not self.enabled:
            return
        if not self.note_generation(shard_key, generation, cause):
            return   # a stale reader's row can never serve a future probe
        key = (shard_key, norm_key)
        self._put(key, generation, row)
        if key in self._entries:
            self._shard_keys.setdefault(shard_key, set()).add(key)


class FusedResultCache(_CacheTier):
    """Coordinator tier: the fused end-to-end response keyed by
    (tenant key, normalized request) and stamped with the
    participating-shard generation VECTOR — any member shard's
    generation moving unstamps the entry, and the invalidation is typed
    by the cause the MOVED shard's engine recorded."""

    EXCLUDE_BUDGETED = True

    def __init__(self) -> None:
        super().__init__()
        self.stats["not_colocated"] = 0

    def _apply_settings(self, state) -> None:
        super()._apply_settings(state)
        from elasticsearch_tpu.utils.settings import (
            SEARCH_REQUEST_CACHE_COORDINATOR, setting_from_state,
        )
        self.enabled = self.enabled and setting_from_state(
            state, SEARCH_REQUEST_CACHE_COORDINATOR)

    def get(self, key: Any, vector: Tuple,
            cause_of: Callable[[Tuple[str, int]], Any]
            ) -> Optional[Dict[str, Any]]:
        def stale_cause():
            entry = self._entries.get(key)
            if entry is None:
                return "unknown"
            for prev, cur in zip(entry["stamp"], vector):
                if prev != cur:
                    return _typed_cause(cause_of((cur[0], cur[1])))
            # length mismatch (shard count changed): a restore/resize
            # class event — attribute to the restore bucket
            return "restore"
        return self._get(key, vector, stale_cause)

    def put(self, key: Any, vector: Tuple, response: Dict[str, Any]
            ) -> None:
        if not self.enabled:
            return
        self._put(key, vector, response)


def merge_request_cache_sections(sections) -> Dict[str, Any]:
    """Fleet merge of per-node ``request_cache`` stats sections for
    ``_cluster/stats`` (the section-filtered nodes-stats fan-out):
    counters sum, the typed invalidation cause maps sum per cause."""
    out: Dict[str, Any] = {}
    for section in sections:
        for name, value in (section or {}).items():
            if isinstance(value, dict):
                agg = out.setdefault(name, {})
                for cause, n in value.items():
                    agg[cause] = agg.get(cause, 0) + int(n)
            elif isinstance(value, (int, float)):
                out[name] = out.get(name, 0) + int(value)
    for name, value in list(out.items()):
        if isinstance(value, dict):
            out[name] = dict(sorted(value.items()))
    return out
