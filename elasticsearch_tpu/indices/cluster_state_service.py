"""The reconciler: diff committed cluster state against local shards.

Reference analog: indices/cluster/IndicesClusterStateService.java:210
(applyClusterState) — on every committed state, each node creates shards
newly routed to it, removes shards routed away or deleted, starts peer
recoveries for initializing replicas, and reports shard-started /
shard-failed back to the master (ShardStateAction analog). Peer recovery
follows indices/recovery/RecoverySourceHandler.java:144's shape collapsed
to one round-trip: snapshot of live ops (phase1+phase2 merged — segments
here are already op-shaped), then mark-in-sync on the source.
"""

from __future__ import annotations

import json
import logging
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.cluster.routing import ShardRouting, ShardState
from elasticsearch_tpu.cluster.state import ClusterState
from elasticsearch_tpu.index.engine import RollbackInfeasibleError
from elasticsearch_tpu.index.seqno import peer_lease_id
from elasticsearch_tpu.indices.indices_service import IndicesService
from elasticsearch_tpu.transport.transport import TransportService
from elasticsearch_tpu.utils.errors import ShardCorruptedError

logger = logging.getLogger(__name__)

SHARD_STARTED = "cluster/shard_started"
SHARD_FAILED = "cluster/shard_failed"
RECOVERY_START = "indices/recovery/start"

# why an ops-based catch-up was refused and the copy paid the file path
# (typed; anything else lands in "unknown", which tests pin at zero)
FILE_FALLBACK_REASONS = (
    "stale_commit",             # local commit had seqno holes / no data
    "term_mismatch",            # commit written under a different primacy
    "beyond_global_checkpoint",  # local history includes unacked ops
    "lease_expired",            # no retention lease for the node anymore
    "lease_not_covering",       # lease exists but starts past lcp+1
    "history_pruned",           # lease held, but the history has a hole
    "rollback_infeasible",      # cross-term tail could not be unwound
)


def new_recovery_stats() -> Dict[str, Any]:
    return {
        "kinds": {},             # recovery_kind -> count
        "ops_replayed": 0,       # ops applied by ops-based catch-ups
        "bytes_copied": 0,       # wire payload actually shipped
        "bytes_avoided": 0,      # full-snapshot bytes NOT shipped
        "file_fallback_reasons": {"unknown": 0},
        # failover machinery: post-promotion primary->replica resyncs and
        # cross-term engine rollbacks (PrimaryReplicaSyncer analog)
        "resync": {"resyncs_started": 0, "resyncs_completed": 0,
                   "resyncs_noop": 0, "resync_failures": 0,
                   "resync_targets": 0, "resync_ops_sent": 0,
                   "resync_ops_applied": 0},
        "rollbacks": 0,
        "ops_rolled_back": 0,
    }


def merge_recovery_sections(sections: List[Dict[str, Any]]
                            ) -> Dict[str, Any]:
    """Fleet-wide merge of per-node "recovery" stats sections
    (_cluster/stats fan-out)."""
    out = new_recovery_stats()
    out.update(active_leases=0, leases_expired_total=0,
               history_retained_ops=0, leases_released_node_left=0)
    for sec in sections:
        if not isinstance(sec, dict):
            continue
        for kind, n in (sec.get("kinds") or {}).items():
            out["kinds"][kind] = out["kinds"].get(kind, 0) + int(n)
        for reason, n in (sec.get("file_fallback_reasons") or {}).items():
            out["file_fallback_reasons"][reason] = \
                out["file_fallback_reasons"].get(reason, 0) + int(n)
        for key, n in (sec.get("resync") or {}).items():
            out["resync"][key] = out["resync"].get(key, 0) + int(n)
        for key in ("ops_replayed", "bytes_copied", "bytes_avoided",
                    "active_leases", "leases_expired_total",
                    "history_retained_ops", "leases_released_node_left",
                    "rollbacks", "ops_rolled_back"):
            out[key] = out.get(key, 0) + int(sec.get(key, 0) or 0)
    return out


class IndicesClusterStateService:
    def __init__(self, node_id: str, indices_service: IndicesService,
                 transport_service: TransportService):
        self.node_id = node_id
        self.indices = indices_service
        self.ts = transport_service
        self.last_applied: Optional[ClusterState] = None
        # shards this node is currently recovering (avoid double-starting)
        self._recovering: set = set()
        # allocation ids with an in-flight shard-failed retry loop (the
        # re-assert-on-every-state path must not stack duplicate loops)
        self._failing: set = set()
        # every completed recovery on this node, by kind, plus a bounded
        # per-recovery log for _cat/recovery (RecoveryState analog)
        self.recovery_stats = new_recovery_stats()
        self._recovery_log: deque = deque(maxlen=128)
        self.ts.register_handler(RECOVERY_START, self._on_recovery_start)
        # post-promotion primary–replica resync (PrimaryReplicaSyncer):
        # late import — action/replication imports SHARD_FAILED from here
        from elasticsearch_tpu.action.replication import (
            PrimaryReplicaSyncer,
        )
        self.resyncer = PrimaryReplicaSyncer(
            node_id, indices_service, transport_service,
            lambda: self.last_applied)

    def _record_recovery(self, sr: ShardRouting, kind: str,
                         ops_replayed: int = 0, bytes_copied: int = 0,
                         bytes_avoided: int = 0,
                         file_reason: Optional[str] = None,
                         source_node: Optional[str] = None) -> None:
        stats = self.recovery_stats
        stats["kinds"][kind] = stats["kinds"].get(kind, 0) + 1
        stats["ops_replayed"] += ops_replayed
        stats["bytes_copied"] += bytes_copied
        stats["bytes_avoided"] += bytes_avoided
        if file_reason is not None:
            reason = file_reason if file_reason in FILE_FALLBACK_REASONS \
                else "unknown"
            stats["file_fallback_reasons"][reason] = \
                stats["file_fallback_reasons"].get(reason, 0) + 1
        self._recovery_log.append({
            "index": sr.index, "shard": sr.shard_id, "kind": kind,
            "primary": sr.primary, "node": self.node_id,
            "source_node": source_node, "ops_replayed": ops_replayed,
            "bytes_copied": bytes_copied, "bytes_avoided": bytes_avoided,
            "file_reason": file_reason})

    def recovery_log(self) -> List[Dict[str, Any]]:
        return list(self._recovery_log)

    # ------------------------------------------------------------------
    # apply
    # ------------------------------------------------------------------

    def apply_cluster_state(self, state: ClusterState) -> None:
        self.last_applied = state
        self._remove_stale_local_shards(state)
        self._update_index_metadata(state)
        self._create_or_recover_shards(state)
        self._release_departed_node_leases(state)

    def _release_departed_node_leases(self, state: ClusterState) -> None:
        """Early-expire `peer_recovery/<node>` leases for nodes that left
        the cluster AND whose copy was reallocated: once every copy of the
        group is active on live nodes, nothing is waiting for the departed
        disk to return, so pinning history for it only bloats retention.
        A lease for a node still in the cluster — or one whose group still
        has an unassigned/initializing copy that may come back to it —
        keeps aging out on the normal clock instead."""
        live = set(state.nodes)
        for shard in self.indices.all_shards():
            if not shard.primary or shard.tracker is None:
                continue
            try:
                irt = state.routing_table.index(shard.shard_id.index)
                group = irt.shard_group(shard.shard_id.shard)
            except Exception:  # noqa: BLE001 — routing gone; normal expiry
                continue
            if not all(r.active and r.node_id in live for r in group):
                continue   # a copy may still return to the departed node
            for lease in shard.tracker.leases():
                if not lease.id.startswith("peer_recovery/"):
                    continue
                node = lease.id.split("/", 1)[1]
                if node not in live:
                    shard.tracker.release_node_lease(node)

    def _remove_stale_local_shards(self, state: ClusterState) -> None:
        for index_name in list(self.indices.indices):
            if not state.metadata.has_index(index_name):
                # index deleted cluster-wide: drop data too
                self.indices.remove_index(index_name, delete_data=True)
                continue
            service = self.indices.indices[index_name]
            if not state.routing_table.has_index(index_name):
                continue
            irt = state.routing_table.index(index_name)
            for sid in list(service.shards):
                local = service.shards[sid]
                routed_here = [
                    sr for sr in irt.shard_group(sid)
                    if sr.node_id == self.node_id and
                    sr.allocation_id == local.allocation_id]
                if not routed_here:
                    service.remove_shard(sid)
                    self._recovering.discard((index_name, sid))

    def _update_index_metadata(self, state: ClusterState) -> None:
        # per-index isolation, like the reference reconciler: one index's
        # bad metadata must not abort the apply pass for every other index
        # (IndicesClusterStateService catches per-index and fails shards)
        for index_name, service in list(self.indices.indices.items()):
            if not state.metadata.has_index(index_name):
                continue
            meta = state.metadata.index(index_name)
            try:
                service.update_metadata(meta)
            except Exception as e:  # noqa: BLE001 — isolate the index
                # A node whose mapper diverged from committed metadata must
                # not keep serving the shards: fail this node's copies
                # LOUDLY and drop the poisoned IndexService entirely, so a
                # reassignment back to this node rebuilds a fresh
                # MapperService from the committed metadata instead of
                # silently reusing the diverged one.
                logger.error(
                    "[%s] failed to apply mapping update on [%s]: %s",
                    self.node_id, index_name, e)
                for sr in state.routing_table.shards_on_node(self.node_id):
                    if sr.index == index_name and \
                            sr.node_id == self.node_id and \
                            self.indices.has_shard(sr.index, sr.shard_id):
                        self._shard_failed(
                            sr, f"mapping update failed to apply: {e}")
                self.indices.remove_index(index_name, delete_data=False)

    def _create_or_recover_shards(self, state: ClusterState) -> None:
        for sr in state.routing_table.shards_on_node(self.node_id):
            if sr.node_id != self.node_id:
                continue   # relocation target handled via its own routing
            key = (sr.index, sr.shard_id)
            try:
                local_exists = self.indices.has_shard(sr.index, sr.shard_id)
                if sr.state == ShardState.INITIALIZING and not local_exists \
                        and key not in self._recovering:
                    self._recovering.add(key)
                    self._start_recovery(state, sr)
                elif sr.state == ShardState.STARTED and local_exists:
                    shard = self.indices.shard(sr.index, sr.shard_id)
                    term = state.metadata.index(sr.index).primary_term(
                        sr.shard_id)
                    if sr.primary and not shard.primary:
                        # replica promoted on failover: seed the tracker
                        # with every other ACTIVE copy so the global
                        # checkpoint stays pinned until resync acks prove
                        # where each one actually is, then re-replicate
                        # the above-checkpoint tail under the new term
                        irt = state.routing_table.index(sr.index)
                        in_sync = [
                            r.allocation_id
                            for r in irt.shard_group(sr.shard_id)
                            if r.active and r.allocation_id is not None]
                        shard.promote_to_primary(
                            term, in_sync_allocations=in_sync)
                        self.resyncer.resync(sr.index, sr.shard_id)
                elif sr.state == ShardState.STARTED and not local_exists:
                    # routing says this node serves the copy but it is
                    # gone locally — a tragic-event removal whose
                    # shard-failed report was lost mid-election, or an
                    # in-place process restart. A restarted PRIMARY whose
                    # disk still holds a committed store recovers IN
                    # PLACE: failing it would hand a possibly-sole copy
                    # to the balance-only allocator, which has no
                    # existing-copy awareness and could start an empty
                    # primary on another node (green-but-empty data
                    # loss). A corruption-marked store refuses to reopen
                    # inside recover_from_store and falls through to the
                    # failure report. Everything else re-asserts
                    # shard-failed on EVERY state application until the
                    # master reroutes — a lost report must not leave
                    # routing diverged forever.
                    if sr.primary and key not in self._recovering and \
                            sr.allocation_id not in self._failing and \
                            self.indices.has_on_disk_data(
                                state.metadata.index(sr.index),
                                sr.shard_id):
                        self._recovering.add(key)
                        self._recover_started_primary_in_place(state, sr)
                    else:
                        self._shard_failed(
                            sr, "shard copy missing locally "
                                "(failed or restarted; re-reporting)")
            except Exception as e:  # noqa: BLE001 — fail just this shard
                self._shard_failed(sr, f"shard apply failed: {e}")

    # ------------------------------------------------------------------
    # recovery
    # ------------------------------------------------------------------

    def _start_recovery(self, state: ClusterState, sr: ShardRouting,
                        allow_reuse: bool = True,
                        forced_file_reason: Optional[str] = None) -> None:
        metadata = state.metadata.index(sr.index)
        service = self.indices.create_index(metadata)
        term = metadata.primary_term(sr.shard_id)

        if sr.primary:
            # primary: recover from the local store (gateway allocation path)
            had_data = self.indices.has_on_disk_data(metadata, sr.shard_id)
            shard = service.create_shard(sr.shard_id, primary=True,
                                         primary_term=term,
                                         allocation_id=sr.allocation_id)
            try:
                if shard.engine.store is not None:
                    shard.engine.recover_from_store()
                    shard.rebind_tracker()
            except Exception as e:  # noqa: BLE001 — reported to master
                # drop the half-opened copy so a later reassignment to
                # this node starts clean instead of colliding with it
                service.remove_shard(sr.shard_id)
                self._shard_failed(sr, f"store recovery failed: {e}")
                return
            shard.recovery_kind = "existing_store" if had_data \
                else "empty_store"
            self._record_recovery(sr, shard.recovery_kind)
            self._watch_engine(service, shard, sr)
            self._shard_started(sr)
            return

        # replica: peer recovery from the active primary's node
        irt = state.routing_table.index(sr.index)
        primary = irt.primary(sr.shard_id)
        if not primary.active or primary.node_id is None:
            self._recovering.discard((sr.index, sr.shard_id))
            return   # retried on a later state where the primary is active

        # local-reuse probe (ReplicaShardAllocator's file-reuse analog,
        # collapsed to the safe ops-shaped gate): a fresh, non-corrupted
        # local commit with no seqno holes is reopened NOW — the shard
        # must exist before the recovery round-trip, or live replication
        # fan-out (which already targets this INITIALIZING copy) would
        # hit a missing shard for a full RTT — and the primary then
        # confirms whether the reopened history may be kept (the source
        # decides; a refusal wipes and pays the full copy)
        local_commit = None
        shard = None
        if allow_reuse:
            local = self.indices.local_shard_state(metadata.uuid,
                                                   sr.shard_id)
            if local and local.get("has_data") and local.get("verified") \
                    and not local.get("corrupted") and \
                    local.get("max_seqno", -1) >= 0:
                try:
                    shard = service.create_shard(
                        sr.shard_id, primary=False, primary_term=term,
                        allocation_id=sr.allocation_id, fresh_store=False)
                    shard.engine.recover_from_store()
                    tracker = shard.engine.tracker
                    if tracker.checkpoint != tracker.max_seqno:
                        # seqno holes survived commit + translog replay:
                        # the local history is not contiguous — the ops
                        # path can't extend it, so don't offer it
                        raise ValueError(
                            "recovered local copy has seqno holes")
                    # report the RECOVERED engine's watermarks (commit
                    # plus replayed translog tail) and let the SOURCE
                    # decide: identical → reuse as-is; behind but lease-
                    # covered → ops-based catch-up from checkpoint+1;
                    # anything else → wipe and file-copy. Acked ops in
                    # the replayed tail are exactly what ops-based
                    # catch-up preserves; UNacked ones are fenced by the
                    # source's global-checkpoint and term gates, which
                    # force the wipe instead of resurrecting them.
                    # the copy's own persisted global checkpoint rides
                    # along: cross-term commits whose history fits at or
                    # under it are still reconcilable by rollback+replay
                    pgcp = int((shard.engine.recovered_commit_extra or {})
                               .get("global_checkpoint", -1))
                    shard.update_global_checkpoint_on_replica(pgcp)
                    local_commit = {
                        "max_seqno": tracker.max_seqno,
                        "local_checkpoint": tracker.checkpoint,
                        "primary_term": local.get("primary_term", -1),
                        "global_checkpoint": pgcp}
                except Exception as e:  # noqa: BLE001 — fall back fresh
                    logger.warning(
                        "[%s] local reuse probe of [%s][%s] failed (%s); "
                        "using full peer recovery",
                        self.node_id, sr.index, sr.shard_id, e)
                    service.remove_shard(sr.shard_id)
                    shard = None
        if shard is None:
            # fresh_store: this copy is rebuilt from the primary's ops,
            # so any leftover on-disk state (incl. corruption markers
            # from a failed previous copy on this node) is wiped first
            shard = service.create_shard(
                sr.shard_id, primary=False, primary_term=term,
                allocation_id=sr.allocation_id, fresh_store=True)

        def on_response(resp: Optional[Dict[str, Any]],
                        err: Optional[Exception]) -> None:
            nonlocal shard
            if err is not None or resp is None:
                service.remove_shard(sr.shard_id)
                self._recovering.discard((sr.index, sr.shard_id))
                self._shard_failed(sr, f"peer recovery failed: {err}")
                return
            mode = resp.get("mode") or \
                ("reuse" if resp.get("reuse") else "file")
            if local_commit is None:
                mode = "file"   # nothing local to reuse or catch up
            reuse = mode == "reuse"
            ops_based = mode == "ops"
            try:
                if mode == "file" and local_commit is not None:
                    # the source refused the reopened history (typed
                    # reason in the response): wipe it and copy in full
                    service.remove_shard(sr.shard_id)
                    shard = service.create_shard(
                        sr.shard_id, primary=False, primary_term=term,
                        allocation_id=sr.allocation_id, fresh_store=True)
                if ops_based and resp.get("rollback_to") is not None:
                    # cross-term reconciliation: the source vouched only
                    # for history at/under rollback_to — unwind this
                    # copy's possibly-divergent tail first, then the
                    # replay below extends pure canonical history
                    try:
                        shard.engine.rollback_above(
                            int(resp["rollback_to"]))
                    except RollbackInfeasibleError as e:
                        # typed refusal: the tail cannot be PROVEN
                        # unwindable (history pruned past it and the
                        # segment copy merged away) — wipe and pay the
                        # full copy rather than serve a maybe-divergent
                        # doc, keeping "unknown" pinned at zero
                        logger.warning(
                            "[%s] cross-term rollback of [%s][%s] "
                            "infeasible (%s); wiping for full copy",
                            self.node_id, sr.index, sr.shard_id, e)
                        service.remove_shard(sr.shard_id)
                        self._start_recovery(
                            self.last_applied or state, sr,
                            allow_reuse=False,
                            forced_file_reason="rollback_infeasible")
                        return
                for op in resp["ops"]:
                    # historical ops keep their original terms; the fence
                    # term is the recovery source's CURRENT primary term.
                    # In ops mode this replays ONLY the missed tail —
                    # including delete tombstones and noops — on top of
                    # the reopened store: no wipe, no segment copy.
                    shard.apply_op_on_replica(
                        op, req_primary_term=resp.get("primary_term"))
                # fill seqno holes (overwritten/deleted history not shipped)
                for seqno in range(shard.engine.tracker.checkpoint + 1,
                                   resp["max_seqno"] + 1):
                    shard.engine.noop(seqno, reason="recovery hole fill")
                shard.update_global_checkpoint_on_replica(
                    resp["global_checkpoint"])
                shard.learn_retention_leases(resp.get("retention_leases"))
                shard.engine.refresh()
            except Exception as e:  # noqa: BLE001 — reported to master
                service.remove_shard(sr.shard_id)
                self._recovering.discard((sr.index, sr.shard_id))
                self._shard_failed(sr, f"recovery apply failed: {e}")
                return
            shard.recovery_kind = "peer_reuse" if reuse else (
                "ops_based" if ops_based else "peer")
            self._record_recovery(
                sr, shard.recovery_kind,
                ops_replayed=len(resp["ops"]) if ops_based else 0,
                bytes_copied=int(resp.get("bytes_copied", 0) or 0),
                bytes_avoided=int(resp.get("bytes_avoided", 0) or 0),
                # a typed reason is only meaningful when a local copy
                # EXISTED and was refused — a fresh copy isn't a
                # fallback, EXCEPT when this recovery is itself the wipe
                # restart of a refused rollback (the forced reason)
                file_reason=(resp.get("file_reason") or "unknown")
                if mode == "file" and local_commit is not None
                else (forced_file_reason if mode == "file" else None),
                source_node=resp.get("source_node"))
            self._watch_engine(service, shard, sr)
            self._shard_started(sr)

        # the start request retries with jittered-exponential backoff
        # through transient source-side failures (primary node briefly
        # unreachable / partitioned) before the copy is failed to the
        # master — RecoveryTarget's RetryableAction-driven retryRecovery
        def attempt(cb) -> None:
            from elasticsearch_tpu.transport.transport import (
                NodeNotConnectedError,
            )
            state_now = self.last_applied
            source = primary.node_id
            if state_now is not None:
                try:
                    sr_now = state_now.routing_table.index(
                        sr.index).primary(sr.shard_id)
                    if sr_now.active and sr_now.node_id is not None:
                        source = sr_now.node_id   # primary moved: follow it
                except Exception:  # noqa: BLE001 — keep the last source
                    pass
            if source is None:
                cb(None, NodeNotConnectedError(
                    f"no active primary for [{sr.index}][{sr.shard_id}]"))
                return
            request = {"index": sr.index, "shard": sr.shard_id,
                       "allocation_id": sr.allocation_id}
            if local_commit is not None:
                request["local_commit"] = local_commit
            self.ts.send_request(source, RECOVERY_START, request, cb,
                                 timeout=60.0)

        from elasticsearch_tpu.utils.retry import (
            RetryableAction, transient_cluster_error,
        )

        def retryable(err) -> bool:
            # the start request is idempotent on the source (snapshot +
            # mark-in-sync), so lost requests AND lost replies both retry
            return transient_cluster_error(err, retry_timeouts=True)

        RetryableAction(
            self.ts.transport.scheduler, attempt, on_response,
            initial_delay=0.5, max_delay=10.0, timeout=120.0,
            is_retryable=retryable).run()

    def _recover_started_primary_in_place(self, state: ClusterState,
                                          sr: ShardRouting) -> None:
        """Re-open a STARTED-routed primary from this node's own store
        after a process restart. No routing change is needed (the master
        already routes the copy here); success just restores service,
        failure (incl. a corruption marker) reports shard-failed like any
        other store-recovery failure."""
        metadata = state.metadata.index(sr.index)
        service = self.indices.create_index(metadata)
        term = metadata.primary_term(sr.shard_id)
        shard = service.create_shard(sr.shard_id, primary=True,
                                     primary_term=term,
                                     allocation_id=sr.allocation_id)
        try:
            if shard.engine.store is not None:
                shard.engine.recover_from_store()
                shard.rebind_tracker()
        except Exception as e:  # noqa: BLE001 — reported to master
            service.remove_shard(sr.shard_id)
            self._shard_failed(sr, f"in-place store recovery failed: {e}")
            return
        shard.recovery_kind = "in_place"
        self._record_recovery(sr, "in_place")
        self._watch_engine(service, shard, sr)
        self._recovering.discard((sr.index, sr.shard_id))
        # the master may be verifying this STARTED copy (gateway
        # reconcile after our reboot): a started report is the fast-path
        # proof the copy is live again — the verify poll is the fallback
        self._shard_started(sr)

    def _watch_engine(self, service, shard, sr: ShardRouting) -> None:
        """Turn a later tragic engine event (corruption, EIO at flush)
        into a routing event: drop the local copy and report shard-failed
        so the master promotes a clean replica and re-replicates."""
        def on_engine_failure(reason: str, exc: Exception,
                              sr=sr, service=service) -> None:
            try:
                service.remove_shard(sr.shard_id)
            except Exception:  # noqa: BLE001 — removal is best-effort
                logger.exception("failed to remove failed shard %s", sr)
            self._shard_failed(
                sr, f"engine failed, reason [{reason}]: {exc}")
        shard.add_failure_listener(on_engine_failure)

    def _on_recovery_start(self, req: Dict[str, Any], sender: str
                           ) -> Dict[str, Any]:
        """Primary side: snapshot live ops + register the recovering copy.

        Runs atomically within one handler dispatch, so the snapshot and
        in-sync registration can't interleave with a concurrent write; ops
        after this point reach the new copy through normal replica fan-out
        (the retention-lease ops-based path of RecoverySourceHandler)."""
        shard = self.indices.shard(req["index"], req["shard"])
        assert shard.primary and shard.tracker is not None
        # a corruption-marked (or failed) store must never be a recovery
        # source: replicas built from it would replicate the damage
        if shard.engine.failed:
            raise ShardCorruptedError(
                f"recovery source [{req['index']}][{req['shard']}] has a "
                f"failed engine: {shard.engine.failure_reason}")
        if shard.engine.store is not None:
            shard.engine.store.ensure_not_corrupted()
        ops, max_seqno = shard.engine.snapshot_ops()
        # mode decision (RecoverySourceHandler's shape): the target's
        # recovered local copy may be kept as-is ("reuse"), caught up by
        # replaying only its missed ops ("ops"), or must be wiped and
        # copied in full ("file", with a typed reason). Shared safety
        # gates for keeping ANY local history: hole-free (checkpoint ==
        # max), inside the global checkpoint (ops <= it are identical on
        # every in-sync copy, so no divergent or missing-delete history
        # can hide in the reused files), AND written under this
        # primary's CURRENT term: equal seqno watermarks across
        # different terms can name different ops (a dead primary's
        # unreplicated write vs its successor's), and only the term
        # identifies whose history the commit holds.
        mode = "file"
        file_reason: Optional[str] = None
        send_ops = ops
        rollback_to: Optional[int] = None
        local_commit = req.get("local_commit") or None

        def ops_if_covered(replay_from: int,
                           check_covering: bool = True) -> None:
            # ops-based catch-up: only when this NODE's retention lease
            # still covers everything the target must replay AND the
            # soft-delete history actually has it (the lease is the
            # promise; the history is the proof). A rollback-directed
            # catch-up skips the covering check: a deposed primary's own
            # lease retains from ITS high checkpoint, above the bound it
            # is told to roll back to — there the history completeness
            # check below is the entire (and sufficient) proof.
            nonlocal mode, file_reason, send_ops
            shard.tracker.expire_leases()
            lease = shard.tracker.get_lease(peer_lease_id(sender))
            if lease is None:
                file_reason = "lease_expired"
            elif check_covering and lease.retaining_seqno > replay_from:
                file_reason = "lease_not_covering"
            else:
                hist_ops, complete = \
                    shard.engine.ops_history_snapshot(replay_from)
                if not complete:
                    file_reason = "history_pruned"
                else:
                    mode = "ops"
                    send_ops = hist_ops

        if local_commit is not None:
            lcp = int(local_commit.get("local_checkpoint", -1))
            lmax = int(local_commit.get("max_seqno", -1))
            lterm = int(local_commit.get("primary_term", -1))
            if not (lcp == lmax >= 0):
                file_reason = "stale_commit"
            elif lterm == shard.primary_term:
                # same-primacy commit: the original three-way decision
                if lmax > shard.global_checkpoint:
                    file_reason = "beyond_global_checkpoint"
                elif lmax == max_seqno:
                    mode = "reuse"
                    send_ops = []
                else:
                    ops_if_covered(lmax + 1)
            else:
                # CROSS-TERM commit. The target's own persisted global
                # checkpoint bounds its canonical prefix: every op it
                # holds at/under that gcp was in-sync-everywhere when it
                # learned the value, so no primacy since can have
                # rewritten those seqnos. Ops ABOVE it may be a deposed
                # primary's unacked tail — reconcilable by directing the
                # target to roll back to the bound and replaying forward
                # from retained history. Only a commit with NO persisted
                # gcp is genuinely unverifiable cross-term.
                pgcp = int(local_commit.get("global_checkpoint", -1))
                # defensive floor: never trust a persisted gcp past what
                # this primary itself knows to be globally acked
                canon = min(pgcp, shard.global_checkpoint)
                if pgcp < 0:
                    file_reason = "term_mismatch"
                elif lmax <= canon:
                    # fully canonical cross-term history: as good as a
                    # same-term commit — reuse or plain ops catch-up
                    if lmax == max_seqno:
                        mode = "reuse"
                        send_ops = []
                    else:
                        ops_if_covered(lmax + 1)
                else:
                    # divergent-possible tail above the canonical bound:
                    # rollback+replay from there, lease permitting
                    rollback_to = min(lmax, canon)
                    ops_if_covered(rollback_to + 1, check_covering=False)
                    if mode != "ops":
                        rollback_to = None
        # payload accounting: what actually ships vs the full snapshot
        # the file path would have shipped (the cost ops-based avoids)
        bytes_full = len(json.dumps(ops))
        bytes_sent = bytes_full if mode == "file" \
            else len(json.dumps(send_ops))
        # the new copy gets a NODE-keyed retention lease immediately
        # (createMissingPeerRecoveryRetentionLeases analog), renewed from
        # here on by its checkpoint advances riding replication acks —
        # so its NEXT restart within the retention window is ops-based
        if mode == "reuse":
            retaining = lmax + 1
        elif mode == "ops":
            # with a rollback directive the copy's guaranteed floor is
            # the rollback bound, not its (about-to-be-unwound) lmax
            retaining = (rollback_to if rollback_to is not None
                         else lmax) + 1
        else:
            retaining = max_seqno + 1
        shard.tracker.init_tracking(
            req["allocation_id"], lease_id=peer_lease_id(sender),
            retaining_seqno=retaining)
        shard.tracker.mark_in_sync(req["allocation_id"], max_seqno)
        return {"mode": mode, "ops": send_ops, "max_seqno": max_seqno,
                "reuse": mode == "reuse",
                "rollback_to": rollback_to,
                "file_reason": file_reason,
                "bytes_copied": bytes_sent,
                "bytes_avoided": max(0, bytes_full - bytes_sent),
                "source_node": self.node_id,
                "global_checkpoint": shard.global_checkpoint,
                "primary_term": shard.primary_term,
                "retention_leases": [
                    lease.to_dict() for lease in shard.tracker.leases()]}

    # ------------------------------------------------------------------
    # master notifications
    # ------------------------------------------------------------------

    def _master_id(self) -> Optional[str]:
        state = self.last_applied
        return state.master_node_id if state is not None else None

    def _shard_started(self, sr: ShardRouting) -> None:
        self._recovering.discard((sr.index, sr.shard_id))
        master = self._master_id()
        if master is None:
            return
        self.ts.send_request(master, SHARD_STARTED,
                             {"shard": sr.to_dict()},
                             lambda r, e: None, timeout=30.0)

    def _shard_failed(self, sr: ShardRouting, reason: str) -> None:
        """Report a failed copy to the master. Reliable: retried with
        jittered backoff through no-master windows and dropped messages
        (SHARD_FAILED is idempotent on the master — apply_failed_shard
        matches by allocation_id, so a duplicate is a no-op), because a
        lost report would leave the master routing a STARTED shard this
        node no longer has (ShardStateAction's own retry discipline)."""
        self._recovering.discard((sr.index, sr.shard_id))
        if sr.allocation_id is not None:
            if sr.allocation_id in self._failing:
                return   # a retry loop for this copy is already running
            self._failing.add(sr.allocation_id)

        def attempt(cb) -> None:
            master = self._master_id()
            if master is None:
                from elasticsearch_tpu.utils.errors import NotMasterError
                cb(None, NotMasterError("no master known to report "
                                        "shard failure to"))
                return
            self.ts.send_request(master, SHARD_FAILED,
                                 {"shard": sr.to_dict(), "reason": reason},
                                 cb, timeout=30.0)

        def retryable(err) -> bool:
            from elasticsearch_tpu.utils.retry import (
                transient_cluster_error,
            )
            # timeouts ARE retryable here: the report is idempotent
            return transient_cluster_error(err, retry_timeouts=True)

        def finished(_r, _e) -> None:
            self._failing.discard(sr.allocation_id)

        from elasticsearch_tpu.utils.retry import RetryableAction
        RetryableAction(
            self.ts.transport.scheduler, attempt, finished,
            initial_delay=0.5, max_delay=10.0, timeout=120.0,
            is_retryable=retryable).run()
