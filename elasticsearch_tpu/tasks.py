"""Task management: every long-running operation is a listable,
cancellable task.

Reference analogs: tasks/TaskManager.java:76 (per-node registry, parent →
child chains across nodes), CancellableTask.java:30 (cooperative
cancellation flag checked inside hot loops), the _tasks list/cancel APIs.
Cancellation here is cooperative too: task code calls
``ensure_not_cancelled()`` at loop boundaries (the search phase checks it
between segments, reindex between batches).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.utils.errors import (
    ResourceNotFoundError, SearchEngineError, TaskCancelledError,
)

LIST_TASKS = "cluster:monitor/tasks/lists"
CANCEL_TASKS = "cluster:admin/tasks/cancel"
GET_TASK = "cluster:monitor/task/get"


class Task:
    def __init__(self, task_id: str, action: str, description: str,
                 cancellable: bool, parent_task_id: Optional[str],
                 start_time_ms: float):
        self.task_id = task_id
        self.action = action
        self.description = description
        self.cancellable = cancellable
        self.parent_task_id = parent_task_id
        self.start_time_ms = start_time_ms
        self._cancelled = threading.Event()
        self.cancel_reason: Optional[str] = None
        self.status: Optional[Dict[str, Any]] = None   # progress payload

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self, reason: str = "by user request") -> None:
        self.cancel_reason = reason
        self._cancelled.set()

    def ensure_not_cancelled(self) -> None:
        if self.cancelled:
            raise TaskCancelledError(
                f"task [{self.task_id}] was cancelled: "
                f"{self.cancel_reason}")

    def to_dict(self) -> Dict[str, Any]:
        node, _, num = self.task_id.partition(":")
        out = {"node": node, "id": int(num) if num.isdigit() else num,
               "action": self.action, "description": self.description,
               "start_time_in_millis": int(self.start_time_ms),
               "cancellable": self.cancellable,
               "cancelled": self.cancelled}
        if self.parent_task_id:
            out["parent_task_id"] = self.parent_task_id
        if self.status is not None:
            out["status"] = self.status
        return out


class TaskManager:
    """Per-node task registry (TaskManager.java:76)."""

    def __init__(self, node_id: str,
                 now_ms: Optional[Callable[[], float]] = None):
        self.node_id = node_id
        self._seq = itertools.count(1)
        self._tasks: Dict[str, Task] = {}
        self._lock = threading.Lock()
        import time
        self._now_ms = now_ms or (lambda: time.time() * 1000)

    def now_ms(self) -> float:
        """This registry's clock (scheduler time on a node) — hot-spans
        elapsed times must read the SAME clock start_time_ms uses."""
        return self._now_ms()

    def register(self, action: str, description: str = "",
                 cancellable: bool = False,
                 parent_task_id: Optional[str] = None) -> Task:
        task_id = f"{self.node_id}:{next(self._seq)}"
        task = Task(task_id, action, description, cancellable,
                    parent_task_id, self._now_ms())
        with self._lock:
            self._tasks[task_id] = task
        return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.task_id, None)

    def get(self, task_id: str) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list(self, actions: Optional[str] = None) -> List[Task]:
        import fnmatch
        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            patterns = [a.strip() for a in actions.split(",")]
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatch(t.action, p)
                            for p in patterns)]
        return tasks

    def cancel(self, task_id: str, reason: str = "by user request"
               ) -> Task:
        task = self.get(task_id)
        if task is None:
            raise ResourceNotFoundError(
                f"task [{task_id}] is not found")
        if not task.cancellable:
            raise SearchEngineError(
                f"task [{task_id}] is not cancellable")
        task.cancel(reason)
        # cancel local children too (ban propagation, simplified to the
        # local registry; cross-node children carry parent_task_id and
        # are cancelled by the broadcast in TaskActions)
        for t in self.list():
            if t.parent_task_id == task_id and t.cancellable:
                t.cancel(reason)
        return task


class TaskActions:
    """Cluster-wide list/cancel: fan out to every node's registry."""

    def __init__(self, node):
        self.node = node
        ts = node.transport_service
        ts.register_handler(LIST_TASKS, self._on_list)
        ts.register_handler(CANCEL_TASKS, self._on_cancel)
        ts.register_handler(GET_TASK, self._on_get)

    def _on_get(self, req: Dict[str, Any], sender: str) -> Dict[str, Any]:
        task_id = req["task_id"]
        task = self.node.task_manager.get(task_id)
        if task is not None:
            return {"completed": False, "task": task.to_dict()}
        result = self.node.task_results.get(task_id)
        if result is not None:
            node, _, num = task_id.partition(":")
            return {"completed": True,
                    "task": {"node": node,
                             "id": int(num) if num.isdigit() else num},
                    "response": result}
        raise ResourceNotFoundError(f"task [{task_id}] is not found")

    def _on_list(self, req: Dict[str, Any], sender: str) -> Dict[str, Any]:
        return {"tasks": [t.to_dict() for t in
                          self.node.task_manager.list(
                              req.get("actions"))]}

    def _on_cancel(self, req: Dict[str, Any], sender: str
                   ) -> Dict[str, Any]:
        tm = self.node.task_manager
        cancelled = []
        not_cancellable = []
        if req.get("task_id"):
            tid = req["task_id"]
            task = tm.get(tid)
            if task is not None:
                if task.cancellable:
                    cancelled.append(tm.cancel(tid).to_dict())
                else:
                    not_cancellable.append(tid)
            # the task's children may run on THIS node while the parent
            # lives on the coordinator (cross-node ban propagation)
            for t in tm.list():
                if t.parent_task_id == tid and t.cancellable \
                        and not t.cancelled:
                    t.cancel()
                    cancelled.append(t.to_dict())
        else:
            for t in tm.list(req.get("actions")):
                if t.cancellable and not t.cancelled:
                    t.cancel()
                    cancelled.append(t.to_dict())
        return {"tasks": cancelled, "not_cancellable": not_cancellable}

    # -- coordinating side ----------------------------------------------

    def _fan_out(self, action: str, req: Dict[str, Any],
                 on_done: Callable[[Dict[str, Any]], None],
                 raw_sink: Optional[Dict[str, Any]] = None) -> None:
        state = self.node._applied_state()
        node_ids = list(state.nodes) or [self.node.node_id]
        results: Dict[str, Any] = {}
        pending = {"n": len(node_ids)}

        def one(nid: str) -> None:
            def cb(resp, err):
                if err is None and resp is not None:
                    results[nid] = resp["tasks"]
                    if raw_sink is not None:
                        raw_sink[nid] = resp
                pending["n"] -= 1
                if pending["n"] == 0:
                    on_done(results)
            self.node.transport_service.send_request(nid, action, req, cb,
                                                     timeout=30.0)
        for nid in node_ids:
            one(nid)

    def list_tasks(self, on_done, actions: Optional[str] = None) -> None:
        def done(results: Dict[str, Any]) -> None:
            nodes_out = {}
            for nid, tasks in results.items():
                if tasks:
                    nodes_out[nid] = {"tasks": {
                        f"{t['node']}:{t['id']}": t for t in tasks}}
            on_done({"nodes": nodes_out}, None)
        self._fan_out(LIST_TASKS, {"actions": actions}, done)

    def cancel_tasks(self, on_done, task_id: Optional[str] = None,
                     actions: Optional[str] = None) -> None:
        req = {"task_id": task_id, "actions": actions}
        raw: Dict[str, Any] = {}

        def done(results: Dict[str, Any]) -> None:
            per_node = {nid: tasks for nid, tasks in results.items()}
            all_cancelled = [t for tasks in per_node.values()
                             for t in tasks]
            not_cancellable = [tid for resp in raw.values()
                               for tid in resp.get("not_cancellable", [])]
            if task_id and not all_cancelled:
                if not_cancellable:
                    on_done(None, SearchEngineError(
                        f"task [{task_id}] is not cancellable"))
                    return
                on_done(None, ResourceNotFoundError(
                    f"task [{task_id}] is not found"))
                return
            on_done({"nodes": {
                nid: {"tasks": {f"{t['node']}:{t['id']}": t
                                for t in tasks}}
                for nid, tasks in per_node.items() if tasks}}, None)
        self._fan_out(CANCEL_TASKS, req, done, raw_sink=raw)

    def get_task(self, task_id: str, on_done) -> None:
        """Resolve a task on whichever node owns it (id prefix)."""
        owner, _, _ = task_id.partition(":")
        state = self.node._applied_state()
        if owner == self.node.node_id or owner not in state.nodes:
            try:
                on_done(self._on_get({"task_id": task_id},
                                     self.node.node_id), None)
            except SearchEngineError as e:
                on_done(None, e)
            return
        self.node.transport_service.send_request(
            owner, GET_TASK, {"task_id": task_id},
            lambda resp, err: on_done(resp, err), timeout=30.0)
