"""Node monitor probes: os / process / fs / device.

Reference: monitor/os/OsProbe.java, monitor/process/ProcessProbe.java,
monitor/fs/FsProbe.java — memory, load, file descriptors, data-path disk
usage — plus the accelerator dimension this build adds: device (HBM)
memory from the JAX backend, the resource that actually bounds search
working sets here.

Bootstrap checks (bootstrap/BootstrapChecks.java analog): run at node
start; failures log loudly and, when ``ESTPU_ENFORCE_BOOTSTRAP`` is
truthy (the production-mode analog), abort startup. The JVM-centric
checks (heap size, G1 settings) are moot in Python; the meaningful ones
here are descriptor limits, a writable data path, and a sane device/HBM
state.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


def _read_proc(path: str) -> Optional[str]:
    try:
        with open(path, encoding="ascii") as fh:
            return fh.read()
    except OSError:
        return None


def os_stats() -> Dict[str, Any]:
    """Memory + load from /proc (OsProbe.java's cgroup-less core)."""
    out: Dict[str, Any] = {"cpu": {"count": os.cpu_count() or 1}}
    meminfo = _read_proc("/proc/meminfo")
    if meminfo:
        fields = {}
        for line in meminfo.splitlines():
            name, _, rest = line.partition(":")
            parts = rest.split()
            if parts:
                fields[name] = int(parts[0]) * 1024   # kB -> bytes
        total = fields.get("MemTotal", 0)
        available = fields.get("MemAvailable", fields.get("MemFree", 0))
        out["mem"] = {
            "total_in_bytes": total,
            "free_in_bytes": available,
            "used_in_bytes": max(total - available, 0),
            "used_percent": round(100.0 * (total - available)
                                  / total, 1) if total else 0.0,
        }
    loadavg = _read_proc("/proc/loadavg")
    if loadavg:
        one, five, fifteen = loadavg.split()[:3]
        out["cpu"]["load_average"] = {"1m": float(one), "5m": float(five),
                                      "15m": float(fifteen)}
    return out


def process_stats() -> Dict[str, Any]:
    """Open FDs + RSS + cpu time for THIS process (ProcessProbe)."""
    out: Dict[str, Any] = {"id": os.getpid()}
    try:
        out["open_file_descriptors"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        out["open_file_descriptors"] = -1
    try:
        import resource
        soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        out["max_file_descriptors"] = soft
        usage = resource.getrusage(resource.RUSAGE_SELF)
        out["mem"] = {"resident_in_bytes": usage.ru_maxrss * 1024}
        out["cpu"] = {"total_in_millis": int(
            (usage.ru_utime + usage.ru_stime) * 1000)}
    except (ImportError, ValueError):
        pass
    return out


def fs_stats(data_path: Optional[str]) -> Dict[str, Any]:
    """Disk totals for the data path (FsProbe)."""
    path = data_path or "."
    try:
        st = os.statvfs(path)
    except OSError:
        return {"total": {}}
    total = st.f_frsize * st.f_blocks
    free = st.f_frsize * st.f_bavail
    return {"total": {
        "path": os.path.abspath(path),
        "total_in_bytes": total,
        "free_in_bytes": free,
        "available_in_bytes": free,
    }}


def device_stats() -> Dict[str, Any]:
    """Accelerator memory per device, when a backend is live — the HBM
    counterpart of the reference's heap stats. Never initializes a
    backend itself (stats observe, they must not pay first-init)."""
    out: Dict[str, Any] = {"devices": []}
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return out
    # jax.devices() triggers FIRST-init of every registered platform
    # when none is up yet — and the environment's TPU-tunnel plugin
    # forces itself first in jax_platforms and can block indefinitely
    # while claiming hardware (r3/r4 bench probes hung exactly here).
    # Stats observe; they must never pay (or hang on) first-init.
    try:
        from jax._src import xla_bridge as _xb
        ready = _xb.backends_are_initialized()
    except Exception:  # noqa: BLE001 — the PRIVATE api moved/renamed:
        # fall through to jax.devices() ONLY when the configured platform
        # set cannot hang on first-init (cpu-only) — on TPU-tunnel hosts
        # the never-pay-first-init invariant above outranks reporting
        platforms = str(getattr(jax.config, "jax_platforms", None)
                        or os.environ.get("JAX_PLATFORMS", "") or "")
        names = [p.strip() for p in platforms.split(",") if p.strip()]
        ready = bool(names) and all(p == "cpu" for p in names)
    if not ready:
        return out
    try:
        devices = jax.devices()
    except Exception:  # noqa: BLE001 — backend init failure: no devices
        return out
    for dev in devices:
        entry: Dict[str, Any] = {
            "id": getattr(dev, "id", -1),
            "platform": getattr(dev, "platform", "unknown"),
        }
        stats = getattr(dev, "memory_stats", None)
        if callable(stats):
            try:
                mem = stats() or {}
                entry["bytes_in_use"] = int(mem.get("bytes_in_use", 0))
                limit = int(mem.get("bytes_limit", 0))
                if limit:
                    entry["bytes_limit"] = limit
            except Exception:  # noqa: BLE001 — cpu devices often lack it
                pass
        out["devices"].append(entry)
    return out


def device_plane_stats() -> Dict[str, Any]:
    """Packed multi-segment plane observability (ops/device_segment.py
    PlaneRegistry): full rebuilds vs incremental appends, evictions,
    resident bytes per kind (the ``columns`` doc-values plane
    included), the quantized coarse tier's configured and SERVED
    re-rank depths (rerank_depth / rerank_depth_max /
    rerank_depth_histogram, with quantized_queries, rerank_escalations,
    quantized_exact_fallbacks and the measured-latency engage rule's
    quantized_disengaged_slow), the drain-wide aggregation counters
    (plane_aggs_queries = specs served from a device partial,
    plane_aggs_fallbacks), and how often a missing/refused plane forced
    the per-segment fallback. Never initializes the device layer
    itself — a node that has served no device work reports an empty
    section."""
    import sys
    mod = sys.modules.get("elasticsearch_tpu.ops.device_segment")
    if mod is None:
        return {}
    return mod.PLANES.stats_snapshot()


def mesh_plane_stats(mesh_executor=None) -> Dict[str, Any]:
    """Mesh-sharded plane observability (ops/device_segment.py
    MeshPlaneRegistry + search/mesh_executor.py): builds vs incremental
    appends, evictions, miss fallbacks, resident bytes (total and per
    device), plus the fan-out executor's served/fallback/dispatch
    counters. On a multi-host mesh (search.mesh.hosts) the section also
    carries the configured topology (``hosts``: n_hosts /
    devices_per_host / spec) and ``per_host`` serving counters — per
    host label, shard results scored off that host's copies and typed
    mesh_host_lost losses — so _nodes/stats shows WHERE the one-program
    fan-out's work actually lands as the mesh grows past one node.
    Never initializes the device layer itself."""
    import sys
    mod = sys.modules.get("elasticsearch_tpu.ops.device_segment")
    if mod is None:
        return {}
    out = mod.MESH_PLANES.stats_snapshot()
    if mesh_executor is not None:
        out.update(mesh_executor.stats)
        per_host = getattr(mesh_executor, "per_host_stats", None)
        if per_host:
            out["per_host"] = {h: dict(c) for h, c in per_host.items()}
    return out


def search_batch_stats(batcher, rrf_fuser=None) -> Dict[str, Any]:
    """Micro-batcher observability (search/batch_executor.py): dispatch /
    occupancy / wait-time counters plus the derived means operators watch
    to see whether cross-query batching is actually engaging. The raw
    counters are cumulative since node start, like every other stat.

    Also derives the per-drain-memo hit rate (what fraction of dispatched
    queries were answered by a batch-mate's rows) and, when this node
    coordinates hybrid searches, merges the RRF fusion batcher's
    counters (rrf_fuse_batches / requests / max occupancy /
    fallbacks)."""
    if batcher is None:
        return {}
    out: Dict[str, Any] = dict(batcher.stats)
    dispatches = out.get("batches_dispatched", 0)
    queries = out.get("queries_dispatched", 0)
    out["mean_occupancy"] = round(queries / dispatches, 3) \
        if dispatches else 0.0
    out["mean_wait_ms"] = round(out.get("wait_ms_total", 0.0) / queries, 3) \
        if queries else 0.0
    out["memo_hit_rate"] = round(
        out.get("memo_hits", 0) / queries, 4) if queries else 0.0
    if rrf_fuser is not None:
        out.update(rrf_fuser.stats)
        fuses = out.get("rrf_fuse_batches", 0)
        out["mean_rrf_fuse_occupancy"] = round(
            out.get("rrf_fuse_requests", 0) / fuses, 3) if fuses else 0.0
    return out


def search_admission_stats(thread_pool, response_collector=None,
                           batcher=None,
                           ars_stats=None,
                           failover_stats=None) -> Dict[str, Any]:
    """Overload-control observability (utils/threadpool.py +
    action/response_collector.py + the shard batcher's pressure
    tracker): the search pool's live queue bounds and adaptive-resize
    state, rejections by tenant key, the Retry-After values issued, the
    node's own self-reported pressure, the shard-side shed point
    (``shard_queue``: configured + effective member bounds, occupancy,
    shard_busy sheds, the drain-rate estimate behind Retry-After) and
    the coordinator's busy-failover counters
    (``shard_busy_failover``: sheds seen, copy failovers, backed-off
    retry rounds, all-copies-shed surfaces), and the C3 rank inputs per
    node — everything an operator needs to explain WHY a request was
    shed, rerouted, or a replica skipped, from the stats surface
    alone."""
    if thread_pool is None:
        return {}
    pool = thread_pool.pools.get("search")
    if pool is None:
        return {}
    out: Dict[str, Any] = pool.admission_stats()
    if batcher is not None:
        out["node_pressure"] = batcher.node_pressure.snapshot(
            batcher.queue_depth())
        out["shard_queue"] = batcher.shard_queue_stats()
    if failover_stats is not None:
        out["shard_busy_failover"] = dict(failover_stats)
    # the caller may pass the already-built rank-input map (node stats
    # serves it under adaptive_selection too — compute once per call)
    if ars_stats is None and response_collector is not None:
        ars_stats = response_collector.stats()
    if ars_stats is not None:
        out["ars"] = ars_stats
    return out


def indexing_pressure_stats(thread_pool, shard_bulk=None) -> Dict[str, Any]:
    """Write-path pressure-plane observability (utils/threadpool.py
    IndexingPressure + action/replication.py): per-stage in-flight /
    lifetime byte accounting under the coordinating / primary / replica
    split, the per-stage rejection buckets (with the pinned-zero
    ``unknown`` bucket — every rejection must be attributable to a
    stage), the measured release rate behind the computed Retry-After
    values, and the primary's replica-pressure retry counters
    (rejections seen, batches that converged on retry, copies failed
    after the retry budget) — so a shed write, a slow ack, or a dropped
    replica is explainable from the stats surface alone."""
    if thread_pool is None:
        return {}
    ip = getattr(thread_pool, "indexing_pressure", None)
    if ip is None:
        return {}
    out: Dict[str, Any] = ip.stats()
    if shard_bulk is not None:
        out["replica_retries"] = dict(
            getattr(shard_bulk, "write_pressure_stats", {}) or {})
    return out


def request_cache_stats(search_transport, search_action=None
                        ) -> Dict[str, Any]:
    """Two-tier request-cache observability (indices/request_cache.py):
    the shard tier's hits / misses / evictions / typed
    invalidations_by_cause / resident bytes plus the batcher's
    intake-hit and pressure-observation counters, and the coordinator
    fused-result tier's figures under ``coordinator_*`` — so the
    duplicate-traffic win (and every entry the breaker refused) is
    explainable from the stats surface alone."""
    if search_transport is None:
        return {}
    out: Dict[str, Any] = search_transport.request_cache.snapshot()
    batcher = getattr(search_transport, "batcher", None)
    if batcher is not None:
        out["intake_hits"] = batcher.stats.get(
            "request_cache_intake_hits", 0)
        out["cached_pressure_observations"] = \
            batcher.node_pressure.cached_served
    if search_action is not None and \
            getattr(search_action, "fused_cache", None) is not None:
        out.update(search_action.fused_cache.snapshot(
            prefix="coordinator_"))
    return out


def search_latency_stats() -> Dict[str, Any]:
    """Search telemetry plane observability (search/telemetry.py
    TELEMETRY): ring-buffer latency histograms (p50/p95/p99 + span-level
    breakdown) per (query class x data plane), device-dispatch counts,
    and the complete fallback-reason taxonomy — every mesh -> RPC,
    plane -> per-segment, and batch -> solo event under a typed reason.
    Never imports the search package before it has served (a node that
    has run no searches reports an empty section)."""
    import sys
    mod = sys.modules.get("elasticsearch_tpu.search.telemetry")
    if mod is None:
        return {}
    return mod.TELEMETRY.snapshot()


def device_profile_stats() -> Dict[str, Any]:
    """Device observatory observability (search/device_profile.py
    DEVICE_PROFILE + the plane registries' residency timelines): per
    kernel-family compile counts vs cache hits, compile wall-time,
    live shape-bucket cardinality, the recompile-storm counter, the
    measured execute-time EWMA per (family, shape bucket) and guarded
    FLOPs/bytes estimates — plus WHERE the plane HBM went (bytes by
    generation age, high-water marks) and WHY it left (eviction
    causes). Never initializes the device layer itself — a node that
    has dispatched no kernels reports an empty section."""
    import sys
    out: Dict[str, Any] = {}
    dp = sys.modules.get("elasticsearch_tpu.search.device_profile")
    if dp is not None:
        out = dp.DEVICE_PROFILE.snapshot()
    seg = sys.modules.get("elasticsearch_tpu.ops.device_segment")
    if seg is not None:
        out["plane_residency"] = seg.PLANES.residency_snapshot()
        out["mesh_plane_residency"] = \
            seg.MESH_PLANES.residency_snapshot()
    return out


def hot_spans_report(node, limit: int = 16) -> Dict[str, Any]:
    """GET /_nodes/hot_spans — the reference hot-threads analog over the
    data planes: sample every in-flight search task (the serving paths
    maintain phase / data plane / drain occupancy on the task status)
    and render the longest-running first, plus the shard batcher's
    queued members per batch key and the node's own pressure snapshot.
    Pure observation: nothing here touches a queue or a task."""
    spans: List[Dict[str, Any]] = []
    tm = getattr(node, "task_manager", None)
    if tm is not None:
        now_ms = tm.now_ms()
        for task in tm.list():
            if not str(task.action).startswith("indices:data/read/search"):
                continue
            status = task.status or {}
            entry: Dict[str, Any] = {
                "task": task.task_id,
                "action": task.action,
                "description": task.description,
                "phase": status.get("phase", "running"),
                "elapsed_ms": round(
                    max(now_ms - task.start_time_ms, 0.0), 3),
            }
            if status.get("data_plane") is not None:
                entry["data_plane"] = status["data_plane"]
            if status.get("occupancy") is not None:
                entry["occupancy"] = status["occupancy"]
            spans.append(entry)
    spans.sort(key=lambda s: (-s["elapsed_ms"], s["task"]))
    out: Dict[str, Any] = {
        "in_flight_total": len(spans),
        "spans": spans[: max(int(limit), 1)],
    }
    batcher = getattr(getattr(node, "search_transport", None),
                      "batcher", None)
    if batcher is not None:
        # batch keys are (index, shard, kind, ...bucketing components) —
        # never request payloads — but the rendering is still truncated
        # so no future key component can balloon a monitoring response;
        # colliding truncations SUM rather than shadow each other
        queued: Dict[str, int] = {}
        for key, queue in batcher._queues.items():
            if queue:
                label = "/".join(str(part) for part in key)[:128]
                queued[label] = queued.get(label, 0) + len(queue)
        out["queued_members"] = queued
        out["node_pressure"] = batcher.node_pressure.snapshot(
            batcher.queue_depth())
    return out


def recovery_stats(reconciler, indices_service=None) -> Dict[str, Any]:
    """Recovery & retention observability (indices/cluster_state_service
    + index/seqno): recoveries by kind (ops_based / peer_reuse / peer /
    in_place / existing_store / empty_store), ops replayed by catch-ups,
    bytes copied vs avoided, the typed file-fallback reason taxonomy,
    plus live lease counts and soft-delete history size across this
    node's primaries — the whole "did that restart pay a wipe?" question
    answerable from _nodes/stats alone."""
    if reconciler is None:
        return {}
    out: Dict[str, Any] = {
        "kinds": dict(reconciler.recovery_stats["kinds"]),
        "ops_replayed": reconciler.recovery_stats["ops_replayed"],
        "bytes_copied": reconciler.recovery_stats["bytes_copied"],
        "bytes_avoided": reconciler.recovery_stats["bytes_avoided"],
        "file_fallback_reasons": dict(
            reconciler.recovery_stats["file_fallback_reasons"]),
        "active_leases": 0, "leases_expired_total": 0,
        "leases_released_node_left": 0,
        "history_retained_ops": 0,
        # failover machinery: post-promotion resyncs this node ran as a
        # new primary, and cross-term rollbacks its engines performed
        "resync": dict(resyncer.stats) if (
            resyncer := getattr(reconciler, "resyncer", None)) is not None
        else {},
        "rollbacks": 0, "ops_rolled_back": 0,
        "translog_ops_trimmed": 0,
    }
    if indices_service is not None:
        for shard in list(indices_service.all_shards()):
            try:
                out["history_retained_ops"] += \
                    shard.engine.history_stats()["retained_ops"]
                out["rollbacks"] += shard.engine.rollbacks_total
                out["ops_rolled_back"] += shard.engine.ops_rolled_back_total
                if shard.engine.translog is not None:
                    out["translog_ops_trimmed"] += \
                        shard.engine.translog.ops_trimmed_below_total + \
                        shard.engine.translog.ops_trimmed_above_total
                if shard.tracker is not None:
                    lease_stats = shard.tracker.lease_stats()
                    out["active_leases"] += lease_stats["active"]
                    out["leases_expired_total"] += \
                        lease_stats["expired_total"]
                    out["leases_released_node_left"] += \
                        lease_stats["released_node_left"]
            except Exception:  # noqa: BLE001 — a closing shard is fine
                continue
    return out


def gateway_stats(gateway_allocator) -> Dict[str, Any]:
    """Gateway shard-state fetch observability (gateway.py
    GatewayAllocator): how many fetches the master issued, how often the
    cache answered, what the nodes reported (no copy / corruption-marked
    / stale), plus reconcile failures and cancelled recoveries — so every
    allocation decision the gateway makes is visible in _nodes/stats."""
    if gateway_allocator is None:
        return {}
    # the allocator owns the race-safe snapshot (stats can be read from
    # a REST thread while the dispatch thread mutates the fetch state)
    return gateway_allocator.stats_snapshot()


# ---------------------------------------------------------------------------
# bootstrap checks
# ---------------------------------------------------------------------------

MIN_FDS = 1024
# boot-time HBM occupancy above this fraction means another process (or a
# leak) already owns the accelerator the node is about to serve from
MAX_BOOT_HBM_FRACTION = 0.5


def bootstrap_checks(data_path: Optional[str]) -> List[str]:
    """Failure messages (empty = healthy). BootstrapChecks analog with a
    device-HBM gate replacing the JVM heap checks."""
    failures: List[str] = []
    proc = process_stats()
    max_fds = proc.get("max_file_descriptors", -1)
    if 0 < max_fds < MIN_FDS:
        failures.append(
            f"max file descriptors [{max_fds}] is too low; raise the "
            f"limit to at least [{MIN_FDS}]")
    if data_path is not None:
        probe = os.path.join(data_path, ".bootstrap_probe")
        try:
            os.makedirs(data_path, exist_ok=True)
            with open(probe, "w", encoding="ascii") as fh:
                fh.write("ok")
            os.remove(probe)
        except OSError as e:
            failures.append(f"data path [{data_path}] is not writable: {e}")
        else:
            fs = fs_stats(data_path).get("total", {})
            if fs.get("available_in_bytes", 1) == 0:
                failures.append(
                    f"data path [{data_path}] has no free space")
    for dev in device_stats().get("devices", []):
        limit = dev.get("bytes_limit")
        in_use = dev.get("bytes_in_use")
        if limit and in_use is not None and \
                in_use > limit * MAX_BOOT_HBM_FRACTION:
            failures.append(
                f"device [{dev.get('id')}] ({dev.get('platform')}) "
                f"already has {in_use} of {limit} HBM bytes in use at "
                f"boot — another process owns the accelerator")
    return failures


def run_bootstrap_checks(data_path: Optional[str]) -> None:
    """Log failures; raise when ESTPU_ENFORCE_BOOTSTRAP is truthy (the
    reference enforces in production mode, warns in dev mode)."""
    failures = bootstrap_checks(data_path)
    if not failures:
        return
    for failure in failures:
        logger.warning("bootstrap check failure: %s", failure)
    if str(os.environ.get("ESTPU_ENFORCE_BOOTSTRAP", "")).lower() in (
            "1", "true", "yes"):
        raise RuntimeError(
            "bootstrap checks failed: " + "; ".join(failures))
