"""Node composition: wire every service together and manage lifecycle.

Reference analog: node/Node.java:273 (constructor builds ~60 services) and
Node.start():708 (ordered startup: indices → transport → discovery/
coordination → API). The NodeClient mirrors client/node/NodeClient.java:43 —
the typed in-process facade the REST layer calls.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.action.admin import (
    BroadcastActions, CLUSTER_HEALTH_ACTION, CLUSTER_UPDATE_SETTINGS,
    CREATE_INDEX, DELETE_INDEX,
    FLUSH_SHARD, FORCEMERGE_SHARD, MasterActions, MasterClient,
    NODE_STATS_ACTION, PUT_MAPPING,
    REFRESH_SHARD, STATS_SHARD, UPDATE_ALIASES, UPDATE_SETTINGS,
    cluster_health,
)
from elasticsearch_tpu.action.bulk import TransportBulkAction
from elasticsearch_tpu.action.document import (
    TransportGetAction, TransportUpdateAction,
)
from elasticsearch_tpu.action.replication import TransportShardBulkAction
from elasticsearch_tpu.action.search_action import (
    SearchTransportService, TransportSearchAction,
)
from elasticsearch_tpu.cluster.allocation import AllocationService
from elasticsearch_tpu.cluster.coordination import (
    Coordinator, CoordinatorSettings, Mode,
)
from elasticsearch_tpu.cluster.metadata import resolve_index_expression
from elasticsearch_tpu.cluster.state import ClusterState, DiscoveryNode, Roles
from elasticsearch_tpu.indices.cluster_state_service import (
    IndicesClusterStateService,
)
from elasticsearch_tpu.indices.indices_service import IndicesService
from elasticsearch_tpu.transport.scheduler import Scheduler
from elasticsearch_tpu.transport.transport import (
    InMemoryTransport, TransportService,
)
from elasticsearch_tpu.utils.errors import (
    IllegalArgumentError, SearchEngineError,
)

logger = logging.getLogger(__name__)


class Node:
    def __init__(self, node_id: str, transport: InMemoryTransport,
                 scheduler: Scheduler,
                 seed_peers: Optional[List[str]] = None,
                 roles: Optional[List[str]] = None,
                 data_path: Optional[str] = None,
                 initial_state: Optional[ClusterState] = None,
                 coordinator_settings: Optional[CoordinatorSettings] = None,
                 mesh_data_plane: bool = False,
                 transport_service=None,
                 disk_io=None):
        self.node_id = node_id
        self.scheduler = scheduler
        import uuid as _uuid
        self.discovery_node = DiscoveryNode(
            node_id=node_id, name=node_id,
            roles=frozenset(roles) if roles else frozenset(Roles.ALL),
            ephemeral_id=_uuid.uuid4().hex)

        # the wire is pluggable: in-memory (simulation / single process) or
        # an injected TcpTransportService (transport/tcp.py) for clusters
        # spanning OS processes — both honor the same service contract
        self.transport_service = transport_service or \
            TransportService(node_id, transport)
        self.indices_service = IndicesService(data_path=data_path,
                                              disk_io=disk_io,
                                              node_id=node_id)
        self.allocation_service = AllocationService()

        # gateway allocation (gateway.py GatewayAllocator): every node
        # answers the _list_gateway_started_shards fetch from its local
        # stores; the elected master uses the results to put restarted
        # shards back on the nodes that actually hold their data
        from elasticsearch_tpu.gateway import GatewayAllocator
        self.gateway_allocator = GatewayAllocator(
            node_id, self.transport_service, self.indices_service,
            self._applied_state)
        self.allocation_service.gateway_allocator = self.gateway_allocator

        initial_state = initial_state or ClusterState()
        persisted_state = None
        if data_path is not None:
            # gateway: boot from the durably persisted term + accepted
            # state (GatewayMetaState analog); shards themselves recover
            # from their local stores when the reconciler applies state
            from elasticsearch_tpu.gateway import GatewayMetaState
            # data_path is already per-node (callers namespace it)
            persisted_state = GatewayMetaState(data_path).load_or_create(
                initial_state)
        self.coordinator = Coordinator(
            self.discovery_node, self.transport_service, scheduler,
            initial_state, settings=coordinator_settings,
            seed_peers=seed_peers, on_committed=self._on_committed,
            persisted_state=persisted_state)
        self.gateway_allocator.bind(self.coordinator,
                                    self.allocation_service)

        self.reconciler = IndicesClusterStateService(
            node_id, self.indices_service, self.transport_service)
        self.master_actions = MasterActions(
            self.coordinator, self.allocation_service, self.transport_service)
        self.master_client = MasterClient(self.transport_service,
                                          self.coordinator)

        from elasticsearch_tpu.ingest import IngestService
        self.ingest_service = IngestService(self._applied_state, node=self)

        from elasticsearch_tpu.tasks import TaskManager
        self.task_manager = TaskManager(
            node_id, now_ms=lambda: scheduler.now() * 1000)
        self.task_results: Dict[str, Any] = {}

        from elasticsearch_tpu.utils.threadpool import ThreadPoolService
        # scheduler-clocked: the Little's-law frame measurement (and the
        # Retry-After rates derived from it) then work identically under
        # the deterministic virtual-time harness and production
        self.thread_pool = ThreadPoolService(now_fn=scheduler.now)

        self.shard_bulk = TransportShardBulkAction(
            node_id, self.indices_service, self.transport_service, scheduler,
            self._applied_state, thread_pool=self.thread_pool,
            # lazy: both services are constructed below, after this action
            node_pressure=lambda: self.search_transport.batcher.node_pressure,
            response_collector=lambda: self.search_action.response_collector)
        self.bulk_action = TransportBulkAction(
            self.shard_bulk, self._applied_state, self._auto_create_index,
            ingest_service=self.ingest_service,
            thread_pool=self.thread_pool)
        self.get_action = TransportGetAction(
            node_id, self.indices_service, self.transport_service,
            self._applied_state)
        self.update_action = TransportUpdateAction(self.get_action,
                                                   self.bulk_action)
        self.search_transport = SearchTransportService(
            node_id, self.indices_service, self.transport_service,
            task_manager=self.task_manager,
            state_supplier=self._applied_state)
        self.mesh_plane = None
        if mesh_data_plane:
            # SPMD data plane over the local device mesh (SURVEY §5.8's
            # two-plane split): eligible whole-index searches run as one
            # pjit program, RPC scatter-gather stays the fallback
            from elasticsearch_tpu.parallel.mesh_plane import MeshDataPlane
            self.mesh_plane = MeshDataPlane()
            # explicit mesh opt-in: bring the backend up at BOOT so the
            # first search finds the mesh ready instead of silently
            # serving the RPC fallback until other compute initializes it
            self.mesh_plane.warmup()
        # search.mesh.warmup_at_boot: pay device first-init NOW (the
        # mesh-sharded plane's mesh_ready() refuses to inside a search,
        # so the first eligible search per process otherwise takes the
        # RPC detour). Checked once more when the setting appears in a
        # later committed state (dynamic settings land after boot).
        self._mesh_warmed = False
        self._maybe_mesh_warmup(self._applied_state())
        from elasticsearch_tpu.transport.remote import RemoteClusterService
        self.remote_clusters = RemoteClusterService(self)
        self.search_action = TransportSearchAction(
            node_id, self.transport_service, self._applied_state,
            task_manager=self.task_manager, indices=self.indices_service,
            mesh_plane=self.mesh_plane, thread_pool=self.thread_pool,
            remote_clusters=self.remote_clusters,
            search_transport=self.search_transport)
        self.broadcast_actions = BroadcastActions(
            node_id, self.indices_service, self.transport_service,
            self._applied_state)

        from elasticsearch_tpu.action.snapshot import (
            SnapshotActions, SnapshotShardActions,
        )
        self.snapshot_shard_actions = SnapshotShardActions(
            self.indices_service, self.transport_service)
        self.snapshot_actions = SnapshotActions(self)

        from elasticsearch_tpu.tasks import TaskActions
        self.task_actions = TaskActions(self)

        from elasticsearch_tpu.action.reindex import ReindexActions
        self.reindex_actions = ReindexActions(self)

        from elasticsearch_tpu.action.misc import MiscReadActions
        self.misc_actions = MiscReadActions(self)

        from elasticsearch_tpu.rankeval import RankEvalAction
        self.rank_eval_action = RankEvalAction(self)

        self.client = NodeClient(self)

        from elasticsearch_tpu.ilm import IndexLifecycleService
        self.ilm_service = IndexLifecycleService(self)
        from elasticsearch_tpu.xpack.slm import SnapshotLifecycleService
        self.slm_service = SnapshotLifecycleService(self)
        from elasticsearch_tpu.persistent import PersistentTasksService
        self.persistent_tasks = PersistentTasksService(self)

        from elasticsearch_tpu.xpack.security import SecurityService
        self.security = SecurityService(self)
        from elasticsearch_tpu.utils.resource_watcher import (
            ResourceWatcherService,
        )
        self.resource_watcher = ResourceWatcherService(self.scheduler)
        if self.security.file_realm.path:
            self.resource_watcher.watch(self.security.file_realm.path,
                                        self.security.file_realm.reload)

        from elasticsearch_tpu.xpack.async_search import AsyncSearchService
        self.async_search = AsyncSearchService(self)

        from elasticsearch_tpu.xpack.sql import SqlService
        self.sql = SqlService(self)

        from elasticsearch_tpu.xpack.transform import TransformService
        self.transform_service = TransformService(self)

        from elasticsearch_tpu.xpack.watcher import WatcherService
        self.watcher_service = WatcherService(self)

        from elasticsearch_tpu.xpack.ccr import CcrService, CcrShardActions
        self.ccr_shard_actions = CcrShardActions(self)
        self.ccr_service = CcrService(self)

        from elasticsearch_tpu.xpack.eql import EqlService
        self.eql = EqlService(self)

        from elasticsearch_tpu.xpack.rollup import RollupService
        self.rollup_service = RollupService(self)

        from elasticsearch_tpu.xpack.enrich import EnrichService
        self.enrich_service = EnrichService(self)

        from elasticsearch_tpu.xpack.graph import GraphService
        self.graph_service = GraphService(self)

        from elasticsearch_tpu.xpack.monitoring import MonitoringService
        self.monitoring_service = MonitoringService(self)

        from elasticsearch_tpu.xpack.searchable_snapshots import (
            SearchableSnapshotsService,
        )
        self.searchable_snapshots = SearchableSnapshotsService(self)

        from elasticsearch_tpu.xpack.ml_jobs import MlJobService
        self.ml_jobs = MlJobService(self)

        from elasticsearch_tpu.xpack.autoscaling import AutoscalingService
        self.autoscaling = AutoscalingService(self)

        from elasticsearch_tpu.action.resize import ResizeActions
        self.resize_actions = ResizeActions(self)

        # per-node stats endpoint (TransportNodesStatsAction node-level
        # handler): the coordinating node fans `_nodes/stats` out here
        self.transport_service.register_handler(
            NODE_STATS_ACTION,
            lambda req, sender: self.local_node_stats(
                sections=(req or {}).get("sections")))
        # master-routed health (TransportClusterHealthAction analog): the
        # unverified-STARTED gate is master-only state, so every node
        # answers health FROM the master's view, not its own
        self.transport_service.register_handler(
            CLUSTER_HEALTH_ACTION, self._on_cluster_health)

    def _on_cluster_health(self, req: Dict[str, Any],
                           sender: str) -> Dict[str, Any]:
        """Answer ONLY while actually the elected master: a node a caller
        still believes is master (stale applied state mid-election) must
        error — the caller then takes its flagged local-view fallback —
        rather than return a stale view dressed up as authoritative."""
        if self.coordinator.mode != Mode.LEADER:
            raise RuntimeError(
                f"[{self.node_id}] is not the elected master")
        state = self._applied_state()
        unverified = self.gateway_allocator.health_unverified()
        if req.get("indices") is not None:
            # bulk form (_cat/indices): every requested index's health in
            # ONE master round trip instead of one RPC per index
            return {"indices": {
                name: cluster_health(state, name, unverified=unverified)
                for name in req["indices"]
                if state.metadata.has_index(name)}}
        return cluster_health(state, req.get("index"),
                              unverified=unverified)

    # ------------------------------------------------------------------

    def _applied_state(self) -> ClusterState:
        return self.coordinator.applied_state

    def local_node_stats(self, sections=None) -> Dict[str, Any]:
        """All stats sections, or — when ``sections`` names some — only
        those, built lazily: a caller merging one section across the
        fleet (``_cluster/stats``'s search_latency view) must not make
        every node walk /proc, the device backend and every shard."""
        from elasticsearch_tpu.indices.breaker import BREAKERS
        from elasticsearch_tpu import monitor

        # the C3 rank inputs serve two sections (adaptive_selection and
        # search_admission.ars) — build them at most once per call
        ars_cache: Dict[str, Any] = {}

        def ars_stats():
            if "v" not in ars_cache:
                ars_cache["v"] = \
                    self.search_action.response_collector.stats()
            return ars_cache["v"]

        builders = {
            "indices": lambda: self.indices_service.stats(),
            "transport": lambda: dict(self.transport_service.stats),
            "breakers": BREAKERS.stats,
            "thread_pool": self.thread_pool.stats,
            "adaptive_selection": ars_stats,
            # overload control plane: adaptive queue bounds, per-tenant
            # rejections, Retry-After values, node pressure + ARS rank
            # inputs (utils/threadpool.py + response_collector.py)
            "search_admission": lambda: monitor.search_admission_stats(
                self.thread_pool,
                batcher=self.search_transport.batcher,
                ars_stats=ars_stats(),
                failover_stats=self.search_action.shard_busy_stats),
            # write-path pressure plane: three-stage in-flight byte
            # accounting, per-stage rejection buckets, Retry-After rates
            # + the primary's replica-retry counters (threadpool.py
            # IndexingPressure + action/replication.py)
            "indexing_pressure": lambda: monitor.indexing_pressure_stats(
                self.thread_pool, shard_bulk=self.shard_bulk),
            # real probes (OsProbe/ProcessProbe/FsProbe analogs + the
            # device/HBM dimension the reference lacks)
            "os": monitor.os_stats,
            "process": monitor.process_stats,
            "fs": lambda: monitor.fs_stats(self.indices_service.data_path),
            "device": monitor.device_stats,
            # packed multi-segment plane residency/rebuild/eviction
            # counters (ops/device_segment.py PlaneRegistry)
            "device_plane": monitor.device_plane_stats,
            # mesh-sharded plane residency + SPMD fan-out executor
            # counters (MeshPlaneRegistry + search/mesh_executor.py)
            "mesh_plane": lambda: monitor.mesh_plane_stats(
                self.search_transport.mesh_executor),
            # cross-query micro-batching occupancy/wait/dispatch/memo/
            # window-controller counters + coordinator RRF fusion batching
            "search_batch": lambda: monitor.search_batch_stats(
                self.search_transport.batcher,
                rrf_fuser=self.search_action.rrf_fuser),
            # two-tier request cache: shard-tier hits/misses/evictions +
            # typed invalidation causes + the coordinator fused-result
            # tier (indices/request_cache.py)
            "request_cache": lambda: monitor.request_cache_stats(
                self.search_transport, self.search_action),
            # per-(query class x data plane) latency histograms + the
            # typed fallback-reason taxonomy (search/telemetry.py)
            "search_latency": monitor.search_latency_stats,
            # device observatory: per-family compile/recompile counters,
            # execute EWMAs, FLOPs estimates + plane-HBM residency
            # timelines (search/device_profile.py + the plane registries)
            "device_profile": monitor.device_profile_stats,
            # gateway shard-state fetch counters (fetches issued, cache
            # hits, copies reported none/corrupted/stale, reconciles)
            "gateway": lambda: monitor.gateway_stats(
                self.gateway_allocator),
            # recovery kinds (ops_based vs wipe-and-copy), replayed-op /
            # byte accounting, typed file-fallback reasons + lease and
            # soft-delete history gauges (cluster_state_service.py)
            "recovery": lambda: monitor.recovery_stats(
                self.reconciler, self.indices_service),
        }
        want = None if sections is None else set(sections)
        out: Dict[str, Any] = {"name": self.node_id}
        for name, build in builders.items():
            if want is None or name in want:
                out[name] = build()
        return out

    def _on_committed(self, state: ClusterState) -> None:
        # appliers are isolated from each other: a reconciler failure (e.g. a
        # shard that can't initialize) must not skip master housekeeping, and
        # vice versa (ClusterApplierService catches per-applier the same way)
        for applier in (self.reconciler.apply_cluster_state,
                        self._master_housekeeping,
                        self._maybe_mesh_warmup):
            try:
                applier(state)
            except Exception:  # noqa: BLE001
                logger.exception("applier %s failed for state v%s on %s",
                                 getattr(applier, "__name__", applier),
                                 state.version, self.node_id)

    def _maybe_mesh_warmup(self, state: ClusterState) -> None:
        """search.mesh.warmup_at_boot applier: the first state (boot or
        committed) that carries the setting pays backend first-init via
        MESH_PLANES.warmup() — once per process, counted in the
        mesh_plane_warmups stat. Off by default: warmup blocks on device
        init, which only a node explicitly opted into mesh serving
        should pay at boot."""
        # getattr: a committed-state applier can fire before __init__
        # reaches the flag assignment
        if getattr(self, "_mesh_warmed", False):
            return
        from elasticsearch_tpu.utils.settings import (
            SEARCH_MESH_WARMUP_AT_BOOT, setting_from_state,
        )
        if not setting_from_state(state, SEARCH_MESH_WARMUP_AT_BOOT):
            return
        self._mesh_warmed = True
        from elasticsearch_tpu.ops.device_segment import MESH_PLANES
        MESH_PLANES.warmup()

    def _master_housekeeping(self, state: ClusterState) -> None:
        """On the elected master: clean up routing after membership changes
        (the reference couples this via NodeRemovalClusterStateTaskExecutor
        and reroute listeners)."""
        if self.coordinator.mode != Mode.LEADER:
            # fetch/verify bookkeeping is master-only state
            self.gateway_allocator.leader_stepdown()
            return
        # keep the gateway fetch cache honest across membership changes,
        # and start verifying STARTED copies on rebooted hosts
        self.gateway_allocator.cluster_changed(state)
        dead = {sr.node_id for sr in state.routing_table.all_shards()
                if sr.node_id is not None and sr.node_id not in state.nodes}
        dead |= {sr.relocating_node_id
                 for sr in state.routing_table.all_shards()
                 if sr.relocating_node_id is not None
                 and sr.relocating_node_id not in state.nodes}
        needs_reroute = any(
            sr.state.value == "UNASSIGNED"
            for sr in state.routing_table.all_shards())
        if not dead and not needs_reroute:
            return

        def update(current: ClusterState) -> ClusterState:
            out = current
            dead_now = {sr.node_id
                        for sr in out.routing_table.all_shards()
                        if sr.node_id is not None
                        and sr.node_id not in out.nodes}
            if dead_now:
                out = self.allocation_service.disassociate_dead_nodes(
                    out, dead_now)
            return self.allocation_service.reroute(out)
        self.coordinator.submit_state_update("housekeeping-reroute", update)

    def _auto_create_index(self, name: str,
                           on_done: Callable[[Optional[Exception]], None]
                           ) -> None:
        def cb(resp, err):
            on_done(err)
        self.master_client.execute(
            CREATE_INDEX, {"index": name, "ignore_existing": True,
                           "settings": {"number_of_replicas": 1}}, cb)

    # ------------------------------------------------------------------

    def start(self) -> None:
        # bootstrap checks first (BootstrapChecks.check analog): dev mode
        # warns, ESTPU_ENFORCE_BOOTSTRAP aborts startup on failure
        from elasticsearch_tpu.monitor import run_bootstrap_checks
        run_bootstrap_checks(self.indices_service.data_path)
        self.coordinator.start()
        self.ilm_service.start()
        self.slm_service.start()
        self.resource_watcher.start()
        self.persistent_tasks.start()
        self.transform_service.start()
        self.watcher_service.start()
        self.ccr_service.start()
        self.rollup_service.start()
        self.monitoring_service.start()
        self.ml_jobs.start()

    def stop(self) -> None:
        self.ml_jobs.stop()
        self.monitoring_service.stop()
        self.rollup_service.stop()
        self.ccr_service.stop()
        self.watcher_service.stop()
        self.transform_service.stop()
        self.ilm_service.stop()
        self.slm_service.stop()
        self.resource_watcher.stop()
        self.persistent_tasks.stop()
        self.coordinator.stop()
        self.transport_service.close()
        self.indices_service.close()


class NodeClient:
    """Typed in-process API facade — what the REST layer dispatches to.

    Every method is callback-style ``(args..., on_done(resp, err))`` so the
    same code runs under the deterministic scheduler and production.
    """

    def __init__(self, node: Node):
        self.node = node

    # -- index admin ----------------------------------------------------

    def create_index(self, name: str, body: Optional[Dict[str, Any]],
                     on_done, ignore_templates: bool = False) -> None:
        body = body or {}
        self.node.master_client.execute(CREATE_INDEX, {
            "index": name,
            "settings": body.get("settings") or {},
            "mappings": body.get("mappings") or {},
            "ignore_templates": ignore_templates,
        }, on_done)

    def delete_index(self, name: str, on_done) -> None:
        self.node.master_client.execute(DELETE_INDEX, {"index": name},
                                        on_done)

    def put_mapping(self, name: str, mappings: Dict[str, Any],
                    on_done) -> None:
        self.node.master_client.execute(
            PUT_MAPPING, {"index": name, "mappings": mappings}, on_done)

    def update_settings(self, name: str, settings: Dict[str, Any],
                        on_done) -> None:
        self.node.master_client.execute(
            UPDATE_SETTINGS, {"index": name, "settings": settings}, on_done)

    def update_aliases(self, actions: List[Dict[str, Any]], on_done) -> None:
        self.node.master_client.execute(
            UPDATE_ALIASES, {"actions": actions}, on_done)

    def cluster_update_settings(self, body: Dict[str, Any], on_done) -> None:
        self.node.master_client.execute(CLUSTER_UPDATE_SETTINGS, body,
                                        on_done)

    def get_mapping(self, name: str):
        state = self.node._applied_state()
        meta = state.metadata.index(name)
        return {meta.name: {"mappings": dict(meta.mappings)}}

    # -- index templates / ILM / rollover -------------------------------

    def put_index_template(self, name: str, body: Dict[str, Any],
                           on_done) -> None:
        from elasticsearch_tpu.action.admin import PUT_TEMPLATE
        self.node.master_client.execute(
            PUT_TEMPLATE, {"name": name, "body": body}, on_done)

    def delete_index_template(self, name: str, on_done) -> None:
        from elasticsearch_tpu.action.admin import DELETE_TEMPLATE
        self.node.master_client.execute(
            DELETE_TEMPLATE, {"name": name}, on_done)

    def get_index_templates(self, name: Optional[str] = None
                            ) -> Dict[str, Any]:
        templates = self.node._applied_state().metadata.templates
        if name is not None:
            import fnmatch
            templates = {k: v for k, v in templates.items()
                         if fnmatch.fnmatch(k, name)}
        return {"index_templates": [
            {"name": k, "index_template": dict(v)}
            for k, v in sorted(templates.items())]}

    def put_ilm_policy(self, name: str, body: Dict[str, Any],
                       on_done) -> None:
        from elasticsearch_tpu.action.admin import PUT_ILM_POLICY
        self.node.master_client.execute(
            PUT_ILM_POLICY,
            {"name": name, "policy": (body or {}).get("policy", body)},
            on_done)

    def delete_ilm_policy(self, name: str, on_done) -> None:
        from elasticsearch_tpu.action.admin import DELETE_ILM_POLICY
        self.node.master_client.execute(
            DELETE_ILM_POLICY, {"name": name}, on_done)

    def get_ilm_policies(self) -> Dict[str, Any]:
        return {k: {"policy": dict(v)} for k, v in sorted(
            self.node._applied_state().metadata.ilm_policies.items())}

    def put_slm_policy(self, policy_id: str, body: Dict[str, Any],
                       on_done) -> None:
        from elasticsearch_tpu.action.admin import PUT_CUSTOM
        from elasticsearch_tpu.xpack.slm import SECTION, \
            SnapshotLifecycleService
        body = dict(body or {})
        try:
            SnapshotLifecycleService.validate(body)
        except Exception as e:  # noqa: BLE001 — report as 400
            on_done(None, e)
            return
        # preserve scheduler bookkeeping across policy updates
        prior = self.node.slm_service.policies().get(policy_id, {})
        for k in ("_counter", "_last_run_ms", "_last_success"):
            if k in prior:
                body.setdefault(k, prior[k])
        self.node.master_client.execute(PUT_CUSTOM, {
            "section": SECTION, "name": policy_id, "body": body}, on_done)

    def delete_slm_policy(self, policy_id: str, on_done) -> None:
        from elasticsearch_tpu.action.admin import DELETE_CUSTOM
        from elasticsearch_tpu.xpack.slm import SECTION
        self.node.master_client.execute(DELETE_CUSTOM, {
            "section": SECTION, "name": policy_id}, on_done)

    # -- security ---------------------------------------------------------

    def put_security_user(self, name: str, body: Dict[str, Any],
                          on_done) -> None:
        from elasticsearch_tpu.action.admin import PUT_SECURITY
        from elasticsearch_tpu.xpack.security import hash_password
        raw = dict(body or {})
        password = raw.pop("password", None)
        if password is None:
            # pre-hashed credentials are NOT accepted (the reference's
            # API doesn't either): a stored malformed hash/salt pair
            # would crash every later verification for that user
            on_done(None, IllegalArgumentError(
                f"user [{name}] requires [password]"))
            return
        roles = raw.get("roles", [])
        if not isinstance(roles, list) or \
                not all(isinstance(r, str) for r in roles):
            on_done(None, IllegalArgumentError(
                f"user [{name}] [roles] must be a list of role names"))
            return
        entity = {"roles": roles, **hash_password(str(password))}
        if "full_name" in raw:
            entity["full_name"] = str(raw["full_name"])
        self.node.master_client.execute(
            PUT_SECURITY, {"kind": "users", "name": name, "body": entity},
            on_done)

    def put_security_role(self, name: str, body: Dict[str, Any],
                          on_done) -> None:
        from elasticsearch_tpu.action.admin import PUT_SECURITY
        from elasticsearch_tpu.xpack.security import (
            CLUSTER_PRIVILEGES, INDEX_PRIVILEGES,
        )
        body = dict(body or {})
        bad = set(body.get("cluster", [])) - CLUSTER_PRIVILEGES
        if bad:
            on_done(None, IllegalArgumentError(
                f"unknown cluster privileges {sorted(bad)}"))
            return
        for grant in body.get("indices", []):
            names = grant.get("names")
            if not isinstance(names, list) or not names:
                on_done(None, IllegalArgumentError(
                    "role index grants require [names] as a list"))
                return
            bad = set(grant.get("privileges", [])) - INDEX_PRIVILEGES
            if bad:
                on_done(None, IllegalArgumentError(
                    f"unknown index privileges {sorted(bad)}"))
                return
        self.node.master_client.execute(
            PUT_SECURITY, {"kind": "roles", "name": name,
                           "body": body}, on_done)

    def delete_security_entity(self, kind: str, name: str, on_done) -> None:
        from elasticsearch_tpu.action.admin import DELETE_SECURITY
        self.node.master_client.execute(
            DELETE_SECURITY, {"kind": kind, "name": name}, on_done)

    def get_security_entities(self, kind: str,
                              name: Optional[str] = None) -> Dict[str, Any]:
        section = dict(self.node._applied_state()
                       .metadata.security.get(kind, {}))
        if name is not None:
            section = {k: v for k, v in section.items() if k == name}
        # never expose hashes over the API
        return {k: {kk: vv for kk, vv in v.items()
                    if kk not in ("hash", "salt")}
                for k, v in section.items()}

    def create_data_stream(self, name: str, on_done) -> None:
        from elasticsearch_tpu.action.admin import CREATE_DATA_STREAM
        self.node.master_client.execute(CREATE_DATA_STREAM,
                                        {"name": name}, on_done)

    def delete_data_stream(self, name: str, on_done) -> None:
        from elasticsearch_tpu.action.admin import DELETE_DATA_STREAM
        self.node.master_client.execute(DELETE_DATA_STREAM,
                                        {"name": name}, on_done)

    def get_data_streams(self, name: Optional[str] = None
                         ) -> Dict[str, Any]:
        """GET /_data_stream[/{name}] shape (GetDataStreamAction)."""
        import fnmatch as _fn
        state = self.node._applied_state()
        streams = state.metadata.data_streams
        if name and "*" not in name:
            if name not in streams:
                from elasticsearch_tpu.utils.errors import (
                    IndexNotFoundError,
                )
                raise IndexNotFoundError(name)
            chosen = {name: streams[name]}
        elif name:
            chosen = {k: v for k, v in streams.items()
                      if _fn.fnmatch(k, name)}
        else:
            chosen = streams
        out = []
        for ds_name in sorted(chosen):
            ds = chosen[ds_name]
            out.append({
                "name": ds_name,
                "timestamp_field": ds.get("timestamp_field",
                                          {"name": "@timestamp"}),
                "generation": ds.get("generation", 1),
                "indices": [{"index_name": n}
                            for n in ds.get("indices", [])],
                "status": "GREEN",
            })
        return {"data_streams": out}

    def rollover(self, alias: str, body: Optional[Dict[str, Any]],
                 on_done) -> None:
        """Coordinator half of rollover (TransportRolloverAction): evaluate
        conditions against live stats, then submit the atomic state update.
        No conditions means roll unconditionally."""
        from elasticsearch_tpu.action.admin import (
            ROLLOVER, next_rollover_name,
        )
        from elasticsearch_tpu.utils.errors import IllegalArgumentError
        body = body or {}
        conditions = body.get("conditions") or {}
        unknown = set(conditions) - {"max_age", "max_docs"}
        if unknown:
            # silently ignoring a condition would mean "never rolls" with
            # no signal — reject like an unknown request parameter
            on_done(None, IllegalArgumentError(
                f"unknown rollover conditions {sorted(unknown)}; "
                "supported: max_age, max_docs"))
            return
        state = self.node._applied_state()
        data_stream = state.metadata.data_streams.get(alias)
        try:
            source = state.metadata.index(alias)   # exactly-one resolution
        except Exception as e:  # noqa: BLE001 — not-found / ambiguous
            on_done(None, e)
            return
        if data_stream is None and alias not in source.aliases:
            on_done(None, IllegalArgumentError(
                f"rollover target [{alias}] is a concrete index, not an "
                "alias or data stream"))
            return
        if data_stream is not None:
            from elasticsearch_tpu.action.admin import backing_index_name
            new_index = body.get("new_index") or backing_index_name(
                alias, int(data_stream.get("generation", 1)) + 1)
        else:
            new_index = body.get("new_index") or \
                next_rollover_name(source.name)

        def proceed(met: Dict[str, bool]) -> None:
            if conditions and not any(met.values()):
                on_done({"acknowledged": False, "rolled_over": False,
                         "dry_run": bool(body.get("dry_run")),
                         "conditions": met}, None)
                return
            if body.get("dry_run"):
                on_done({"acknowledged": False, "rolled_over": False,
                         "dry_run": True, "conditions": met}, None)
                return
            request = {
                "new_index": new_index,
                "settings": body.get("settings") or {},
                "mappings": body.get("mappings") or {},
            }
            if data_stream is not None:
                request["data_stream"] = alias
            else:
                request["alias"] = alias
            self.node.master_client.execute(ROLLOVER, request,
                                            lambda resp, err: on_done(
                {**(resp or {}), "old_index": source.name,
                 "conditions": met} if err is None else None, err))

        met: Dict[str, bool] = {}
        if "max_age" in conditions:
            created = int(source.settings.get("index.creation_date", 0))
            age_ms = self.node.scheduler.wall_now() * 1000 - created
            from elasticsearch_tpu.utils.settings import (
                parse_time_to_seconds,
            )
            met[f"[max_age: {conditions['max_age']}]"] = \
                age_ms >= parse_time_to_seconds(conditions["max_age"]) * 1000
        if "max_docs" in conditions:
            def with_stats(resp, err=None):
                if err is not None or resp is None:
                    # a stats failure must NOT read as "condition unmet" —
                    # that would silently stop a series from ever rolling
                    on_done(None, err or SearchEngineError(
                        f"stats unavailable for [{source.name}]"))
                    return
                idx = resp.get("indices", {}).get(source.name, {})
                docs = idx.get("primaries", {}).get(
                    "docs", {}).get("count", 0)
                met[f"[max_docs: {conditions['max_docs']}]"] = \
                    docs >= int(conditions["max_docs"])
                proceed(met)
            self.index_stats(source.name, with_stats)
            return
        proceed(met)

    # -- documents ------------------------------------------------------

    def index_doc(self, index: str, doc_id: str, source: Dict[str, Any],
                  on_done, routing: Optional[str] = None,
                  op_type: str = "index",
                  if_seq_no: Optional[int] = None,
                  if_primary_term: Optional[int] = None,
                  pipeline: Optional[str] = None) -> None:
        item = {"action": "create" if op_type == "create" else "index",
                "index": index, "id": doc_id, "source": source,
                "routing": routing}
        if pipeline is not None:
            item["pipeline"] = pipeline
        if if_seq_no is not None:
            item["if_seq_no"] = if_seq_no
        if if_primary_term is not None:
            item["if_primary_term"] = if_primary_term
        self._single_item_bulk(item, index, on_done)

    def delete_doc(self, index: str, doc_id: str, on_done,
                   routing: Optional[str] = None) -> None:
        self._single_item_bulk(
            {"action": "delete", "index": index, "id": doc_id,
             "routing": routing}, index, on_done)

    def _single_item_bulk(self, item, index, on_done) -> None:
        def cb(resp: Dict[str, Any]) -> None:
            result = next(iter(resp["items"][0].values()))
            if "error" in result:
                status = result.get("status", 500)
                err = SearchEngineError(result["error"]["reason"])
                err.status = status
                # an indexing-pressure 429 carries a computed Retry-After:
                # keep it on the error so the REST controller's
                # _retry_after_of emits the header for single-doc writes
                ra = result["error"].get("retry_after")
                if ra is not None:
                    err.metadata["retry_after"] = ra
                on_done(result, err)
            else:
                # keep the CONCRETE index the bulk path resolved (an
                # aliased write reports its write index, not the alias)
                result.setdefault("_index", index)
                result["_id"] = result.pop("id", item["id"])
                on_done(result, None)
        self.node.bulk_action.execute([item], cb)

    def bulk(self, items: List[Dict[str, Any]], on_done,
             payload_bytes: Optional[int] = None) -> None:
        self.node.bulk_action.execute(
            items, lambda resp: on_done(resp, None),
            payload_bytes=payload_bytes)

    def get(self, index: str, doc_id: str, on_done,
            routing: Optional[str] = None, realtime: bool = True) -> None:
        self.node.get_action.execute(index, doc_id, on_done,
                                     routing=routing, realtime=realtime)

    def update(self, index: str, doc_id: str, body: Dict[str, Any],
               on_done, routing: Optional[str] = None,
               retry_on_conflict: int = 3) -> None:
        self.node.update_action.execute(index, doc_id, body, on_done,
                                        routing=routing,
                                        retry_on_conflict=retry_on_conflict)

    # -- search ---------------------------------------------------------

    def search(self, index_expression: str, body: Optional[Dict[str, Any]],
               on_done, search_type: str = "query_then_fetch") -> None:
        self.node.search_action.execute(index_expression, body or {},
                                        on_done, search_type=search_type)

    def count(self, index_expression: str, body: Optional[Dict[str, Any]],
              on_done) -> None:
        body = dict(body or {})
        body["size"] = 0
        body["track_total_hits"] = True

        def cb(resp, err):
            if err is not None:
                on_done(None, err)
            else:
                on_done({"count": resp["hits"]["total"]["value"],
                         "_shards": resp["_shards"]}, None)
        self.search(index_expression, body, cb)

    # -- maintenance ----------------------------------------------------

    def refresh(self, index_expression: str, on_done) -> None:
        self.node.broadcast_actions.broadcast(
            REFRESH_SHARD, index_expression,
            lambda r: on_done(_shards_only(r), None))

    def flush(self, index_expression: str, on_done) -> None:
        self.node.broadcast_actions.broadcast(
            FLUSH_SHARD, index_expression,
            lambda r: on_done(_shards_only(r), None))

    def force_merge(self, index_expression: str, on_done,
                    max_num_segments: int = 1) -> None:
        self.node.broadcast_actions.broadcast(
            FORCEMERGE_SHARD, index_expression,
            lambda r: on_done(_shards_only(r), None),
            extra={"max_num_segments": max_num_segments})

    def index_stats(self, index_expression: str, on_done) -> None:
        """Per-index doc/segment stats aggregated over primary shards
        (TransportIndicesStatsAction analog)."""
        state = self.node._applied_state()
        try:
            names = resolve_index_expression(index_expression,
                                             state.metadata)
        except Exception as e:  # IndexNotFoundError → caller maps to 404
            on_done(None, e)
            return

        search_keys = ("query_total", "wand_queries",
                       "wand_blocks_total", "wand_blocks_scored",
                       "request_cache_hits", "request_cache_misses")

        def _zero() -> Dict[str, Any]:
            return {"docs": 0, "segments": 0, "translog_ops": 0,
                    "search": {k: 0 for k in search_keys}}

        def cb(r: Dict[str, Any]) -> None:
            per_index: Dict[str, Dict[str, Any]] = {n: _zero() for n in names}
            for p in r.get("payloads", []):
                if not p.get("primary"):
                    continue
                agg = per_index.setdefault(p["index"], _zero())
                agg["docs"] += p.get("docs", 0)
                agg["segments"] += p.get("segments", 0)
                agg["translog_ops"] += p.get("translog_ops", 0)
                for k in search_keys:
                    agg["search"][k] += p.get("search", {}).get(k, 0)
            indices_out = {}
            total_docs = 0
            for n in names:
                agg = per_index[n]
                total_docs += agg["docs"]
                prim = {"docs": {"count": agg["docs"], "deleted": 0},
                        "segments": {"count": agg["segments"]},
                        "translog": {"operations": agg["translog_ops"]},
                        "search": agg["search"]}
                indices_out[n] = {
                    "uuid": state.metadata.index(n).uuid,
                    "primaries": prim, "total": prim}
            total = {"docs": {"count": total_docs, "deleted": 0}}
            on_done({"_shards": r["_shards"],
                     "_all": {"primaries": total, "total": total},
                     "indices": indices_out}, None)
        self.node.broadcast_actions.broadcast(STATS_SHARD, index_expression,
                                              cb, names=names)

    # -- misc read APIs -------------------------------------------------

    def mget(self, body: Dict[str, Any], on_done,
             index: Optional[str] = None) -> None:
        self.node.misc_actions.mget(body, index, on_done)

    def termvectors(self, index: str, doc_id: str, on_done,
                    fields: Optional[List[str]] = None,
                    routing: Optional[str] = None) -> None:
        self.node.misc_actions.termvectors(index, doc_id, on_done,
                                           fields=fields, routing=routing)

    def explain(self, index: str, doc_id: str, body: Dict[str, Any],
                on_done, routing: Optional[str] = None) -> None:
        self.node.misc_actions.explain(index, doc_id, body, on_done,
                                       routing=routing)

    def field_caps(self, index_expression: str,
                   fields: Optional[str] = None) -> Dict[str, Any]:
        return self.node.misc_actions.field_caps(index_expression, fields)

    def analyze(self, body: Dict[str, Any],
                index: Optional[str] = None) -> Dict[str, Any]:
        return self.node.misc_actions.analyze(body, index=index)

    def rank_eval(self, index: str, body: Dict[str, Any], on_done) -> None:
        self.node.rank_eval_action.execute(index, body, on_done)

    # -- stored scripts / search templates ------------------------------

    def put_stored_script(self, script_id: str, body: Dict[str, Any],
                          on_done) -> None:
        from elasticsearch_tpu.script.mustache import STORED_SCRIPT_PREFIX
        script = (body or {}).get("script", body or {})
        self.cluster_update_settings(
            {"persistent": {STORED_SCRIPT_PREFIX + script_id: script}},
            on_done)

    def get_stored_script(self, script_id: str) -> Optional[Dict[str, Any]]:
        from elasticsearch_tpu.script.mustache import STORED_SCRIPT_PREFIX
        state = self.node._applied_state()
        return state.metadata.persistent_settings.get(
            STORED_SCRIPT_PREFIX + script_id)

    def delete_stored_script(self, script_id: str, on_done) -> None:
        from elasticsearch_tpu.script.mustache import STORED_SCRIPT_PREFIX
        from elasticsearch_tpu.utils.errors import ResourceNotFoundError
        if self.get_stored_script(script_id) is None:
            on_done(None, ResourceNotFoundError(
                f"stored script [{script_id}] does not exist"))
            return
        self.cluster_update_settings(
            {"persistent": {STORED_SCRIPT_PREFIX + script_id: None}},
            on_done)

    def search_template(self, index_expression: str,
                        template: Dict[str, Any], on_done) -> None:
        from elasticsearch_tpu.script.mustache import render_search_body
        try:
            body = render_search_body(template or {},
                                      self.get_stored_script)
        except Exception as e:
            on_done(None, e)
            return
        self.search(index_expression, body, on_done)

    def render_template(self, template: Dict[str, Any]) -> Dict[str, Any]:
        from elasticsearch_tpu.script.mustache import render_search_body
        return {"template_output": render_search_body(
            template or {}, self.get_stored_script)}

    # -- reindex family -------------------------------------------------

    def reindex(self, body: Dict[str, Any], on_done,
                wait_for_completion: bool = True) -> None:
        self.node.reindex_actions.reindex(
            body, on_done, wait_for_completion=wait_for_completion)

    def update_by_query(self, index: str, body: Dict[str, Any], on_done,
                        wait_for_completion: bool = True) -> None:
        self.node.reindex_actions.update_by_query(
            index, body, on_done,
            wait_for_completion=wait_for_completion)

    def delete_by_query(self, index: str, body: Dict[str, Any], on_done,
                        wait_for_completion: bool = True) -> None:
        self.node.reindex_actions.delete_by_query(
            index, body, on_done,
            wait_for_completion=wait_for_completion)

    # -- tasks ----------------------------------------------------------

    def list_tasks(self, on_done, actions: Optional[str] = None) -> None:
        self.node.task_actions.list_tasks(on_done, actions=actions)

    def get_task(self, task_id: str, on_done) -> None:
        """Resolved on the task's owning node (cross-node by id prefix)."""
        self.node.task_actions.get_task(task_id, on_done)

    def cancel_tasks(self, on_done, task_id: Optional[str] = None,
                     actions: Optional[str] = None) -> None:
        self.node.task_actions.cancel_tasks(on_done, task_id=task_id,
                                            actions=actions)

    # -- ingest pipelines ----------------------------------------------

    def put_pipeline(self, pipeline_id: str, body: Dict[str, Any],
                     on_done) -> None:
        from elasticsearch_tpu.ingest import (
            PIPELINE_SETTING_PREFIX, IngestService,
        )
        try:
            IngestService.validate(body or {})
        except Exception as e:
            on_done(None, e)
            return
        self.cluster_update_settings(
            {"persistent": {PIPELINE_SETTING_PREFIX + pipeline_id:
                            body or {}}}, on_done)

    def get_pipeline(self, pipeline_id: Optional[str] = None
                     ) -> Dict[str, Any]:
        from elasticsearch_tpu.utils.errors import ResourceNotFoundError
        pipelines = self.node.ingest_service.list_pipelines()
        if pipeline_id in (None, "*", "_all"):
            return pipelines
        if pipeline_id not in pipelines:
            raise ResourceNotFoundError(
                f"pipeline [{pipeline_id}] does not exist")
        return {pipeline_id: pipelines[pipeline_id]}

    def delete_pipeline(self, pipeline_id: str, on_done) -> None:
        from elasticsearch_tpu.ingest import PIPELINE_SETTING_PREFIX
        from elasticsearch_tpu.utils.errors import ResourceNotFoundError
        if pipeline_id not in self.node.ingest_service.list_pipelines():
            on_done(None, ResourceNotFoundError(
                f"pipeline [{pipeline_id}] does not exist"))
            return
        self.cluster_update_settings(
            {"persistent": {PIPELINE_SETTING_PREFIX + pipeline_id: None}},
            on_done)

    def simulate_pipeline(self, body: Dict[str, Any],
                          pipeline_id: Optional[str] = None
                          ) -> Dict[str, Any]:
        """POST _ingest/pipeline/[{id}/]_simulate"""
        service = self.node.ingest_service
        if pipeline_id is None:
            inline = (body or {}).get("pipeline")
            if inline is None:
                raise IllegalArgumentError(
                    "simulate requires a [pipeline] definition or id")
            procs = [service.compile_processor(p)
                     for p in inline.get("processors", [])]

            def run_pipeline(doc):
                for p in procs:
                    doc = p.run(doc)
                    if doc is None:
                        return None
                return doc
        else:
            def run_pipeline(doc):
                return service.execute_pipeline(pipeline_id, doc)
        docs_out = []
        for entry in (body or {}).get("docs", []):
            doc = {"_source": dict(entry.get("_source") or {}),
                   "_index": entry.get("_index", "_index"),
                   "_id": entry.get("_id", "_id"),
                   "_routing": entry.get("_routing")}
            try:
                result = run_pipeline(doc)
                if result is None:
                    docs_out.append({"doc": None})
                else:
                    docs_out.append({"doc": {
                        "_index": result["_index"],
                        "_id": result["_id"],
                        "_source": result["_source"]}})
            except Exception as e:  # noqa: BLE001 — per-doc result
                docs_out.append({"error": {
                    "type": type(e).__name__, "reason": str(e)}})
        return {"docs": docs_out}

    # -- snapshots ------------------------------------------------------

    def put_repository(self, name: str, body: Dict[str, Any],
                       on_done) -> None:
        from elasticsearch_tpu.repositories import repository_settings
        try:
            settings = repository_settings(name, body or {})
        except Exception as e:
            on_done(None, e)
            return
        self.cluster_update_settings({"persistent": settings}, on_done)

    def get_repositories(self) -> Dict[str, Any]:
        state = self.node._applied_state()
        out: Dict[str, Any] = {}
        for key, val in state.metadata.persistent_settings.items():
            if key.startswith("repositories.") and key.endswith(".type"):
                name = key[len("repositories."):-len(".type")]
                out[name] = {
                    "type": val,
                    "settings": {"location":
                                 state.metadata.persistent_settings.get(
                                     f"repositories.{name}.location")}}
        return out

    def create_snapshot(self, repo: str, snap: str,
                        body: Optional[Dict[str, Any]], on_done) -> None:
        self.node.snapshot_actions.create(repo, snap, body, on_done)

    def restore_snapshot(self, repo: str, snap: str,
                         body: Optional[Dict[str, Any]], on_done) -> None:
        self.node.snapshot_actions.restore(repo, snap, body, on_done)

    def get_snapshots(self, repo: str, snap: str = "_all"
                      ) -> Dict[str, Any]:
        return self.node.snapshot_actions.get(repo, snap)

    def delete_snapshot(self, repo: str, snap: str) -> Dict[str, Any]:
        return self.node.snapshot_actions.delete(repo, snap)

    # -- cluster --------------------------------------------------------

    def cluster_health(self, index: Optional[str] = None) -> Dict[str, Any]:
        # STARTED copies the (local, if master) gateway allocator hasn't
        # confirmed are actually hosted count against green: a rebooted
        # node's stale routing must not hide a missing shard. NOTE: the
        # unverified marks live on the elected master only — REST health
        # goes through cluster_health_async, which routes non-master
        # requests to the master so the gate is authoritative
        # cluster-wide; this sync form reports the LOCAL view.
        return cluster_health(
            self.node._applied_state(), index,
            unverified=self.node.gateway_allocator.health_unverified())

    def _route_health_to_master(self, payload: Dict[str, Any],
                                leader_answer, local_flagged,
                                on_done) -> None:
        """Shared master-routing ladder for the health surfaces: answer
        on the ELECTED MASTER (whose gateway allocator owns the
        unverified-STARTED marks), refuse to serve a deposed master's
        stale view as authoritative, and fall back to the FLAGGED local
        view only when no master is known or the master doesn't
        answer."""
        master = self.node._applied_state().master_node_id
        if master == self.node.node_id:
            if self.node.coordinator.mode == Mode.LEADER:
                leader_answer()
            else:
                local_flagged()
            return
        if master is None:
            local_flagged()
            return

        def cb(resp, err):
            if err is not None or resp is None:
                local_flagged()
            else:
                on_done(resp, None)

        self.node.transport_service.send_request(
            master, CLUSTER_HEALTH_ACTION, payload, cb, timeout=10.0)

    def cluster_health_async(self, index: Optional[str],
                             on_done) -> None:
        """Authoritative cluster health: computed on the elected master,
        like the reference's master-node health action — a non-master
        node can no longer report green during the post-reboot verify
        window."""

        def local_flagged() -> None:
            local = self.cluster_health(index)
            local["master_routed"] = False
            on_done(local, None)

        self._route_health_to_master(
            {"index": index},
            lambda: on_done(self.cluster_health(index), None),
            local_flagged, on_done)

    def cluster_healths_async(self, indices: List[str], on_done) -> None:
        """Bulk master-routed health: every index's status resolved in
        ONE round trip to the elected master (the _cat/indices surface —
        the chained per-index form paid O(n_indices) sequential RPCs).
        ``on_done({"indices": {name: health_dict}}, None)``; the
        flagged local-view fallback applies exactly as in
        cluster_health_async."""

        def local_flagged() -> None:
            state = self.node._applied_state()
            out = {"indices": {
                name: self.cluster_health(name) for name in indices
                if state.metadata.has_index(name)},
                "master_routed": False}
            on_done(out, None)

        self._route_health_to_master(
            {"indices": indices},
            lambda: on_done(self.node._on_cluster_health(
                {"indices": indices}, self.node.node_id), None),
            local_flagged, on_done)

    def cluster_state(self) -> Dict[str, Any]:
        return self.node._applied_state().to_dict()

    def nodes_stats(self) -> Dict[str, Any]:
        """Local node's stats only (the historical sync form)."""
        return {"nodes": {self.node.node_id: self.node.local_node_stats()}}

    def nodes_stats_all(self, on_done, sections=None,
                        timeout: float = 30.0) -> None:
        """Every cluster node's stats, gathered over transport
        (TransportNodesStatsAction fan-out). ``sections`` narrows the
        request so single-section consumers (the _cluster/stats
        search_latency merge) don't make every node build its full
        stats payload; they also pass a short ``timeout`` so one dead
        node can't stall the endpoint for the full 30s."""
        state = self.node._applied_state()
        node_ids = sorted(state.nodes)
        out: Dict[str, Any] = {}
        pending = {"n": len(node_ids)}
        if not node_ids:
            on_done({"nodes": {}}, None)
            return
        req = {"sections": list(sections)} if sections else {}
        for nid in node_ids:
            def cb(resp, err, nid=nid):
                if err is None and resp is not None:
                    out[nid] = resp
                pending["n"] -= 1
                if pending["n"] == 0:
                    on_done({"_nodes": {"total": len(node_ids),
                                        "successful": len(out),
                                        "failed":
                                            len(node_ids) - len(out)},
                             "nodes": out}, None)
            if nid == self.node.node_id:
                cb(self.node.local_node_stats(sections=sections), None)
            else:
                self.node.transport_service.send_request(
                    nid, NODE_STATS_ACTION, req, cb, timeout=timeout)


def _shards_only(r: Dict[str, Any]) -> Dict[str, Any]:
    return {"_shards": r["_shards"]}
