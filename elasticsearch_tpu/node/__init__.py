from elasticsearch_tpu.node.node import Node, NodeClient

__all__ = ["Node", "NodeClient"]
