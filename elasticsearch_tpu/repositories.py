"""Snapshot repositories: content-addressed incremental blob storage.

Reference analog: repositories/blobstore/BlobStoreRepository.java:156 —
snapshotShard (:1695) copies only segment files the repository doesn't
already hold (content addressing makes snapshots incremental for free) and
restoreShard (:1924) downloads a shard generation back. The unit here is a
whole serialized segment (segments are immutable, so a segment blob is the
exact analog of Lucene's immutable segment files).

Layout under the repository root:
    blobs/<sha256>.npz            segment arrays (shared across snapshots)
    blobs/<sha256>.json           segment meta
    snapshots/<name>.json         snapshot manifest: indices, shard -> blobs
    index.json                    list of snapshot names
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.index.segment import Segment
from elasticsearch_tpu.index.store import (
    segment_from_payload, segment_payload,
)
from elasticsearch_tpu.utils.errors import (
    IllegalArgumentError, SearchEngineError, ShardCorruptedError,
)


class RepositoryError(SearchEngineError):
    status = 500


class SnapshotMissingError(SearchEngineError):
    status = 404


class FsRepository:
    """Shared-filesystem repository (repositories/fs/FsRepository analog).
    Cloud backends implement the same three blob verbs over object stores."""

    def __init__(self, location: str):
        if not location:
            raise IllegalArgumentError("repository requires a [location]")
        self.root = Path(location)
        (self.root / "blobs").mkdir(parents=True, exist_ok=True)
        (self.root / "snapshots").mkdir(parents=True, exist_ok=True)

    # -- segment blobs (content-addressed) -------------------------------

    def put_segment(self, seg: Segment) -> str:
        """Upload a segment if absent; returns its content hash."""
        arrays, meta = segment_payload(seg)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        data = buf.getvalue()
        meta_bytes = json.dumps(meta, sort_keys=True).encode("utf-8")
        sha = hashlib.sha256(data + meta_bytes).hexdigest()
        npz_path = self.root / "blobs" / f"{sha}.npz"
        if npz_path.exists():
            return sha                     # incremental: already held
        self._atomic_write(npz_path, data)
        self._atomic_write(self.root / "blobs" / f"{sha}.json", meta_bytes)
        return sha

    def get_segment(self, sha: str) -> Segment:
        try:
            data = (self.root / "blobs" / f"{sha}.npz").read_bytes()
            meta_bytes = (self.root / "blobs" / f"{sha}.json").read_bytes()
        except FileNotFoundError:
            raise RepositoryError(f"missing segment blob [{sha}]")
        # content addressing doubles as end-to-end verification: the name
        # IS the expected hash, so a restore can never deserialize a
        # blob that rotted in the repository (BlobStoreIndexShardSnapshot
        # file checksums analog)
        actual = hashlib.sha256(data + meta_bytes).hexdigest()
        if actual != sha:
            raise ShardCorruptedError(
                f"snapshot blob [{sha}] failed verification "
                f"(content hash [{actual}])")
        meta = json.loads(meta_bytes.decode("utf-8"))
        with np.load(io.BytesIO(data)) as arrays:
            return segment_from_payload(meta, arrays)

    # -- snapshot manifests ---------------------------------------------

    def write_snapshot(self, name: str, manifest: Dict[str, Any]) -> None:
        path = self.root / "snapshots" / f"{name}.json"
        self._atomic_write(path,
                           json.dumps(manifest, sort_keys=True).encode())
        names = set(self.list_snapshots())
        names.add(name)
        self._atomic_write(self.root / "index.json",
                           json.dumps(sorted(names)).encode())

    def read_snapshot(self, name: str) -> Dict[str, Any]:
        try:
            with open(self.root / "snapshots" / f"{name}.json") as f:
                return json.load(f)
        except FileNotFoundError:
            raise SnapshotMissingError(f"snapshot [{name}] is missing")

    def list_snapshots(self) -> List[str]:
        try:
            with open(self.root / "index.json") as f:
                return list(json.load(f))
        except FileNotFoundError:
            return []

    def delete_snapshot(self, name: str) -> None:
        manifest = self.read_snapshot(name)
        names = [n for n in self.list_snapshots() if n != name]
        self._atomic_write(self.root / "index.json",
                           json.dumps(sorted(names)).encode())
        (self.root / "snapshots" / f"{name}.json").unlink(missing_ok=True)
        # gc blobs referenced by no remaining snapshot
        still_referenced = set()
        for other in names:
            still_referenced.update(_manifest_blobs(
                self.read_snapshot(other)))
        for sha in _manifest_blobs(manifest) - still_referenced:
            (self.root / "blobs" / f"{sha}.npz").unlink(missing_ok=True)
            (self.root / "blobs" / f"{sha}.json").unlink(missing_ok=True)

    # -- util -----------------------------------------------------------

    @staticmethod
    def _atomic_write(path: Path, data: bytes) -> None:
        tmp = path.with_name("." + path.name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)


def _manifest_blobs(manifest: Dict[str, Any]) -> set:
    out = set()
    for index in manifest.get("indices", {}).values():
        for blobs in index.get("shards", {}).values():
            out.update(blobs)
    return out


# ---------------------------------------------------------------------------
# repository registry (cluster-settings backed, RepositoriesService analog)
# ---------------------------------------------------------------------------

def repository_from_settings(name: str,
                             persistent_settings: Dict[str, Any]
                             ) -> FsRepository:
    rtype = persistent_settings.get(f"repositories.{name}.type")
    if rtype is None:
        raise SnapshotMissingError(f"repository [{name}] is missing")
    if rtype != "fs":
        raise IllegalArgumentError(
            f"unknown repository type [{rtype}] for [{name}]")
    return FsRepository(
        persistent_settings.get(f"repositories.{name}.location", ""))


def repository_settings(name: str, body: Dict[str, Any]) -> Dict[str, Any]:
    """PUT _snapshot/{name} body -> persistent-settings entries."""
    rtype = body.get("type")
    if rtype != "fs":
        raise IllegalArgumentError(
            f"repository type must be [fs], got [{rtype}]")
    location = (body.get("settings") or {}).get("location")
    if not location:
        raise IllegalArgumentError("repository requires settings.location")
    return {f"repositories.{name}.type": rtype,
            f"repositories.{name}.location": location}
