"""Index lifecycle management: hot -> warm -> cold -> delete.

Reference: x-pack/plugin/ilm/.../IndexLifecycleService.java:53 — a
master-side periodic service that walks indices carrying an
``index.lifecycle.name`` setting and advances them through their policy's
phases. Phases and actions implemented:

  hot:    {actions: {rollover: {max_age, max_docs}}} — roll the write
          alias (``index.lifecycle.rollover_alias``) or the data stream
          the index backs; rollover applies matching templates so the
          series keeps its mappings.
  warm:   {min_age, actions: {readonly: {}, forcemerge:
          {max_num_segments}, shrink: {number_of_shards}}} — write-block,
          merge segments, and optionally shrink to fewer shards (the
          shrunken index REPLACES the original in its aliases/data
          stream, then the original is deleted — ShrinkStep +
          ShrinkSetAliasStep semantics).
  cold:   {min_age, actions: {searchable_snapshot:
          {snapshot_repository}}} — snapshot the index into the repo,
          mount it back as a repo-backed searchable index replacing the
          original (MountSnapshotStep).
  delete: {min_age} — delete the index (and drop it from its stream).

The age origin is the rollover date when the index has been rolled (or
creation date for policies without a rollover action) — an index that is
still its series' write target is never advanced past hot. Steps are
idempotent and marked in index settings ("index.lifecycle.*"), so a
master failover resumes mid-phase work from the replicated state. The
loop only acts while this node is the elected master, and every action
goes through the normal master APIs — ILM is policy over the existing
primitives, not a second control plane.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from elasticsearch_tpu.utils.retry import retry_transient
from elasticsearch_tpu.utils.settings import parse_time_to_seconds

logger = logging.getLogger(__name__)

POLL_INTERVAL_SETTING = "indices.lifecycle.poll_interval"
DEFAULT_POLL_INTERVAL = 10.0

PHASE_ORDER = ("hot", "warm", "cold", "delete")


def compute_phase(settings, phases: Dict[str, Any],
                  now_ms: float) -> Dict[str, Any]:
    """{phase, age_ms, rolled_over} — ONE implementation of the age-origin
    and phase-gate rules, shared by the advance loop and the explain API
    so what explain reports is exactly what the machine will do."""
    hot = (phases.get("hot") or {}).get("actions") or {}
    rollover = hot.get("rollover")
    rolled_ms = settings.get("index.rollover_date")
    origin_ms: Optional[float] = None
    if rolled_ms is not None:
        origin_ms = int(rolled_ms)
    elif rollover is None:
        origin_ms = int(settings.get("index.creation_date", 0) or 0) or None
    age_ms = max(now_ms - origin_ms, 0) if origin_ms is not None else 0
    phase = "hot"
    if origin_ms is not None:
        for candidate in ("delete", "cold", "warm"):
            spec = phases.get(candidate)
            if spec is None:
                continue
            min_age_s = parse_time_to_seconds(spec.get("min_age", 0))
            if age_ms >= min_age_s * 1000:
                phase = candidate
                break
    return {"phase": phase, "age_ms": age_ms,
            "rolled_over": rolled_ms is not None}


class IndexLifecycleService:
    def __init__(self, node) -> None:
        self.node = node
        self._running = False
        self._timer = None
        # step keys with an in-flight retry loop: the poll tick must not
        # stack a second loop for the same index/step while one is still
        # backing off (non-idempotent steps like rollover would execute
        # once per stacked loop when the control plane recovers)
        self._inflight: set = set()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._schedule()

    def stop(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()

    def _poll_interval(self) -> float:
        state = self.node._applied_state()
        raw = state.metadata.persistent_settings.get(
            POLL_INTERVAL_SETTING, DEFAULT_POLL_INTERVAL)
        try:
            return max(0.5, parse_time_to_seconds(raw))
        except (TypeError, ValueError):
            return DEFAULT_POLL_INTERVAL

    def _schedule(self) -> None:
        if not self._running:
            return
        self._timer = self.node.scheduler.schedule(
            self._poll_interval(), self._tick)

    # -- the loop --------------------------------------------------------

    def _tick(self) -> None:
        if not self._running:
            return
        try:
            if self.node.coordinator.mode == "LEADER":
                self.run_once()
        except Exception:  # noqa: BLE001 — the loop must survive anything
            logger.exception("ilm tick failed")
        self._schedule()

    def run_once(self) -> None:
        """One pass over managed indices (triggerPolicies analog). Public
        so tests and an explicit API can step the lifecycle without
        waiting for the poll timer. Each pass advances each index by at
        most one step — repeated passes converge."""
        state = self.node._applied_state()
        now_ms = self.node.scheduler.wall_now() * 1000
        # backing index -> (stream name, is_write_index)
        stream_of: Dict[str, tuple] = {}
        for ds_name, ds in state.metadata.data_streams.items():
            indices = ds.get("indices", [])
            for i, backing in enumerate(indices):
                stream_of[backing] = (ds_name, i == len(indices) - 1)
        for meta in list(state.metadata.indices.values()):
            policy_name = meta.settings.get("index.lifecycle.name")
            if not policy_name:
                continue
            policy = state.metadata.ilm_policies.get(policy_name)
            if not policy:
                continue
            phases = policy.get("phases") or {}
            try:
                self._advance(meta, phases, now_ms,
                              stream_of.get(meta.name))
            except Exception:  # noqa: BLE001 — one index must not stall ILM
                logger.exception("ilm advance failed for [%s]", meta.name)

    # -- per-index step machine ------------------------------------------

    def _step(self, key: str, attempt, on_done=None) -> None:
        """Run one lifecycle action through the unified RetryableAction:
        transient control-plane failures (no master mid-election,
        unreachable node) retry with jittered backoff inside the tick
        instead of silently waiting out a whole poll interval
        (IndexLifecycleRunner's step-retry discipline). ``attempt(cb)``
        fires the async client call; non-transient errors surface to
        ``on_done`` (default: logged). ``key`` (index:step) dedupes:
        while one loop is still backing off, later poll ticks skip the
        step rather than stacking a second loop that would re-execute a
        non-idempotent action on recovery."""
        if key in self._inflight:
            return
        self._inflight.add(key)
        inner = on_done or _log_err

        def finished(resp, err) -> None:
            self._inflight.discard(key)
            inner(resp, err)

        retry_transient(self.node.scheduler, attempt, finished)

    def _advance(self, meta, phases: Dict[str, Any], now_ms: float,
                 stream: Optional[tuple]) -> None:
        hot = (phases.get("hot") or {}).get("actions") or {}
        rollover = hot.get("rollover")

        # age origin + phase gates: ONE shared rule set (compute_phase) —
        # an index still its series' write target (rollover pending) is
        # never advanced out from under the writers
        computed = compute_phase(meta.settings, phases, now_ms)
        phase_name = computed["phase"]
        if phase_name != "hot":
            getattr(self, f"_run_{phase_name}")(
                meta, (phases.get(phase_name) or {}).get("actions") or {},
                stream)
            return

        # hot: rollover the alias or data stream this index writes for
        alias = meta.settings.get("index.lifecycle.rollover_alias")
        if rollover is not None and alias and alias in meta.aliases:
            self._step(f"{meta.name}:rollover",
                       lambda cb: self.node.client.rollover(
                           alias, {"conditions": dict(rollover)}, cb))
        elif rollover is not None and stream is not None and stream[1]:
            self._step(f"{meta.name}:rollover",
                       lambda cb: self.node.client.rollover(
                           stream[0], {"conditions": dict(rollover)},
                           cb))

    def _run_delete(self, meta, _actions, _stream) -> None:
        logger.info("ilm: deleting [%s] (delete phase)", meta.name)
        self._step(f"{meta.name}:delete",
                   lambda cb: self.node.client.delete_index(meta.name,
                                                            cb))

    def _run_warm(self, meta, actions: Dict[str, Any], stream) -> None:
        """One warm step per pass: readonly -> forcemerge -> shrink."""
        client = self.node.client
        if "readonly" in actions and \
                not meta.settings.get("index.blocks.write"):
            self._step(f"{meta.name}:readonly",
                       lambda cb: client.update_settings(
                           meta.name, {"index.blocks.write": True}, cb))
            return
        if "forcemerge" in actions and \
                not meta.settings.get("index.lifecycle.forcemerged"):
            segs = int((actions.get("forcemerge") or {})
                       .get("max_num_segments", 1))

            def mark(_r, err):
                if err is None:
                    self._step(f"{meta.name}:forcemerged-mark",
                               lambda cb: client.update_settings(
                                   meta.name,
                                   {"index.lifecycle.forcemerged": True},
                                   cb))
                else:
                    _log_err(None, err)
            self._step(f"{meta.name}:forcemerge",
                       lambda cb: client.force_merge(
                           meta.name, cb, max_num_segments=segs),
                       on_done=mark)
            return
        if "shrink" in actions and \
                not meta.settings.get("index.lifecycle.shrink_source"):
            target = f"shrink-{meta.name}"
            state = self.node._applied_state()
            if not meta.settings.get("index.blocks.write"):
                # shrink requires the write block even without readonly
                self._step(f"{meta.name}:shrink-block",
                           lambda cb: client.update_settings(
                               meta.name, {"index.blocks.write": True},
                               cb))
                return
            if state.metadata.has_index(target):
                if self._copy_done(state, target,
                                   "index.resize.copy_complete"):
                    self._swap_references(meta, target, stream)
                return
            n = int((actions.get("shrink") or {})
                    .get("number_of_shards", 1))
            self.node.resize_actions.resize(
                "shrink", meta.name, target,
                {"settings": {
                    "index.number_of_shards": n,
                    # the target inherits the policy at the WARM phase
                    # with shrink already done (marker below)
                    "index.lifecycle.name":
                        meta.settings.get("index.lifecycle.name"),
                    "index.lifecycle.shrink_source": meta.name,
                    "index.rollover_date":
                        meta.settings.get("index.rollover_date"),
                    "index.lifecycle.forcemerged": True,
                }}, _log_err)
            return

    def _run_cold(self, meta, actions: Dict[str, Any], stream) -> None:
        """Cold: snapshot + mount back as a searchable-snapshot index
        replacing the original."""
        spec = actions.get("searchable_snapshot")
        if spec is None:
            # cold without searchable_snapshot: just ensure read-only
            if not meta.settings.get("index.blocks.write"):
                self._step(f"{meta.name}:cold-readonly",
                           lambda cb: self.node.client.update_settings(
                               meta.name, {"index.blocks.write": True},
                               cb))
            return
        if meta.settings.get("index.store.snapshot.repository_name"):
            return   # already mounted (this IS the restored index)
        repo = spec.get("snapshot_repository")
        if not repo:
            return
        client = self.node.client
        snap = f"ilm-{meta.name}"
        target = f"restored-{meta.name}"
        state = self.node._applied_state()
        if state.metadata.has_index(target):
            if self._copy_done(
                    state, target,
                    "index.store.snapshot.repository_name"):
                self._swap_references(meta, target, stream)
            return
        if not meta.settings.get("index.lifecycle.snapshot_started"):
            def started(_r, err):
                # "already exists" means a previous attempt succeeded but
                # its ack was lost (e.g. a timed-out round-trip): the
                # deterministic name makes the step idempotent, so adopt
                # the existing snapshot instead of wedging the phase
                if err is None or "already exists" in str(err):
                    self._step(f"{meta.name}:snapshot-mark",
                               lambda cb: client.update_settings(
                                   meta.name,
                                   {"index.lifecycle.snapshot_started":
                                    snap}, cb))
                else:
                    _log_err(None, err)
            self._step(f"{meta.name}:snapshot",
                       lambda cb: client.create_snapshot(
                           repo, snap, {"indices": meta.name}, cb),
                       on_done=started)
            return
        # snapshot taken: mount it back under the restored name, keeping
        # the policy so the delete phase still applies to the mount
        self.node.searchable_snapshots.mount(repo, snap, {
            "index": meta.name, "renamed_index": target,
            "index_settings": {
                "index.lifecycle.name":
                    meta.settings.get("index.lifecycle.name"),
                "index.rollover_date":
                    meta.settings.get("index.rollover_date"),
            }}, _log_err)

    @staticmethod
    def _copy_done(state, target: str, marker: str) -> bool:
        """has_index(target) only proves the async shrink/mount STARTED
        (create-then-copy): swapping references and deleting the source
        before the copy finishes loses data permanently. The marker
        settings key is written by the resize/mount completion callback,
        and every target primary must be active — the
        ShrunkenIndexCheckStep 'target is green' gate, re-expressed.

        A marker-less target parks the policy rather than swapping: a
        target persisted by pre-marker code is indistinguishable from a
        mid-copy one, and a wrong swap deletes the source (operators
        delete the stale target to let ILM re-run the resize)."""
        try:
            tmeta = state.metadata.index(target)
        except Exception:  # noqa: BLE001 — racing a delete: not ready
            return False
        if not tmeta.settings.get(marker):
            return False
        try:
            irt = state.routing_table.index(target)
            return all(irt.primary(s).active
                       for s in range(tmeta.number_of_shards))
        except Exception:  # noqa: BLE001 — no routing yet: not ready
            return False

    def _swap_references(self, old_meta, target: str, stream) -> None:
        """The transformed index replaces the original in its data stream
        or aliases, then the original is deleted (ShrinkSetAliasStep /
        SwapAliasesAndDeleteSourceIndexStep)."""
        client = self.node.client
        if stream is not None:
            ds_name = stream[0]
            state = self.node._applied_state()
            ds = state.metadata.data_streams.get(ds_name)
            if ds is not None:
                from elasticsearch_tpu.action.admin import PUT_CUSTOM
                indices = [target if n == old_meta.name else n
                           for n in ds.get("indices", [])]

                def then_delete(_r, err):
                    if err is None:
                        client.delete_index(old_meta.name, _log_err)
                    else:
                        _log_err(None, err)
                self.node.master_client.execute(PUT_CUSTOM, {
                    "section": "data_streams", "name": ds_name,
                    "body": {**ds, "indices": indices}}, then_delete)
                return
        aliases = list(old_meta.aliases)
        if aliases:
            actions = [{"add": {"index": target, "alias": a}}
                       for a in aliases]

            def then_delete(_r, err):
                if err is None:
                    client.delete_index(old_meta.name, _log_err)
                else:
                    _log_err(None, err)
            client.update_aliases(actions, then_delete)
            return
        client.delete_index(old_meta.name, _log_err)


def _log_err(_resp: Optional[Dict[str, Any]], err: Optional[Exception]
             ) -> None:
    if err is not None:
        logger.warning("ilm action failed: %s", err)
