"""Index lifecycle management (ILM-lite): hot -> rollover, then delete.

Reference: x-pack/plugin/ilm/.../IndexLifecycleService.java:53 — a
master-side periodic service that walks indices carrying an
``index.lifecycle.name`` setting and advances them through their policy's
phases. This build implements the two phases that cover the dominant
time-series workflow:

  hot:    {actions: {rollover: {max_age, max_docs}}}  — roll the write
          alias (``index.lifecycle.rollover_alias``) when a condition
          trips; the rollover API applies matching index templates to the
          new index, so the series keeps its mappings.
  delete: {min_age: "30d", ...}                       — delete an index
          once it has been rolled over (or created) ``min_age`` ago.

The loop only acts while this node is the elected master (the reference
gates on the same condition), and every action goes through the normal
master APIs — ILM is policy over the existing primitives, not a second
control plane.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from elasticsearch_tpu.utils.settings import parse_time_to_seconds

logger = logging.getLogger(__name__)

POLL_INTERVAL_SETTING = "indices.lifecycle.poll_interval"
DEFAULT_POLL_INTERVAL = 10.0


class IndexLifecycleService:
    def __init__(self, node) -> None:
        self.node = node
        self._running = False
        self._timer = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._schedule()

    def stop(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()

    def _poll_interval(self) -> float:
        state = self.node._applied_state()
        raw = state.metadata.persistent_settings.get(
            POLL_INTERVAL_SETTING, DEFAULT_POLL_INTERVAL)
        try:
            return max(0.5, parse_time_to_seconds(raw))
        except (TypeError, ValueError):
            return DEFAULT_POLL_INTERVAL

    def _schedule(self) -> None:
        if not self._running:
            return
        self._timer = self.node.scheduler.schedule(
            self._poll_interval(), self._tick)

    # -- the loop --------------------------------------------------------

    def _tick(self) -> None:
        if not self._running:
            return
        try:
            if self.node.coordinator.mode == "LEADER":
                self.run_once()
        except Exception:  # noqa: BLE001 — the loop must survive anything
            logger.exception("ilm tick failed")
        self._schedule()

    def run_once(self) -> None:
        """One pass over managed indices (triggerPolicies analog). Public
        so tests and an explicit API can step the lifecycle without
        waiting for the poll timer."""
        state = self.node._applied_state()
        now_ms = self.node.scheduler.wall_now() * 1000
        for meta in list(state.metadata.indices.values()):
            policy_name = meta.settings.get("index.lifecycle.name")
            if not policy_name:
                continue
            policy = state.metadata.ilm_policies.get(policy_name)
            if not policy:
                continue
            phases = policy.get("phases") or {}
            try:
                self._advance(meta, phases, now_ms)
            except Exception:  # noqa: BLE001 — one index must not stall ILM
                logger.exception("ilm advance failed for [%s]", meta.name)

    def _advance(self, meta, phases: Dict[str, Any], now_ms: float) -> None:
        rolled_ms = meta.settings.get("index.rollover_date")
        delete_phase = phases.get("delete") or {}
        hot = (phases.get("hot") or {}).get("actions") or {}
        rollover = hot.get("rollover")

        # delete-phase age origin: the rollover when one happened; for a
        # policy WITHOUT a rollover action, the creation date — an index
        # that is still this series' write target (rollover pending) is
        # never deleted out from under the writers
        origin_ms = None
        if rolled_ms is not None:
            origin_ms = int(rolled_ms)
        elif rollover is None:
            origin_ms = int(meta.settings.get("index.creation_date", 0)
                            or 0) or None
        if delete_phase and origin_ms is not None:
            min_age_s = parse_time_to_seconds(
                delete_phase.get("min_age", 0))
            if now_ms - origin_ms >= min_age_s * 1000:
                logger.info("ilm: deleting [%s] (delete phase)", meta.name)
                self.node.client.delete_index(meta.name, _log_err)
            return

        alias = meta.settings.get("index.lifecycle.rollover_alias")
        if rollover is not None and alias and alias in meta.aliases:
            self.node.client.rollover(
                alias, {"conditions": dict(rollover)}, _log_err)


def _log_err(_resp: Optional[Dict[str, Any]], err: Optional[Exception]
             ) -> None:
    if err is not None:
        logger.warning("ilm action failed: %s", err)
