from elasticsearch_tpu.parallel.mesh import make_mesh, replicated, shard_spec
from elasticsearch_tpu.parallel.sharded_search import (
    ShardedTextIndex,
    ShardedVectorIndex,
    make_sharded_bm25,
    make_sharded_hybrid,
    make_sharded_knn,
)

__all__ = [
    "ShardedTextIndex",
    "ShardedVectorIndex",
    "make_mesh",
    "make_sharded_bm25",
    "make_sharded_hybrid",
    "make_sharded_knn",
    "replicated",
    "shard_spec",
]
