from elasticsearch_tpu.parallel.mesh import make_mesh, replicated, shard_spec
from elasticsearch_tpu.parallel.sharded_search import (
    ShardedTextIndex,
    ShardedVectorIndex,
    make_sharded_bm25,
    make_sharded_bm25_batch,
    make_sharded_hybrid,
    make_sharded_knn,
    to_original_ids,
)

__all__ = [
    "ShardedTextIndex",
    "ShardedVectorIndex",
    "make_mesh",
    "make_sharded_bm25",
    "make_sharded_bm25_batch",
    "make_sharded_hybrid",
    "make_sharded_knn",
    "replicated",
    "shard_spec",
    "to_original_ids",
]
