"""Device mesh construction.

The data-plane analog of the reference's node topology: an index's shards map
onto the ``shard`` mesh axis (each device slice holds a doc partition, like
an ES shard on a data node), while the ``dp`` axis replicates the corpus for
query-batch throughput (like ES replicas serving reads,
README.asciidoc:13). Collectives ride ICI inside the mesh — the data-plane
half of the two-plane split (SURVEY.md §5.8 TPU-native equivalent).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_shards: Optional[int] = None, n_dp: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create a (dp, shard) mesh. Defaults to all devices on the shard axis."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards is None:
        n_shards = len(devices) // n_dp
    if n_dp * n_shards != len(devices):
        raise ValueError(
            f"dp({n_dp}) x shard({n_shards}) != device count {len(devices)}")
    arr = np.asarray(devices).reshape(n_dp, n_shards)
    return Mesh(arr, ("dp", "shard"))


def shard_spec(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
