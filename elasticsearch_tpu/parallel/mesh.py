"""Device mesh construction + the mesh-sharded plane's SPMD kernels.

The data-plane analog of the reference's node topology: an index's shards map
onto the ``shard`` mesh axis (each device slice holds a doc partition, like
an ES shard on a data node), while the ``dp`` axis replicates the corpus for
query-batch throughput (like ES replicas serving reads,
README.asciidoc:13). Collectives ride ICI inside the mesh — the data-plane
half of the two-plane split (SURVEY.md §5.8 TPU-native equivalent).

The second half of this module is the serving tier's kernel factories
(ROADMAP item 2): shard_map programs over the **mesh-sharded plane**
(ops/device_segment.py MeshPlaneRegistry) — each co-located ES shard's
packed plane occupies one slot of a ``[S, ...]`` stack laid out with
``NamedSharding`` over the ``shard`` mesh axis (model parallel), the
micro-batched query stack rides ``dp``, and ONE compiled program scores
every (shard, query) pair with each slot's arithmetic identical to the
single-shard plane kernels (ops/bm25.py `_bm25_flat_kernel`,
ops/knn.py `_batch_scores`, ops/sparse.py) so mesh residency can never
change a served result. Per-shard top-k comes back stitched along the
shard axis; the host-side demux and coordinator merge stay unchanged.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticsearch_tpu.search.device_profile import profiled_callable


def make_mesh(n_shards: Optional[int] = None, n_dp: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create a (dp, shard) mesh. Defaults to all devices on the shard axis."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards is None:
        n_shards = len(devices) // n_dp
    if n_dp * n_shards != len(devices):
        raise ValueError(
            f"dp({n_dp}) x shard({n_shards}) != device count {len(devices)}")
    arr = np.asarray(devices).reshape(n_dp, n_shards)
    return Mesh(arr, ("dp", "shard"))


def shard_spec(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# serving-tier mesh layout (mesh-sharded plane)
# ---------------------------------------------------------------------------

try:
    from jax import shard_map
except ImportError:   # pre-0.5 jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_legacy
    from functools import wraps as _wraps

    @_wraps(_shard_map_legacy)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(*args, **kwargs)


_MESH_CACHE: Dict[Tuple, Mesh] = {}


# ---------------------------------------------------------------------------
# multi-host topology (search.mesh.hosts)
# ---------------------------------------------------------------------------

class HostTopology:
    """N hosts x M devices per host — the ``num_nodes`` /
    ``gpus_per_node`` shape multi-process SPMD deployments pin
    explicitly. Hosts partition the device axis CONTIGUOUSLY (device d
    lives on host d // devices_per_host), the standard process-major
    device order of multi-process jax, so a ``(dp, shard)`` mesh over
    the first ``dp*d_used`` devices spans hosts 0..ceil(dp*d_used/M)-1
    and each plane slot has a well-defined serving host."""

    __slots__ = ("n_hosts", "devices_per_host", "spec")

    def __init__(self, n_hosts: int, devices_per_host: int,
                 spec: str = ""):
        self.n_hosts = int(n_hosts)
        self.devices_per_host = int(devices_per_host)
        self.spec = spec or f"{n_hosts}x{devices_per_host}"

    @property
    def n_devices(self) -> int:
        return self.n_hosts * self.devices_per_host

    def host_of_device(self, device_index: int) -> int:
        return min(device_index // self.devices_per_host,
                   self.n_hosts - 1)

    def __eq__(self, other) -> bool:
        return (isinstance(other, HostTopology)
                and self.n_hosts == other.n_hosts
                and self.devices_per_host == other.devices_per_host)

    def __hash__(self) -> int:
        return hash((self.n_hosts, self.devices_per_host))

    def __repr__(self) -> str:
        return (f"HostTopology({self.n_hosts}x{self.devices_per_host},"
                f" spec={self.spec!r})")


def parse_host_topology(spec: str, total: Optional[int] = None
                        ) -> Optional[HostTopology]:
    """``search.mesh.hosts`` -> HostTopology. "" = single-host (None);
    "N" = N equal hosts over the visible devices; "NxM" = N hosts x M
    devices per host. Raises ValueError when the spec asks for more
    devices than the backend exposes — a misdeclared fleet must fail
    loudly at configure time, not mis-shard at dispatch time."""
    spec = (spec or "").strip()
    if not spec:
        return None
    if total is None:
        total = len(jax.devices())
    if "x" in spec:
        hosts_s, _, per_s = spec.partition("x")
        n_hosts, per = int(hosts_s), int(per_s)
    else:
        n_hosts, per = int(spec), 0
    if n_hosts < 1:
        raise ValueError(
            f"search.mesh.hosts [{spec}]: host count must be >= 1")
    if per == 0:
        if n_hosts > total:
            raise ValueError(
                f"search.mesh.hosts [{spec}]: {n_hosts} hosts over "
                f"{total} visible devices")
        per = total // n_hosts
    if per < 1 or n_hosts * per > total:
        raise ValueError(
            f"search.mesh.hosts [{spec}]: {n_hosts}x{per} devices "
            f"exceed the {total} visible")
    return HostTopology(n_hosts, per, spec)


def mesh_member_hosts(topo: HostTopology, dp: int, d_used: int
                      ) -> Tuple[int, ...]:
    """Hosts whose devices participate in a (dp, d_used) mesh — the
    membership the executor's liveness checks (and the typed
    ``mesh_host_lost`` fallback) are defined over."""
    return tuple(sorted({topo.host_of_device(d)
                         for d in range(dp * d_used)}))


def slot_host(topo: HostTopology, slot: int, slots_per_device: int,
              ) -> int:
    """Primary host serving a plane slot: slots partition contiguously
    over the shard-axis device columns, and dp row 0 of column j is
    global device j."""
    return topo.host_of_device(slot // max(1, slots_per_device))


# The process's host-partition backend: maps cluster nodes onto virtual
# (or real) mesh hosts and answers liveness. Duck-typed protocol —
# ``topology`` (HostTopology), ``host_of_node(node_id)``,
# ``host_alive(host)``, ``nodes_on_host(host)``, ``indices_of(node_id)``
# (the member's IndicesService, for the single-process stand-in where
# one process holds every host's devices), ``pressure_snapshot(node_id)``.
# testing.VirtualHostBackend registers here; a real multi-process
# runtime would install its own.
_HOST_BACKEND = None


def set_host_backend(backend) -> None:
    global _HOST_BACKEND
    _HOST_BACKEND = backend


def host_backend():
    return _HOST_BACKEND


def mesh_ready() -> bool:
    """True when a jax backend is ALREADY initialized — mesh layout must
    observe devices, never pay (or hang on) first-init inside a search
    (the same never-pay guard as parallel/mesh_plane.py and monitor)."""
    import sys
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return False
    try:
        from jax._src import xla_bridge as _xb
        return bool(_xb.backends_are_initialized())
    except Exception:  # noqa: BLE001 — private API moved: assume the
        return True    # pre-guard behavior (devices() below inits)


def mesh_layout(n_shards: int, dp: int = 1, max_devices: int = 0,
                hosts: Optional[HostTopology] = None
                ) -> Tuple[Mesh, int, int]:
    """(mesh, n_slots, slots_per_device) for ``n_shards`` mesh-served
    shards over the fleet's devices.

    One shard = one slot of the stacked plane; slots map onto a
    ``(dp, shard)`` mesh over a device SUBSET sized to the shard count
    (2 shards on an 8-chip host use 2 chips — the other 6 stay free for
    other planes), padding the slot count up to a multiple of the used
    devices when shards outnumber chips. ``max_devices`` (0 = all)
    bounds the subset — the single-device layout is the byte-identity
    baseline the golden tests pin. ``hosts`` (search.mesh.hosts) caps
    the subset at the declared fleet and makes the device order
    host-contiguous by construction, so growing the shard count walks
    the program onto additional HOSTS, not just additional chips."""
    devices = jax.devices()
    total = len(devices)
    if hosts is not None:
        total = min(total, hosts.n_devices)
    if max_devices > 0:
        total = min(total, max_devices)
    dp = max(1, min(int(dp), total))
    d_used = max(1, min(total // dp, n_shards))
    n_slots = -(-n_shards // d_used) * d_used
    key = (dp, d_used)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        arr = np.asarray(devices[: dp * d_used]).reshape(dp, d_used)
        mesh = Mesh(arr, ("dp", "shard"))
        _MESH_CACHE[key] = mesh
    return mesh, n_slots, n_slots // d_used


# ---------------------------------------------------------------------------
# mesh-sharded plane kernels (one slot = one ES shard's packed plane)
# ---------------------------------------------------------------------------

_COMPILED: Dict[Tuple, object] = {}


def mesh_bm25_flat(mesh: Mesh, n_docs_pad: int, n_q: int, k: int,
                   n_segs: int, k1: float, b: float):
    """One SPMD program over the stacked postings planes.

    fn(block_docs [S,NB,B], block_tfs [S,NB,B], doc_lens [S,N],
       flat_idx [S,DP,FB], flat_w [S,DP,FB], flat_q [S,DP,FB],
       flat_avgdl [S,DP,FB], live [S,N], seg_ids [S,N])
      -> (scores [S,DP,n_q,k], plane docs [S,DP,n_q,k],
          hits [S,DP,n_q,n_segs])

    The flat gather stacks SPLIT over the dp axis: each dp row holds
    its own ``n_q``-query slice of the fan-out's micro-batch (the
    corpus stack stays replicated per row), so added dp rows buy query
    throughput instead of re-scoring the identical stack. Each
    (slot, row) runs exactly ops/bm25.py ``bm25_flat_body`` — the SAME
    traced function `_bm25_flat_kernel` / `_bm25_flat_kernel_seg` call
    (same gather/scatter order, same f32 adds), so every query's row is
    bit-compatible with that shard's single-plane dispatch BY
    CONSTRUCTION, at any dp. Per-segment hit counts serve BOTH totals
    contracts host-side: summed for counts-then-skip, clipped per
    segment for totals-disabled."""
    from elasticsearch_tpu.ops.bm25 import bm25_flat_body
    key = ("bm25", id(mesh), n_docs_pad, n_q, k, n_segs, k1, b)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def one_slot(bd, bt, dl, fi, fw, fq, fa, lv, si):
        # fi/fw/fq/fa: [1, FB] — this device's dp row of the stack
        scores, matched = bm25_flat_body(bd, bt, fi[0], fw[0], fq[0],
                                         dl, fa[0], lv, n_docs_pad,
                                         n_q, k1=k1, b=b)
        s, d = jax.lax.top_k(scores, k)
        onehot = jax.nn.one_hot(si, n_segs, dtype=jnp.int32)
        hits = matched.astype(jnp.int32) @ onehot
        return s[None], d[None], hits[None]

    def local(bd, bt, dl, fi, fw, fq, fa, lv, si):
        return jax.vmap(one_slot)(bd, bt, dl, fi, fw, fq, fa, lv, si)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    pq = P("shard", "dp", None)
    pout = P("shard", "dp", None, None)
    fn = profiled_callable("mesh_bm25_flat", shard_map(
        local, mesh=mesh,
        in_specs=(p3, p3, p2, pq, pq, pq, pq, p2, p2),
        out_specs=(pout, pout, pout), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_bm25_coarse(mesh: Mesh, n_docs_pad: int, n_q: int, kprime: int,
                     n_segs: int, k1: float, b: float):
    """Quantized coarse tier over the stacked postings planes: one SPMD
    program whose per-slot body is EXACTLY ops/bm25.py
    ``bm25_coarse_body`` (bf16 mirror gathers, f32 accumulation), so a
    slot's coarse candidates match that shard's single-plane coarse
    dispatch by construction.

    fn(block_docs [S,NB,B], block_tfs_q [S,NB,B] bf16, doc_lens_q [S,N]
       bf16, flat_idx [S,FB], flat_w [S,FB], flat_q [S,FB],
       flat_avgdl [S,FB], live [S,N], seg_ids [S,N])
      -> (coarse scores [S,n_q,k'], cand [S,n_q,k'],
          hits [S,n_q,n_segs])"""
    from elasticsearch_tpu.ops.bm25 import bm25_coarse_body
    key = ("bm25_coarse", id(mesh), n_docs_pad, n_q, kprime, n_segs,
           k1, b)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def one_slot(bd, btq, dlq, fi, fw, fq, fa, lv, si):
        return bm25_coarse_body(bd, btq, fi, fw, fq, dlq, fa, lv, si,
                                n_docs_pad, n_q, n_segs, kprime,
                                k1=k1, b=b)

    def local(bd, btq, dlq, fi, fw, fq, fa, lv, si):
        return jax.vmap(one_slot)(bd, btq, dlq, fi, fw, fq, fa, lv, si)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    fn = profiled_callable("mesh_bm25_coarse", shard_map(
        local, mesh=mesh,
        in_specs=(p3, p3, p2, p2, p2, p2, p2, p2, p2),
        out_specs=(p3, p3, p3), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_bm25_rerank(mesh: Mesh, n_docs_pad: int, n_q: int, kprime: int,
                     k: int, n_segs: int, k1: float, b: float):
    """Exact re-rank tier over the stacked postings planes: per-slot
    body is ops/bm25.py ``bm25_rerank_body`` — the same f32 contribution
    arithmetic and linear scatter order as the exact flat kernel, into
    the compact candidate plane — so re-ranked scores are bit-compatible
    with the per-shard quantized path AND the exact path.

    fn(block_docs, block_tfs [S,NB,B] f32, flat_idx, flat_w, flat_q,
       flat_avgdl, doc_lens [S,N] f32, live [S,N], cand [S,n_q,k'],
       coarse_s [S,n_q,k'])
      -> (scores [S,n_q,k], plane docs [S,n_q,k], eps [S,n_q])"""
    from elasticsearch_tpu.ops.bm25 import bm25_rerank_body
    key = ("bm25_rerank", id(mesh), n_docs_pad, n_q, kprime, k, n_segs,
           k1, b)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def one_slot(bd, bt, fi, fw, fq, fa, dl, lv, cand, cs):
        return bm25_rerank_body(bd, bt, fi, fw, fq, dl, fa, lv, cand,
                                cs, n_docs_pad, n_q, kprime, k,
                                k1=k1, b=b)

    def local(bd, bt, fi, fw, fq, fa, dl, lv, cand, cs):
        return jax.vmap(one_slot)(bd, bt, fi, fw, fq, fa, dl, lv, cand,
                                  cs)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    fn = profiled_callable("mesh_bm25_rerank", shard_map(
        local, mesh=mesh,
        in_specs=(p3, p3, p2, p2, p2, p2, p2, p2, p3, p3),
        out_specs=(p3, p3, p2), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_sparse_coarse(mesh: Mesh, n_docs_pad: int, kprime: int):
    """Quantized coarse tier over the stacked rank_features planes;
    per-slot body is ops/sparse.py ``sparse_coarse_body``.

    fn(block_docs [S,NB,B], block_weights_q [S,NB,B] bf16, idx [S,Q,QB],
       qw [S,Q,QB], live [S,N])
      -> (coarse scores [S,Q,k'], cand [S,Q,k'], hits [S,Q])"""
    from elasticsearch_tpu.ops.sparse import sparse_coarse_body
    key = ("sparse_coarse", id(mesh), n_docs_pad, kprime)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def one_slot(bd, bwq, bi, qw, lv):
        return sparse_coarse_body(bd, bwq, bi, qw, lv, n_docs_pad,
                                  kprime)

    def local(bd, bwq, bi, qw, lv):
        return jax.vmap(one_slot)(bd, bwq, bi, qw, lv)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    fn = profiled_callable("mesh_sparse_coarse", shard_map(
        local, mesh=mesh,
        in_specs=(p3, p3, p3, p3, p2),
        out_specs=(p3, p3, p2), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_sparse_rerank(mesh: Mesh, n_docs_pad: int, kprime: int, k: int):
    """Exact re-rank tier over the stacked rank_features planes;
    per-slot body is ops/sparse.py ``sparse_rerank_body``.

    fn(block_docs, block_weights [S,NB,B] f32, idx [S,Q,QB],
       qw [S,Q,QB], live [S,N], cand [S,Q,k'], coarse_s [S,Q,k'])
      -> (scores [S,Q,k], plane docs [S,Q,k], eps [S,Q])"""
    from elasticsearch_tpu.ops.sparse import sparse_rerank_body
    key = ("sparse_rerank", id(mesh), n_docs_pad, kprime, k)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def one_slot(bd, bw, bi, qw, lv, cand, cs):
        return sparse_rerank_body(bd, bw, bi, qw, lv, cand, cs,
                                  n_docs_pad, kprime, k)

    def local(bd, bw, bi, qw, lv, cand, cs):
        return jax.vmap(one_slot)(bd, bw, bi, qw, lv, cand, cs)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    fn = profiled_callable("mesh_sparse_rerank", shard_map(
        local, mesh=mesh,
        in_specs=(p3, p3, p3, p3, p2, p3, p3),
        out_specs=(p3, p3, p2), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_knn_coarse(mesh: Mesh, kprime: int, similarity: str,
                    masked: bool):
    """Quantized int8 coarse tier over the stacked vector planes: the
    query stack rides ``dp``, the corpus the ``shard`` axis, and each
    slot runs ops/knn.py's ``_coarse_plane`` arithmetic (int8 x int8
    MXU matmul, int32 accumulate, rescale + positive-score transform).

    fn(q8 [S,N,D] int8, scales [S,N], norms [S,N], allowed [S,N],
       queries [Q,D] [, masks [S,Q,N]])
      -> (coarse scores [S,Q,k'], cand [S,Q,k'])"""
    from elasticsearch_tpu.ops.knn import _coarse_plane
    key = ("knn_coarse", id(mesh), kprime, similarity, masked)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def local(q8, sc, nr, al, q, mk=None):
        def one_slot(q8_s, sc_s, nr_s, al_s, mk_s=None):
            s = _coarse_plane(q8_s, sc_s, nr_s, q, similarity)
            ok = al_s[None, :] if mk_s is None else (al_s[None, :] & mk_s)
            s = jnp.where(ok, s, -jnp.inf)
            cs, cand = jax.lax.top_k(s, kprime)
            return cs, cand
        if mk is not None:
            return jax.vmap(one_slot)(q8, sc, nr, al, mk)
        return jax.vmap(lambda a, b_, c, d: one_slot(a, b_, c, d))(
            q8, sc, nr, al)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    pq = P("dp", None)
    pout = P("shard", "dp", None)
    if masked:
        fn = profiled_callable("mesh_knn_coarse", shard_map(
            local, mesh=mesh,
            in_specs=(p3, p2, p2, p2, pq, P("shard", "dp", None)),
            out_specs=(pout, pout), check_vma=False))
    else:
        fn = profiled_callable("mesh_knn_coarse", shard_map(
            lambda q8, sc, nr, al, q: local(q8, sc, nr, al, q),
            mesh=mesh, in_specs=(p3, p2, p2, p2, pq),
            out_specs=(pout, pout), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_knn_rerank(mesh: Mesh, k: int, similarity: str, masked: bool):
    """Exact re-rank tier over the stacked vector planes; per-slot body
    is ops/knn.py ``knn_rerank_body`` (candidate sort, exact einsum
    scores, observed-deviation eps), so re-ranked scores match the
    per-shard quantized path bit-for-bit.

    fn(matrix [S,N,D] f32, norms [S,N], allowed [S,N], queries [Q,D],
       cand [S,Q,k'], coarse_s [S,Q,k'] [, masks [S,Q,N]])
      -> (scores [S,Q,k], plane docs [S,Q,k], eps [S,Q])"""
    from elasticsearch_tpu.ops.knn import knn_rerank_body
    key = ("knn_rerank", id(mesh), k, similarity, masked)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def local(m, nr, al, q, cand, cs, mk=None):
        def one_slot(m_s, nr_s, al_s, cand_s, cs_s, mk_s=None):
            return knn_rerank_body(m_s, nr_s, al_s, q, cand_s, cs_s,
                                   mk_s, k, similarity)
        if mk is not None:
            return jax.vmap(one_slot)(m, nr, al, cand, cs, mk)
        return jax.vmap(
            lambda a, b_, c, d, e: one_slot(a, b_, c, d, e))(
            m, nr, al, cand, cs)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    pq = P("dp", None)
    pc = P("shard", "dp", None)
    pout = P("shard", "dp", None)
    if masked:
        fn = profiled_callable("mesh_knn_rerank", shard_map(
            local, mesh=mesh,
            in_specs=(p3, p2, p2, pq, pc, pc, P("shard", "dp", None)),
            out_specs=(pout, pout, P("shard", "dp")), check_vma=False))
    else:
        fn = profiled_callable("mesh_knn_rerank", shard_map(
            lambda m, nr, al, q, cand, cs: local(m, nr, al, q, cand, cs),
            mesh=mesh, in_specs=(p3, p2, p2, pq, pc, pc),
            out_specs=(pout, pout, P("shard", "dp")), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_sparse_topk(mesh: Mesh, n_docs_pad: int, k: int):
    """One SPMD program over the stacked rank_features planes.

    fn(block_docs [S,NB,B], block_weights [S,NB,B], idx [S,DP,Q,QB],
       qw [S,DP,Q,QB], live [S,N])
      -> (scores [S,DP,Q,k], plane docs [S,DP,Q,k], hits [S,DP,Q])

    The query stack SPLITS over the dp axis (each row scores its own
    Q-query slice against its corpus replica). Per (slot, row, query)
    the body is ops/sparse.py ``sparse_topk_body`` with linear scoring
    — the SAME traced function ``sparse_topk_batch`` vmaps, so a mesh
    row is bit-compatible with the single-shard batch dispatch by
    construction: same gather, same scatter-add, exact whole-shard
    counts off the score plane."""
    from elasticsearch_tpu.ops.sparse import sparse_topk_body
    key = ("sparse", id(mesh), n_docs_pad, k)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def one_slot(bd, bw, bi, qw, lv):
        def one_q(bi_q, qw_q):
            return sparse_topk_body(bd, bw, bi_q, qw_q, 1.0, 1.0, lv,
                                    n_docs_pad, k, "linear")
        ts, td, hits = jax.vmap(one_q)(bi[0], qw[0])
        return ts[None], td[None], hits[None]

    def local(bd, bw, bi, qw, lv):
        return jax.vmap(one_slot)(bd, bw, bi, qw, lv)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    pq = P("shard", "dp", None, None)
    fn = profiled_callable("mesh_sparse_topk", shard_map(
        local, mesh=mesh,
        in_specs=(p3, p3, pq, pq, p2),
        out_specs=(pq, pq, P("shard", "dp", None)), check_vma=False))
    _COMPILED[key] = fn
    return fn


def mesh_knn_topk(mesh: Mesh, k: int, similarity: str, masked: bool):
    """One SPMD program over the stacked vector planes: the query stack
    rides the ``dp`` mesh axis, the corpus the ``shard`` axis.

    fn(matrix [S,N,D], norms [S,N], allowed [S,N], queries [Q,D]
       [, masks [S,Q,N]]) -> (scores [S,Q,k], plane docs [S,Q,k])

    Scoring is ops/knn.py ``knn_topk_body`` per slot — the SAME traced
    function `knn_topk_batch` / `knn_topk_batch_masked` call (bf16
    multiply, f32 accumulate, `_coarse_similarity` transform), so each
    slot's row matches that shard's exact plane matmul by construction.
    ``allowed`` already folds live & exists (& a shared filter mask when
    every batch member carries the same filter); ``masks`` is the
    per-member stack for heterogeneous filters."""
    from elasticsearch_tpu.ops.knn import knn_topk_body
    key = ("knn", id(mesh), k, similarity, masked)
    fn = _COMPILED.get(key)
    if fn is not None:
        return fn

    def local(m, nr, al, q, mk=None):
        def one_slot(m_s, nr_s, al_s, mk_s=None):
            return knn_topk_body(m_s, nr_s, al_s, q, mk_s, k, similarity)
        if mk is not None:
            return jax.vmap(one_slot)(m, nr, al, mk)
        return jax.vmap(lambda a, c, d: one_slot(a, c, d))(m, nr, al)

    p3 = P("shard", None, None)
    p2 = P("shard", None)
    pq = P("dp", None)
    pout = P("shard", "dp", None)
    if masked:
        fn = profiled_callable("mesh_knn_topk", shard_map(
            local, mesh=mesh,
            in_specs=(p3, p2, p2, pq, P("shard", "dp", None)),
            out_specs=(pout, pout), check_vma=False))
    else:
        fn = profiled_callable("mesh_knn_topk", shard_map(
            lambda m, nr, al, q: local(m, nr, al, q), mesh=mesh,
            in_specs=(p3, p2, p2, pq),
            out_specs=(pout, pout), check_vma=False))
    _COMPILED[key] = fn
    return fn
