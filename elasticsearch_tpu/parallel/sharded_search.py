"""SPMD distributed search: one pjit program replaces scatter-gather RPC.

The reference fans a query out over shards with per-shard RPCs and merges
top-k on a coordinator (action/search/AbstractSearchAsyncAction.java:156,214;
SearchPhaseController.sortDocs:160 + TopDocs.merge). Here the whole
scatter-gather is ONE compiled program over a (dp, shard) mesh:

  local score -> local top-k -> all_gather(top-k over 'shard') -> global top-k

The all_gather moves only k (score, id) pairs per shard — the wire-efficient
merge the reference gets from query_then_fetch — but over ICI inside the
compiled program instead of TCP between processes. DFS-style global term
stats (search/dfs/DfsPhase.java:43) become a host-side df sum (or a psum)
before weight computation.

Layouts (S = number of shards on the mesh axis):
  postings: block_docs/tfs [S, NB, BLOCK] sharded on axis 0; local doc ids
  vectors:  matrix [S, N, D] sharded on axis 0
  queries:  [B, ...] sharded on 'dp'
Docs are placed round-robin (doc g -> shard g % S, local g // S) so load
balances regardless of pow2 padding (the murmur3-routing analog for a
monotonically-assigned corpus). Inside a program a doc is addressed by its
mesh-global id shard_idx * N_per_shard + local. The batched BM25 program
emits ORIGINAL corpus ids directly (tie-break by ascending original id is
baked into its device-side lexsort); the single-query and kNN paths still
return mesh-global ids that search APIs translate via to_original_ids.
"""

from __future__ import annotations

from collections import Counter
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:   # pre-0.5 jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_legacy
    from functools import wraps as _wraps

    @_wraps(_shard_map_legacy)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(*args, **kwargs)

from elasticsearch_tpu.index.segment import BLOCK, next_pow2
from elasticsearch_tpu.search.device_profile import profiled_callable
from elasticsearch_tpu.ops.bm25 import (
    DEFAULT_B, DEFAULT_K1, P1_BUCKET, QueryPlan, TermCellIndex,
    build_query_plan, idf as idf_fn, qb_bucket,
)


# ---------------------------------------------------------------------------
# shared local scoring bodies (used by the standalone AND hybrid programs —
# one definition so kernel fixes can't drift between them)
# ---------------------------------------------------------------------------

def _local_bm25_scores(block_docs, block_tfs, doc_lens, avgdl,
                       block_idx, block_w, n_per_shard: int,
                       k1: float, b: float):
    """Per-shard BM25: gather query blocks, score, scatter-add into doc
    space. Returns dense scores [n_per_shard] with -inf for non-matches."""
    docs = block_docs[block_idx]              # [QB, BLOCK]
    tfs = block_tfs[block_idx]
    valid = docs >= 0
    safe = jnp.where(valid, docs, 0)
    dl = doc_lens[safe]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    contrib = block_w[:, None] * tfs * (k1 + 1.0) / (tfs + norm)
    contrib = jnp.where(valid, contrib, 0.0)
    scores = jnp.zeros((n_per_shard,), jnp.float32)
    scores = scores.at[safe.reshape(-1)].add(contrib.reshape(-1), mode="drop")
    return jnp.where(scores > 0, scores, -jnp.inf)


def _local_knn_scores(m, norms, valid, queries, similarity: str):
    """Per-shard kNN: MXU matmul + similarity transform.
    queries [B, D] -> scores [B, N] with -inf for missing vectors.

    cosine/dot run in bf16 (the dot IS the score — bf16 relative error is
    fine). l2 runs the dot in f32: the ||m||^2 + ||q||^2 - 2<q,m>
    cancellation turns bf16 rounding into large absolute error exactly at
    small distances, where ranking is decided.
    """
    dot_dtype = jnp.float32 if similarity == "l2_norm" else jnp.bfloat16
    dots = jax.lax.dot_general(
        queries.astype(dot_dtype), m.astype(dot_dtype),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [B, N]
    if similarity == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True) + 1e-30
        scores = (1.0 + dots / (norms[None, :] * qn + 1e-30)) / 2.0
    elif similarity == "dot_product":
        scores = 0.5 + dots / 2.0
    elif similarity == "l2_norm":
        q2 = jnp.sum(queries * queries, axis=1, keepdims=True)
        d2 = jnp.maximum(norms[None, :] ** 2 + q2 - 2.0 * dots, 0.0)
        scores = 1.0 / (1.0 + jnp.sqrt(d2))
    else:
        raise ValueError(f"unknown similarity {similarity!r}")
    return jnp.where(valid[None, :], scores, -jnp.inf)


def to_original_ids(ids, n_shards: int, n_per_shard: int):
    """Mesh-global ids (shard*per + local) -> original corpus ids under the
    round-robin placement; -1 (empty slot) passes through."""
    ids = np.asarray(ids)
    return np.where(ids >= 0,
                    (ids % n_per_shard) * n_shards + ids // n_per_shard,
                    -1)


def _topk_padded(scores, k: int):
    """top_k that clamps to the axis size and pads back out to k with
    (-inf, -1) — ES clamps size to available hits instead of erroring."""
    n = scores.shape[-1]
    kk = min(k, n)
    s, i = jax.lax.top_k(scores, kk)
    if kk < k:
        pad = [(0, 0)] * (scores.ndim - 1) + [(0, k - kk)]
        s = jnp.pad(s, pad, constant_values=-jnp.inf)
        i = jnp.pad(i, pad, constant_values=-1)
    return s, i


def _global_topk_1d(scores, k: int, n_per_shard: int):
    """Per-shard [N] scores -> global (scores [k], ids [k]) via all_gather
    over 'shard'. Ids of -inf slots are masked to -1 so downstream fusion
    can't credit phantom/padding docs."""
    ls, li = _topk_padded(scores, k)
    shard_idx = jax.lax.axis_index("shard")
    gi = jnp.where(jnp.isfinite(ls), li + shard_idx * n_per_shard, -1)
    all_s = jax.lax.all_gather(ls, "shard", axis=0).reshape(-1)
    all_i = jax.lax.all_gather(gi, "shard", axis=0).reshape(-1)
    gs, pos = jax.lax.top_k(all_s, k)
    return gs, all_i[pos]


# ---------------------------------------------------------------------------
# sharded kNN
# ---------------------------------------------------------------------------

def make_sharded_knn(mesh: Mesh, n_per_shard: int, dims: int, k: int,
                     similarity: str = "cosine"):
    """Compile the distributed kNN program for the given shapes.

    Returns fn(matrix [S,N,D], norms [S,N], valid [S,N], queries [B,D])
    -> (scores [B,k], global_ids [B,k]).
    """

    def local_search(matrix, norms, valid, queries):
        # per-device blocks: matrix [1, N, D], queries [B_local, D]
        scores = _local_knn_scores(matrix[0], norms[0], valid[0], queries,
                                   similarity)
        local_s, local_i = _topk_padded(scores, k)          # [B, k]
        shard_idx = jax.lax.axis_index("shard")
        global_i = jnp.where(jnp.isfinite(local_s),
                             local_i + shard_idx * n_per_shard, -1)
        # gather each shard's top-k, then reduce to the global top-k
        all_s = jax.lax.all_gather(local_s, "shard", axis=0)   # [S, B, k]
        all_i = jax.lax.all_gather(global_i, "shard", axis=0)
        S = all_s.shape[0]
        B = all_s.shape[1]
        flat_s = jnp.transpose(all_s, (1, 0, 2)).reshape(B, S * k)
        flat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(B, S * k)
        g_s, pos = jax.lax.top_k(flat_s, k)
        g_i = jnp.take_along_axis(flat_i, pos, axis=1)
        return g_s, g_i

    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None), P("shard", None),
                  P("dp", None)),
        out_specs=(P("dp", None), P("dp", None)),
        check_vma=False,
    )
    return profiled_callable("sharded_knn", fn)


class ShardedVectorIndex:
    """Corpus of vectors partitioned over the mesh 'shard' axis."""

    def __init__(self, mesh: Mesh, vectors: np.ndarray,
                 similarity: str = "cosine",
                 n_per_shard: Optional[int] = None):
        self.mesh = mesh
        n_shards = mesh.shape["shard"]
        n, d = vectors.shape
        self.n_docs = n
        per = n_per_shard or next_pow2(max(-(-n // n_shards), 1), minimum=8)
        self.n_per_shard = per
        mat = np.zeros((n_shards, per, d), np.float32)
        valid = np.zeros((n_shards, per), bool)
        for s in range(n_shards):
            orig = np.arange(s, n, n_shards)     # round-robin placement
            mat[s, : len(orig)] = vectors[orig]
            valid[s, : len(orig)] = True
        norms = np.linalg.norm(mat, axis=2).astype(np.float32)
        self.matrix = jax.device_put(mat, NamedSharding(mesh, P("shard", None, None)))
        self.norms = jax.device_put(norms, NamedSharding(mesh, P("shard", None)))
        self.valid = jax.device_put(valid, NamedSharding(mesh, P("shard", None)))
        self.similarity = similarity
        self._compiled: Dict[int, callable] = {}

    def search(self, queries: np.ndarray, k: int):
        """queries [B, D] -> (scores [B, k], global doc ids [B, k]).

        B need not divide the dp axis: the batch is padded to a multiple of
        n_dp for the sharded device_put and the pad rows dropped on return.
        """
        fn = self._compiled.get(k)
        if fn is None:
            fn = make_sharded_knn(self.mesh, self.n_per_shard,
                                  queries.shape[1], k, self.similarity)
            self._compiled[k] = fn
        b = queries.shape[0]
        n_dp = self.mesh.shape["dp"]
        b_pad = -(-b // n_dp) * n_dp
        q = np.zeros((b_pad, queries.shape[1]), np.float32)
        q[:b] = queries
        q = jax.device_put(jnp.asarray(q),
                           NamedSharding(self.mesh, P("dp", None)))
        s, i = fn(self.matrix, self.norms, self.valid, q)
        return s[:b], to_original_ids(i[:b], self.mesh.shape["shard"],
                                      self.n_per_shard)


# ---------------------------------------------------------------------------
# sharded BM25
# ---------------------------------------------------------------------------

def make_sharded_bm25(mesh: Mesh, n_per_shard: int, k: int,
                      k1: float = DEFAULT_K1, b: float = DEFAULT_B):
    """Compile the distributed BM25 program.

    fn(block_docs [S,NB,BLOCK], block_tfs [S,NB,BLOCK], doc_lens [S,N],
       avgdl scalar, block_idx [S,QB], block_w [S,QB])
    -> (scores [k], global ids [k])  (single query; batch via host loop or vmap)
    """

    def local_search(block_docs, block_tfs, doc_lens, avgdl, block_idx, block_w):
        scores = _local_bm25_scores(block_docs[0], block_tfs[0], doc_lens[0],
                                    avgdl, block_idx[0], block_w[0],
                                    n_per_shard, k1, b)
        return _global_topk_1d(scores, k, n_per_shard)

    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None),
                  P("shard", None), P(), P("shard", None), P("shard", None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return profiled_callable("sharded_bm25", fn)


def make_sharded_bm25_batch(mesh: Mesh, n_per_shard: int, k: int,
                            k1: float = DEFAULT_K1, b: float = DEFAULT_B,
                            counted: bool = False):
    """Compile the BATCHED distributed BM25 program: Q queries per dispatch
    (the knn batched-program analog — BM25 was previously dispatch-bound at
    one compiled call per query).

    fn(block_docs [S,NB,BLOCK], block_tfs [S,NB,BLOCK], doc_lens [S,N],
       avgdl scalar, block_idx [S,Q,QB], block_w [S,Q,QB])
    -> (scores [Q,k], ORIGINAL corpus doc ids [Q,k])

    Ties at equal score break by ascending original id — the same
    (shard, segment, doc) order the host-RPC coordinator merge uses
    (SearchPhaseController.java:160 analog), so both data planes return
    identical hit sets at tie boundaries.
    """

    def local_search(block_docs, block_tfs, doc_lens, avgdl,
                     block_idx, block_w):
        def one(bi, bw):
            return _local_bm25_scores(block_docs[0], block_tfs[0],
                                      doc_lens[0], avgdl, bi, bw,
                                      n_per_shard, k1, b)
        scores = jax.vmap(one)(block_idx[0], block_w[0])       # [Q, N]
        local_s, local_i = _topk_padded(scores, k)             # [Q, k]
        shard_idx = jax.lax.axis_index("shard")
        # psum(1) == axis size on every jax vintage (lax.axis_size is
        # newer than the floor this build supports)
        n_shards = jax.lax.psum(1, "shard")
        # round-robin placement: original id = local * S + shard; empty
        # slots get an out-of-range id so the lexsort puts them last
        orig_i = jnp.where(jnp.isfinite(local_s),
                           local_i * n_shards + shard_idx,
                           n_shards * n_per_shard)
        all_s = jax.lax.all_gather(local_s, "shard", axis=0)   # [S, Q, k]
        all_i = jax.lax.all_gather(orig_i, "shard", axis=0)
        S, Q = all_s.shape[0], all_s.shape[1]
        flat_s = jnp.transpose(all_s, (1, 0, 2)).reshape(Q, S * k)
        flat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(Q, S * k)
        # lexicographic (descending score, ascending original id)
        srt_neg, srt_i = jax.lax.sort((-flat_s, flat_i), dimension=1,
                                      num_keys=2)
        g_s = -srt_neg[:, :k]
        g_i = jnp.where(jnp.isfinite(g_s), srt_i[:, :k], -1)
        if counted:
            # matched docs across the mesh: local finite-score count,
            # summed over the shard axis (counts-then-skip's observation
            # — exact when every block was gathered, else a lower bound)
            local_hits = jnp.sum(jnp.isfinite(scores), axis=1,
                                 dtype=jnp.int32)               # [Q]
            hits = jax.lax.psum(local_hits, "shard")
            return g_s, g_i, hits
        return g_s, g_i

    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None),
                  P("shard", None), P(), P("shard", None, None),
                  P("shard", None, None)),
        out_specs=(P(), P(), P()) if counted else (P(), P()),
        check_vma=False,
    )
    return profiled_callable("sharded_bm25_batch", fn)


class ShardedTextIndex:
    """Text corpus partitioned by doc over the mesh 'shard' axis, with
    corpus-GLOBAL document frequencies so every shard scores with the same
    idf.

    The reference routes docs to shards by murmur3 and each shard builds its
    own Lucene index; idf consistency comes from the optional DFS phase. Here
    per-shard dfs are summed host-side at build time for exact global idf,
    and the per-query host prep emits one gather list per shard.
    """

    def __init__(self, mesh: Mesh, docs_terms: Sequence[Sequence[str]],
                 qb_bucket_min: int = 8):
        n_shards = mesh.shape["shard"]
        n = len(docs_terms)
        per = next_pow2(max(-(-n // n_shards), 1), minimum=BLOCK)

        # per-shard postings: term -> {local_doc: tf}
        shard_postings: List[Dict[str, Dict[int, int]]] = [dict() for _ in range(n_shards)]
        doc_lens = np.zeros((n_shards, per), np.float32)
        df: Dict[str, int] = {}
        for g, terms in enumerate(docs_terms):
            s, local = g % n_shards, g // n_shards   # round-robin placement
            doc_lens[s, local] = len(terms)
            seen = set()
            for t in terms:
                shard_postings[s].setdefault(t, {})
                shard_postings[s][t][local] = shard_postings[s][t].get(local, 0) + 1
                if t not in seen:
                    df[t] = df.get(t, 0) + 1
                    seen.add(t)
        self._finish_init(mesh, n, per, shard_postings, doc_lens, df,
                          qb_bucket_min)

    @classmethod
    def from_postings_sources(cls, mesh: Mesh, sources,
                              qb_bucket_min: int = 8) -> "ShardedTextIndex":
        """Build directly from already-indexed postings instead of raw docs.

        ``sources``: ordered [(postings_field_or_None, live_mask, n_docs)]
        — one entry per source segment, concatenated into a global doc
        space (global id g = segment base + local doc). Tombstoned docs are
        dropped at build time (the mesh copy is born merged), so df and
        doc_lens reflect live docs only."""
        obj = cls.__new__(cls)
        n_shards = mesh.shape["shard"]
        n = sum(n_docs for _, _, n_docs in sources)
        per = next_pow2(max(-(-n // n_shards), 1), minimum=BLOCK)
        shard_postings: List[Dict[str, Dict[int, int]]] = \
            [dict() for _ in range(n_shards)]
        doc_lens = np.zeros((n_shards, per), np.float32)
        df: Dict[str, int] = {}
        base = 0
        for pf, live, n_docs in sources:
            if pf is None or n_docs == 0:
                base += n_docs
                continue
            live = np.asarray(live[:n_docs], bool)
            g = base + np.arange(n_docs)
            s_arr, local_arr = g % n_shards, g // n_shards
            lens = np.where(live, pf.doc_lens[:n_docs], 0.0)
            doc_lens[s_arr, local_arr] = lens
            for term in pf.terms:
                docs, tfs = pf.postings_for(term)
                keep = live[docs]
                docs, tfs = docs[keep], tfs[keep]
                if len(docs) == 0:
                    continue
                df[term] = df.get(term, 0) + len(docs)
                gg = base + docs
                for gdoc, tf in zip(gg.tolist(), tfs.tolist()):
                    sp = shard_postings[gdoc % n_shards].setdefault(term, {})
                    sp[gdoc // n_shards] = int(tf)
            base += n_docs
        obj._finish_init(mesh, n, per, shard_postings, doc_lens, df,
                         qb_bucket_min)
        return obj

    def _finish_init(self, mesh: Mesh, n: int, per: int,
                     shard_postings: List[Dict[str, Dict[int, int]]],
                     doc_lens: np.ndarray, df: Dict[str, int],
                     qb_bucket_min: int) -> None:
        self.mesh = mesh
        n_shards = mesh.shape["shard"]
        self.n_shards = n_shards
        self.n_docs = n
        self.n_per_shard = per
        self.df = df

        # pack per-shard blocks; all shards padded to the same block count
        packed = []
        for s in range(n_shards):
            blocks_d, blocks_t = [], []
            index: Dict[str, Tuple[int, int]] = {}
            for t, posting in shard_postings[s].items():
                entries = sorted(posting.items())
                nb = max(1, -(-len(entries) // BLOCK))
                index[t] = (len(blocks_d), nb)
                d = np.full(nb * BLOCK, -1, np.int32)
                f = np.zeros(nb * BLOCK, np.float32)
                d[: len(entries)] = [e[0] for e in entries]
                f[: len(entries)] = [e[1] for e in entries]
                blocks_d.extend(d.reshape(nb, BLOCK))
                blocks_t.extend(f.reshape(nb, BLOCK))
            if not blocks_d:
                blocks_d = [np.full(BLOCK, -1, np.int32).reshape(1, BLOCK)[0]]
                blocks_t = [np.zeros(BLOCK, np.float32)]
            packed.append((np.stack(blocks_d), np.stack(blocks_t), index))

        nb_max = next_pow2(max(p[0].shape[0] for p in packed))
        bd = np.full((n_shards, nb_max, BLOCK), -1, np.int32)
        bt = np.zeros((n_shards, nb_max, BLOCK), np.float32)
        self.term_index: List[Dict[str, Tuple[int, int]]] = []
        for s, (d, t, index) in enumerate(packed):
            bd[s, : d.shape[0]] = d
            bt[s, : t.shape[0]] = t
            self.term_index.append(index)

        self.block_docs = jax.device_put(bd, NamedSharding(mesh, P("shard", None, None)))
        self.block_tfs = jax.device_put(bt, NamedSharding(mesh, P("shard", None, None)))
        self.doc_lens = jax.device_put(doc_lens, NamedSharding(mesh, P("shard", None)))
        total_len = float(doc_lens.sum())
        self.avgdl = total_len / max(1, n)
        # per-shard block-max impact bounds for WAND pruning (host-side,
        # default similarity params — PostingsField.block_max_impact analog)
        self._impacts = np.zeros((n_shards, nb_max), np.float32)
        for s in range(n_shards):
            v = bd[s] >= 0
            dl = doc_lens[s][np.where(v, bd[s], 0)]
            norm = DEFAULT_K1 * (1.0 - DEFAULT_B + DEFAULT_B * dl /
                                 max(self.avgdl, 1e-9))
            x = np.where(v, bt[s] / np.maximum(bt[s] + norm, 1e-9), 0.0)
            self._impacts[s] = x.max(axis=1)
        self._cell_indexes = [
            TermCellIndex(bd[s], bt[s], doc_lens[s], self.avgdl)
            for s in range(n_shards)]
        self.qb_bucket_min = qb_bucket_min
        self._compiled: Dict[Tuple[int, int], callable] = {}
        self._compiled_batch: Dict[int, callable] = {}
        self.last_prune_stats: Tuple[int, int] = (0, 0)

    def prep_query(self, terms: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Host prep: per-shard gather indices + per-block weights from
        GLOBAL df (exact idf, no DFS round needed)."""
        per_shard_idx: List[List[int]] = [[] for _ in range(self.n_shards)]
        per_shard_w: List[List[float]] = [[] for _ in range(self.n_shards)]
        # dedupe but keep ES match semantics: a repeated query term is a
        # repeated bool clause, so its weight scales with multiplicity (qtf)
        # — same scoring as Bm25Executor.query_weights on the segment path.
        # Entries may be (term, boost) pairs (bool/should clause boosts).
        counts = Counter(terms)
        for t, qtf in counts.items():
            boost = 1.0
            if isinstance(t, tuple):
                t, boost = t
            df = self.df.get(t, 0)
            if df <= 0:
                continue
            w = idf_fn(self.n_docs, df) * qtf * float(boost)
            for s in range(self.n_shards):
                entry = self.term_index[s].get(t)
                if entry is None:
                    continue
                start, count = entry
                for b_ in range(start, start + count):
                    per_shard_idx[s].append(b_)
                    per_shard_w[s].append(w)
        qb = max(max((len(x) for x in per_shard_idx), default=1), 1)
        qb_pad = next_pow2(qb, minimum=self.qb_bucket_min)
        idx = np.zeros((self.n_shards, qb_pad), np.int32)
        w = np.zeros((self.n_shards, qb_pad), np.float32)
        for s in range(self.n_shards):
            idx[s, : len(per_shard_idx[s])] = per_shard_idx[s]
            w[s, : len(per_shard_w[s])] = per_shard_w[s]
        return idx, w

    def search(self, terms: Sequence[str], k: int):
        idx, w = self.prep_query(terms)
        key = (k, idx.shape[1])
        fn = self._compiled.get(key)
        if fn is None:
            fn = make_sharded_bm25(self.mesh, self.n_per_shard, k)
            self._compiled[key] = fn
        sh = NamedSharding(self.mesh, P("shard", None))
        s, i = fn(self.block_docs, self.block_tfs, self.doc_lens,
                  jnp.float32(self.avgdl),
                  jax.device_put(idx, sh), jax.device_put(w, sh))
        return s, to_original_ids(i, self.n_shards, self.n_per_shard)

    # -- batched + block-max-pruned path ------------------------------------

    def _plans(self, terms: Sequence[str]) -> List[QueryPlan]:
        """One WAND block plan per shard for one query (global idf)."""
        tw = []
        # dedupe keeping order, weight scaled by query-term multiplicity
        # (qtf) to match the repeated-bool-clause semantics of the segment
        # executor (see prep_query); entries may be (term, boost) pairs
        for t, qtf in Counter(terms).items():
            boost = 1.0
            if isinstance(t, tuple):
                t, boost = t
            df = self.df.get(t, 0)
            if df > 0:
                tw.append((t, idf_fn(self.n_docs, df) * qtf * float(boost)))
        out = []
        for s in range(self.n_shards):
            out.append(build_query_plan(
                tw, lambda t, s=s: self.term_index[s].get(t, (0, 0)),
                self._impacts[s], cell_index=self._cell_indexes[s]))
        return out

    def _batch_fn(self, k: int, counted: bool = False):
        fn = self._compiled_batch.get((k, counted))
        if fn is None:
            fn = make_sharded_bm25_batch(self.mesh, self.n_per_shard, k,
                                         counted=counted)
            self._compiled_batch[(k, counted)] = fn
        return fn

    def hits_upper(self, terms) -> int:
        """df-based upper bound on matching docs (df per distinct term;
        overlap only lowers the true union)."""
        seen = set()
        total = 0
        for t in terms:
            if isinstance(t, tuple):
                t = t[0]
            if t in seen:
                continue
            seen.add(t)
            total += int(self.df.get(t, 0))
        return total

    def _run_batch(self, fn, plans: List[List[QueryPlan]], qb_pad: int):
        """plans[q][s] -> one batched dispatch over all (query, shard)."""
        n_q = len(plans)
        idx = np.zeros((self.n_shards, n_q, qb_pad), np.int32)
        w = np.zeros((self.n_shards, n_q, qb_pad), np.float32)
        for q, per_shard in enumerate(plans):
            for s, p in enumerate(per_shard):
                idx[s, q, : p.n_blocks] = p.idx
                w[s, q, : p.n_blocks] = p.w
        sh = NamedSharding(self.mesh, P("shard", None, None))
        return fn(self.block_docs, self.block_tfs, self.doc_lens,
                  jnp.float32(self.avgdl),
                  jax.device_put(idx, sh), jax.device_put(w, sh))

    def search_batch(self, queries: Sequence[Sequence[str]], k: int,
                     prune: bool = True, count_hits: bool = False):
        """Q queries -> (scores [Q,k], original corpus doc ids [Q,k]) in two
        device dispatches (phase-1 theta + phase-2 exact over survivors).
        See ops/bm25.py Bm25Executor.top_k_batch for the soundness
        argument; here phase-1 theta comes from the GLOBAL top-k across
        shards, so pruning tightens with every shard's evidence.

        With ``count_hits`` a third return carries matched-doc counts
        [Q] from the score plane; ``last_hits_exact`` records whether
        every block was gathered (exact) or only survivors (lower
        bound)."""
        plans = [self._plans(t) for t in queries]
        fn = self._batch_fn(k, counted=count_hits)
        total = sum(p.n_blocks for per in plans for p in per)
        qb_max = max((p.n_blocks for per in plans for p in per), default=1)
        qb_pad = qb_bucket(max(qb_max, 1))
        if not prune or qb_max <= P1_BUCKET:
            # every plan fits phase 1 whole — pruning cannot pay
            self.last_prune_stats = (total, total)
            self.last_hits_exact = True
            return self._run_batch(fn, plans, qb_pad)
        p1 = [[p.top_by_ub(P1_BUCKET) for p in per] for per in plans]
        s1 = self._run_batch(self._batch_fn(k), p1, P1_BUCKET)[0]
        theta = np.asarray(s1)[:, k - 1]
        p2 = [[p.survivors(float(theta[q])) for p in per]
              for q, per in enumerate(plans)]
        scored = sum(p.n_blocks for per in p2 for p in per)
        p1_cost = sum(p.n_blocks for per in p1 for p in per)
        self.last_prune_stats = (total, min(scored + p1_cost, total))
        self.last_hits_exact = scored >= total
        qb2_max = max((p.n_blocks for per in p2 for p in per), default=1)
        qb2 = qb_bucket(max(qb2_max, 1))
        return self._run_batch(fn, p2, qb2)


# ---------------------------------------------------------------------------
# sharded sparse (rank_features / text_expansion)
# ---------------------------------------------------------------------------

def _local_sparse_scores(block_docs, block_weights, block_idx, qw,
                         n_per_shard: int):
    """Per-shard linear sparse scoring: gather feature blocks, contrib =
    query_weight * stored_weight, scatter-add (the text_expansion scoring
    of execute._h_text_expansion, distributed)."""
    docs = block_docs[block_idx]              # [QB, BLOCK]
    w = block_weights[block_idx]
    valid = docs >= 0
    safe = jnp.where(valid, docs, 0)
    contrib = qw[:, None] * w
    contrib = jnp.where(valid, contrib, 0.0)
    scores = jnp.zeros((n_per_shard,), jnp.float32)
    scores = scores.at[safe.reshape(-1)].add(contrib.reshape(-1),
                                             mode="drop")
    return jnp.where(scores > 0, scores, -jnp.inf)


def make_sharded_sparse(mesh: Mesh, n_per_shard: int, k: int):
    """Compile the distributed sparse-retrieval program:
    fn(block_docs [S,NB,B], block_weights [S,NB,B], block_idx [S,QB],
    qw [S,QB]) -> (scores [k], global ids [k])."""

    def local(block_docs, block_weights, block_idx, qw):
        s = _local_sparse_scores(block_docs[0], block_weights[0],
                                 block_idx[0], qw[0], n_per_shard)
        return _global_topk_1d(s, k, n_per_shard)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None),
                  P("shard", None), P("shard", None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return profiled_callable("sharded_sparse", fn)


class ShardedFeaturesIndex:
    """rank_features corpus partitioned by doc over the mesh 'shard' axis
    (the text_expansion serving substrate — ShardedTextIndex's layout with
    stored weights instead of tfs and linear scoring)."""

    @classmethod
    def from_features_sources(cls, mesh: Mesh, sources,
                              qb_bucket_min: int = 8
                              ) -> "ShardedFeaturesIndex":
        """``sources``: ordered [(features_field_or_None, live, n_docs)]
        concatenated into a global doc space; tombstones dropped at build
        time."""
        obj = cls.__new__(cls)
        n_shards = mesh.shape["shard"]
        n = sum(n_docs for _, _, n_docs in sources)
        per = next_pow2(max(-(-n // max(n_shards, 1)), 1), minimum=BLOCK)
        shard_postings: List[Dict[str, Dict[int, float]]] = \
            [dict() for _ in range(n_shards)]
        base = 0
        for ff, live, n_docs in sources:
            if ff is None or n_docs == 0:
                base += n_docs
                continue
            live = np.asarray(live[:n_docs], bool)
            for feat, fid in ff.features.items():
                s0 = int(ff.feat_block_start[fid])
                cnt = int(ff.feat_block_count[fid])
                docs = ff.block_docs[s0 : s0 + cnt].reshape(-1)
                ws = ff.block_weights[s0 : s0 + cnt].reshape(-1)
                m = docs >= 0
                docs, ws = docs[m], ws[m]
                m = (docs < n_docs) & live[np.minimum(docs, n_docs - 1)]
                for d, wv in zip((base + docs[m]).tolist(),
                                 ws[m].tolist()):
                    sp = shard_postings[d % n_shards].setdefault(feat, {})
                    sp[d // n_shards] = float(wv)
            base += n_docs

        packed = []
        for s in range(n_shards):
            blocks_d, blocks_w = [], []
            index: Dict[str, Tuple[int, int]] = {}
            for t, posting in shard_postings[s].items():
                entries = sorted(posting.items())
                nb = max(1, -(-len(entries) // BLOCK))
                index[t] = (len(blocks_d), nb)
                d = np.full(nb * BLOCK, -1, np.int32)
                w = np.zeros(nb * BLOCK, np.float32)
                d[: len(entries)] = [e[0] for e in entries]
                w[: len(entries)] = [e[1] for e in entries]
                blocks_d.extend(d.reshape(nb, BLOCK))
                blocks_w.extend(w.reshape(nb, BLOCK))
            if not blocks_d:
                blocks_d = [np.full(BLOCK, -1, np.int32)]
                blocks_w = [np.zeros(BLOCK, np.float32)]
            packed.append((np.stack(blocks_d), np.stack(blocks_w), index))

        nb_max = next_pow2(max(p[0].shape[0] for p in packed))
        bd = np.full((n_shards, nb_max, BLOCK), -1, np.int32)
        bw = np.zeros((n_shards, nb_max, BLOCK), np.float32)
        obj.term_index = []
        for s, (d, w, index) in enumerate(packed):
            bd[s, : d.shape[0]] = d
            bw[s, : w.shape[0]] = w
            obj.term_index.append(index)
        obj.mesh = mesh
        obj.n_shards = n_shards
        obj.n_docs = n
        obj.n_per_shard = per
        obj.qb_bucket_min = qb_bucket_min
        obj.block_docs = jax.device_put(
            bd, NamedSharding(mesh, P("shard", None, None)))
        obj.block_weights = jax.device_put(
            bw, NamedSharding(mesh, P("shard", None, None)))
        obj._compiled = {}
        return obj

    def _prep(self, expansion) -> Tuple[np.ndarray, np.ndarray]:
        per_idx: List[List[int]] = [[] for _ in range(self.n_shards)]
        per_w: List[List[float]] = [[] for _ in range(self.n_shards)]
        for feat, weight in expansion:
            for s in range(self.n_shards):
                entry = self.term_index[s].get(feat)
                if entry is None:
                    continue
                start, count = entry
                for b_ in range(start, start + count):
                    per_idx[s].append(b_)
                    per_w[s].append(float(weight))
        qb = max(max((len(x) for x in per_idx), default=1), 1)
        qb_pad = next_pow2(qb, minimum=self.qb_bucket_min)
        idx = np.zeros((self.n_shards, qb_pad), np.int32)
        w = np.zeros((self.n_shards, qb_pad), np.float32)
        for s in range(self.n_shards):
            idx[s, : len(per_idx[s])] = per_idx[s]
            w[s, : len(per_w[s])] = per_w[s]
        return idx, w

    def search_batch(self, expansions, k: int):
        """[(feature, weight)] expansions -> (scores [Q, k], original ids
        [Q, k]); one compiled dispatch per query (expansions are tens of
        features — the gather is tiny)."""
        out_s, out_i = [], []
        for expansion in expansions:
            idx, w = self._prep(expansion)
            key = (k, idx.shape[1])
            fn = self._compiled.get(key)
            if fn is None:
                fn = make_sharded_sparse(self.mesh, self.n_per_shard, k)
                self._compiled[key] = fn
            sh = NamedSharding(self.mesh, P("shard", None))
            s, i = fn(self.block_docs, self.block_weights,
                      jax.device_put(idx, sh), jax.device_put(w, sh))
            out_s.append(np.asarray(s))
            out_i.append(to_original_ids(i, self.n_shards,
                                         self.n_per_shard))
        return np.stack(out_s), np.stack(out_i)


# ---------------------------------------------------------------------------
# fused hybrid (BM25 + kNN + RRF) — one program, no host round-trips
# ---------------------------------------------------------------------------

def make_sharded_hybrid(mesh: Mesh, n_per_shard: int, k: int,
                        rank_constant: int = 60,
                        similarity: str = "cosine",
                        k1: float = DEFAULT_K1, b: float = DEFAULT_B):
    """Distributed hybrid retrieval: BM25 and kNN branches execute locally,
    each produces a global top-k via all_gather, and RRF fuses on device —
    the BASELINE config-4 path as a single compiled program."""

    def local(block_docs, block_tfs, doc_lens, avgdl, block_idx, block_w,
              matrix, norms, valid, qvec):
        bscores = _local_bm25_scores(block_docs[0], block_tfs[0], doc_lens[0],
                                     avgdl, block_idx[0], block_w[0],
                                     n_per_shard, k1, b)
        vscores = _local_knn_scores(matrix[0], norms[0], valid[0],
                                    qvec[None, :], similarity)[0]

        _, bm25_ids = _global_topk_1d(bscores, k, n_per_shard)
        _, knn_ids = _global_topk_1d(vscores, k, n_per_shard)

        # --- RRF fuse on the (replicated) global id lists; -1 ids mark
        # below-threshold slots and must not earn rank credit
        ranks = jnp.arange(1, k + 1, dtype=jnp.float32)
        ids = jnp.concatenate([bm25_ids, knn_ids])
        contrib_r = jnp.concatenate([1.0 / (rank_constant + ranks)] * 2)
        present = ids >= 0
        contrib_r = jnp.where(present, contrib_r, 0.0)
        # dedupe: score(id) = sum of contributions where ids match
        eq = ids[:, None] == ids[None, :]
        fused = eq.astype(jnp.float32) @ contrib_r
        first = jnp.argmax(eq, axis=1) == jnp.arange(2 * k)  # keep first occurrence
        fused = jnp.where(first & present, fused, -jnp.inf)
        fs, fpos = jax.lax.top_k(fused, k)
        return fs, jnp.where(jnp.isfinite(fs), ids[fpos], -1)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None),
                  P("shard", None), P(), P("shard", None), P("shard", None),
                  P("shard", None, None), P("shard", None), P("shard", None),
                  P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return profiled_callable("sharded_hybrid", fn)
