"""Mesh data plane: the SPMD one-program search path behind the node.

When one node process drives a multi-device mesh (a TPU slice) and holds
every active shard of an index locally, eligible whole-index top-k queries
skip the per-shard RPC fan-out entirely: the corpus lives sharded over the
mesh and the query runs as ONE pjit program — local score -> local top-k ->
all_gather merge (parallel/sharded_search.py). This collapses the
reference's scatter-gather (action/search/AbstractSearchAsyncAction.java:156
fan-out + SearchPhaseController.java:160 merge) into compiled collectives
over ICI, per SURVEY §5.8's two-plane design; the host RPC path remains the
fallback for everything else (multi-node topologies, aggs, filters, exact
counts).

The mesh copy is rebuilt lazily per (index, field) whenever the underlying
shard readers change (segment set or live-doc count), and is born merged:
tombstoned docs are dropped at build time, so totals/idf reflect live docs
only — the same scores the RPC path produces after a force-merge.
"""

from __future__ import annotations

import time

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.search import dsl

__all__ = ["MeshDataPlane", "mesh_eligible"]


def mesh_eligible(body: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Describe how the request can run as one mesh program, or None.

    Returns {"kind": "text", "field", "clauses"} for disjunctive text
    queries (Match, or bool of only-should Matches on one field — the
    same shapes the shard WAND collector serves),
    {"kind": "knn", "field", "query"} for unfiltered kNN queries, or
    {"kind": "sparse", "field", "query"} for text_expansion /
    rank-features queries. Structural conditions mirror
    choose_collector_context plus mesh-specific ones (no phases needing
    per-shard readers during query).

    Totals: text serves totals-disabled AND finite-threshold requests
    (counts-then-skip rides the sharded program's psum'd match counts);
    only track_total_hits: true (unbounded exact) falls back to RPC.
    knn/sparse are top-k-exact by construction (total = k, relation eq),
    so any finite threshold is servable."""
    if body.get("aggs") or body.get("aggregations") or body.get("suggest"):
        return None
    if body.get("sort") is not None or body.get("search_after") is not None:
        return None
    if body.get("min_score") is not None:
        return None
    if body.get("rescore") or body.get("collapse") or body.get("slice"):
        return None
    if int(body.get("size", 10)) <= 0:
        return None
    try:
        q = dsl.parse_query(body.get("query"))
    except Exception:  # noqa: BLE001 — let the RPC path raise the real error
        return None
    if body.get("track_total_hits") is True:
        return None   # unbounded exact counting: host path
    if isinstance(q, dsl.Knn) and q.filter is None:
        return {"kind": "knn", "field": q.field, "query": q}
    if isinstance(q, dsl.TextExpansion):
        return {"kind": "sparse", "field": q.field, "query": q}
    got = dsl.disjunctive_clauses(q)
    if got is None:
        return None
    field, clauses = got
    return {"kind": "text", "field": field, "clauses": clauses}


class MeshDataPlane:
    """Owns the device mesh and per-index mesh-resident search structures."""

    def __init__(self, mesh=None, min_devices: int = 2):
        self._mesh = mesh
        self._min_devices = min_devices
        self._tried_default = False
        # (index, field) -> (freshness_key, Sharded*Index, id_map arrays)
        self._text: Dict[Tuple[str, str], Tuple[Any, Any, Any]] = {}
        self._vec: Dict[Tuple[str, str], Tuple[Any, Any, Any]] = {}
        self._feat: Dict[Tuple[str, str], Tuple[Any, Any, Any]] = {}
        self._mesh2d = None
        self.stats: Dict[str, Any] = {
            "mesh_queries": 0, "mesh_builds": 0,
            # eligible queries that escaped to the host-RPC plane because
            # the mesh program raised mid-flight (degradation telemetry)
            "mesh_fallbacks": 0,
            "wand_blocks_total": 0, "wand_blocks_scored": 0,
            # rebuild cost telemetry (VERDICT r3 weak #8: refresh-heavy
            # workloads invalidate the mesh copy — the price must be
            # observable): cumulative + last build wall seconds and docs
            "build_seconds_total": 0.0, "last_build_seconds": 0.0,
            "last_build_docs": 0}

    def _record_build(self, t0: float, n_docs: int) -> None:
        took = time.perf_counter() - t0
        self.stats["mesh_builds"] += 1
        self.stats["build_seconds_total"] = round(
            self.stats["build_seconds_total"] + took, 6)
        self.stats["last_build_seconds"] = round(took, 6)
        self.stats["last_build_docs"] = n_docs

    # ------------------------------------------------------------------

    def warmup(self) -> None:
        """Pay backend first-init NOW (node boot). The mesh property's
        guard refuses to pay it inside a search, so a mesh-configured
        node whose workload never touches the device would otherwise
        serve the RPC fallback forever; the operator who opted into the
        mesh plane accepts the init cost at startup instead."""
        try:
            import jax
            jax.devices()
        except Exception:  # noqa: BLE001 — no backend: stay on RPC
            pass

    @property
    def mesh(self):
        if self._mesh is None and not self._tried_default:
            import jax
            from jax.sharding import Mesh
            try:
                from jax._src import xla_bridge as _xb
                ready = _xb.backends_are_initialized()
            except Exception:  # noqa: BLE001 — private API moved: the
                ready = True   # pre-guard behavior (init here) applies
            if not ready:
                # first-init of the TPU-tunnel platform can block for
                # minutes while claiming hardware; a SEARCH must not pay
                # that. Stay on the RPC plane and re-check once compute
                # elsewhere (ingest, ops) has brought the backend up.
                return None
            self._tried_default = True
            devices = jax.devices()
            if len(devices) >= self._min_devices:
                self._mesh = Mesh(np.array(devices), ("shard",))
        return self._mesh

    @property
    def available(self) -> bool:
        return self.mesh is not None

    @property
    def mesh2d(self):
        """(shard, dp=1) view over the same devices — the vector program's
        expected axes (queries ride the dp axis)."""
        if self._mesh2d is None and self.mesh is not None:
            import numpy as _np
            from jax.sharding import Mesh
            self._mesh2d = Mesh(
                _np.asarray(self.mesh.devices).reshape(-1, 1),
                ("shard", "dp"))
        return self._mesh2d

    # ------------------------------------------------------------------
    # build / cache
    # ------------------------------------------------------------------

    @staticmethod
    def _freshness_key(readers) -> Tuple:
        # identity of the segment set + live count per segment: any refresh,
        # merge, or delete changes it and invalidates the mesh copy
        return tuple(
            (sid, tuple(seg.uid for seg in reader.segments),
             int(sum(int(np.asarray(m).sum()) for m in reader.live_masks)))
            for sid, reader in readers)

    def _text_index(self, index_name: str, field: str, readers):
        key = self._freshness_key(readers)
        got = self._text.get((index_name, field))
        if got is not None and got[0] == key:
            return got[1], got[2]
        t0 = time.perf_counter()
        from elasticsearch_tpu.parallel.sharded_search import ShardedTextIndex
        sources = []
        id_shard: List[int] = []
        id_segment: List[int] = []
        id_doc: List[int] = []
        for sid, reader in readers:
            for si, (seg, live) in enumerate(
                    zip(reader.segments, reader.live_masks)):
                sources.append((seg.postings.get(field), live, seg.n_docs))
                id_shard.extend([sid] * seg.n_docs)
                id_segment.extend([si] * seg.n_docs)
                id_doc.extend(range(seg.n_docs))
        tindex = ShardedTextIndex.from_postings_sources(self.mesh, sources)
        id_map = (np.asarray(id_shard, np.int32),
                  np.asarray(id_segment, np.int32),
                  np.asarray(id_doc, np.int32))
        self._text[(index_name, field)] = (key, tindex, id_map)
        self._record_build(t0, tindex.n_docs)
        return tindex, id_map

    # ------------------------------------------------------------------
    # search
    # ------------------------------------------------------------------

    def _vector_index(self, index_name: str, field: str, readers):
        key = self._freshness_key(readers)
        got = self._vec.get((index_name, field))
        if got is not None and got[0] == key:
            return got[1], got[2], got[3]
        t0 = time.perf_counter()
        from elasticsearch_tpu.parallel.sharded_search import (
            ShardedVectorIndex,
        )
        rows = []
        id_shard: List[int] = []
        id_segment: List[int] = []
        id_doc: List[int] = []
        similarity = "cosine"
        for sid, reader in readers:
            for si, (seg, live) in enumerate(
                    zip(reader.segments, reader.live_masks)):
                vf = seg.vectors.get(field)
                if vf is None:
                    continue
                similarity = vf.similarity
                live = np.asarray(live[: seg.n_docs], bool)
                keep = np.nonzero(vf.exists[: seg.n_docs] & live)[0]
                if len(keep) == 0:
                    continue
                rows.append(vf.matrix[keep])
                id_shard.extend([sid] * len(keep))
                id_segment.extend([si] * len(keep))
                id_doc.extend(keep.tolist())
        if not rows:
            return None, None, None
        matrix = np.concatenate(rows).astype(np.float32)
        vindex = ShardedVectorIndex(self.mesh2d, matrix,
                                    similarity=similarity)
        id_map = (np.asarray(id_shard, np.int32),
                  np.asarray(id_segment, np.int32),
                  np.asarray(id_doc, np.int32))
        # per-shard live-vector counts, computed ONCE per build: knn
        # totals parity needs them every query and id_map scans are
        # O(n_docs)
        _, shard_counts = np.unique(id_map[0], return_counts=True)
        self._vec[(index_name, field)] = (key, vindex, id_map,
                                          shard_counts)
        self._record_build(t0, vindex.n_docs)
        return vindex, id_map, shard_counts

    def _features_index(self, index_name: str, field: str, readers):
        key = self._freshness_key(readers)
        got = self._feat.get((index_name, field))
        if got is not None and got[0] == key:
            return got[1], got[2]
        t0 = time.perf_counter()
        from elasticsearch_tpu.parallel.sharded_search import (
            ShardedFeaturesIndex,
        )
        sources = []
        id_shard: List[int] = []
        id_segment: List[int] = []
        id_doc: List[int] = []
        for sid, reader in readers:
            for si, (seg, live) in enumerate(
                    zip(reader.segments, reader.live_masks)):
                sources.append((seg.features.get(field), live, seg.n_docs))
                id_shard.extend([sid] * seg.n_docs)
                id_segment.extend([si] * seg.n_docs)
                id_doc.extend(range(seg.n_docs))
        findex = ShardedFeaturesIndex.from_features_sources(self.mesh,
                                                            sources)
        id_map = (np.asarray(id_shard, np.int32),
                  np.asarray(id_segment, np.int32),
                  np.asarray(id_doc, np.int32))
        self._feat[(index_name, field)] = (key, findex, id_map)
        self._record_build(t0, findex.n_docs)
        return findex, id_map

    # ------------------------------------------------------------------
    # search
    # ------------------------------------------------------------------

    @staticmethod
    def _want(body: Dict[str, Any], n: int) -> int:
        want = int(body.get("size", 10)) + int(body.get("from", 0))
        return max(1, min(want, n if n else 1))

    def _emit(self, scores, ids, id_map, boost: float
              ) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for sc, gid in zip(np.asarray(scores), np.asarray(ids)):
            if not np.isfinite(sc) or gid < 0:
                break
            out.append({"shard": int(id_map[0][gid]),
                        "segment": int(id_map[1][gid]),
                        "doc": int(id_map[2][gid]),
                        "score": float(sc) * boost,
                        "sort": [float(sc) * boost]})
        return out

    def search_text(self, index_name: str, field: str, shards,
                    body: Dict[str, Any], mappers,
                    clauses=None) -> Optional[Dict[str, Any]]:
        """Run the one-program path; returns {"hits": [...], "total",
        "relation"} with hits globally sorted, or None if the field isn't
        analyzable here (caller falls back to RPC).

        Totals (counts-then-skip over the mesh): with a finite
        track_total_hits the counted program observes matches in the
        blocks it gathers — observed >= threshold proves ("gte",
        threshold); otherwise an unpruned counted pass gives the exact
        count (skipped entirely when the df upper bound already fits
        under the threshold)."""
        if not self.available:
            return None
        mapper = mappers.mapper(field)
        analyzer = getattr(mapper, "search_analyzer", None)
        if analyzer is None:
            return None
        if clauses is None:
            q = dsl.parse_query(body.get("query"))
            clauses = [(q.text, q.boost)]
        terms: List[Any] = []
        for text, boost in clauses:
            terms.extend((t, float(boost)) for t in analyzer.terms(text))
        if not terms:
            return {"hits": [], "total": 0, "relation": "eq"}
        tth = body.get("track_total_hits", 10_000)
        limit = 0 if tth in (False, 0) else int(tth)
        readers = [(sid, shard.engine.acquire_reader())
                   for sid, shard in sorted(shards.items())]
        tindex, id_map = self._text_index(index_name, field, readers)
        k = self._want(body, tindex.n_docs)
        count = limit > 0
        if count and tindex.hits_upper(terms) <= limit:
            # few enough postings that pruning can't pay: one unpruned
            # counted pass, exact totals for free
            scores, ids, hits = tindex.search_batch(
                [terms], k, prune=False, count_hits=True)
            total, relation = int(hits[0]), "eq"
        elif count:
            scores, ids, hits = tindex.search_batch(
                [terms], k, count_hits=True)
            observed = int(hits[0])
            if observed >= limit:
                total, relation = limit, "gte"
            elif tindex.last_hits_exact:
                total, relation = observed, "eq"
            else:
                _s, _i, hits = tindex.search_batch(
                    [terms], 1, prune=False, count_hits=True)
                exact = int(hits[0])
                total, relation = (limit, "gte") if exact > limit \
                    else (exact, "eq")
        else:
            scores, ids = tindex.search_batch([terms], k)
            total, relation = None, "gte"
        t, g = tindex.last_prune_stats
        self.stats["mesh_queries"] += 1
        self.stats["wand_blocks_total"] += t
        self.stats["wand_blocks_scored"] += g
        out = self._emit(scores[0], ids[0], id_map, 1.0)
        return {"hits": out,
                "total": len(out) if total is None else total,
                "relation": relation}

    def search_knn(self, index_name: str, field: str, shards,
                   body: Dict[str, Any], query: "dsl.Knn"
                   ) -> Optional[Dict[str, Any]]:
        """Unfiltered exact kNN as one mesh program (the
        parallel/sharded_search.py kNN kernel behind the REST surface)."""
        if not self.available:
            return None
        readers = [(sid, shard.engine.acquire_reader())
                   for sid, shard in sorted(shards.items())]
        vindex, id_map, shard_counts = self._vector_index(
            index_name, field, readers)
        if vindex is None:
            return None
        # size+from bounds the result like the RPC path's shard collection
        # window (query.k bounds PER-SHARD collection there, so clamping
        # the global mesh result by it would return fewer hits than the
        # RPC path on multi-shard indices)
        k = self._want(body, vindex.n_docs)
        qv = np.asarray(query.query_vector, np.float32)[None, :]
        scores, ids = vindex.search(qv, k)
        self.stats["mesh_queries"] += 1
        out = self._emit(scores[0], ids[0], id_map, query.boost)
        # totals match the RPC plane's EXACT path: there each shard's Knn
        # rewrites to a per-shard top-k doc set (KnnBound, <= query.k
        # docs) and the coordinator sums per-shard collection counts.
        # Documented divergence: the RPC ANN path (ivf opt-in or
        # >=65536-doc segments) can post-filter to fewer than k live
        # hits; the mesh plane is always exact, so it reports the exact
        # path's total. The hit window (size+from) is not bounded by
        # query.k, so the clamp keeps hits <= total invariant when the
        # window exceeds the per-shard collection sum (ADVICE r5 medium).
        total = max(int(np.minimum(shard_counts, query.k).sum()), len(out))
        return {"hits": out, "total": total, "relation": "eq"}

    def search_sparse(self, index_name: str, field: str, shards,
                      body: Dict[str, Any], query: "dsl.TextExpansion"
                      ) -> Optional[Dict[str, Any]]:
        """text_expansion / learned-sparse retrieval as one mesh program:
        expansion tokens (from the on-device model when not precomputed)
        score linearly against the sharded rank-features blocks."""
        if not self.available:
            return None
        tokens = query.tokens
        if tokens is None:
            from elasticsearch_tpu.ml import get_model
            tokens = get_model(query.model_id).expand(query.model_text or "")
        if not tokens:
            return {"hits": [], "total": 0, "relation": "eq"}
        readers = [(sid, shard.engine.acquire_reader())
                   for sid, shard in sorted(shards.items())]
        findex, id_map = self._features_index(index_name, field, readers)
        if findex is None or findex.n_docs == 0:
            return {"hits": [], "total": 0, "relation": "eq"}
        k = self._want(body, findex.n_docs)
        expansion = [(t, float(w) * query.boost) for t, w in tokens.items()]
        scores, ids = findex.search_batch([expansion], k)
        self.stats["mesh_queries"] += 1
        out = self._emit(scores[0], ids[0], id_map, 1.0)
        # the sparse mesh program returns only the global top-k, so the
        # matched-doc count is unobserved; len(out) is a LOWER bound —
        # report "gte" rather than claiming the RPC plane's exact
        # collected-count (documented divergence, vs search_text's
        # counts-then-skip which does prove its totals)
        return {"hits": out, "total": len(out), "relation": "gte"}
