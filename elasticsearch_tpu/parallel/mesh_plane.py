"""Mesh data plane: the SPMD one-program search path behind the node.

When one node process drives a multi-device mesh (a TPU slice) and holds
every active shard of an index locally, eligible whole-index top-k queries
skip the per-shard RPC fan-out entirely: the corpus lives sharded over the
mesh and the query runs as ONE pjit program — local score -> local top-k ->
all_gather merge (parallel/sharded_search.py). This collapses the
reference's scatter-gather (action/search/AbstractSearchAsyncAction.java:156
fan-out + SearchPhaseController.java:160 merge) into compiled collectives
over ICI, per SURVEY §5.8's two-plane design; the host RPC path remains the
fallback for everything else (multi-node topologies, aggs, filters, exact
counts).

The mesh copy is rebuilt lazily per (index, field) whenever the underlying
shard readers change (segment set or live-doc count), and is born merged:
tombstoned docs are dropped at build time, so totals/idf reflect live docs
only — the same scores the RPC path produces after a force-merge.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.search import dsl

__all__ = ["MeshDataPlane", "mesh_eligible"]


def mesh_eligible(body: Dict[str, Any]) -> Optional[str]:
    """Return the match field if the request can run as one mesh program.

    Mirrors choose_collector_context's WAND conditions (pure score-sorted
    top-k text query, totals disabled) plus mesh-specific ones (no
    highlight-independent phases that need per-shard readers during query).
    """
    if body.get("aggs") or body.get("aggregations") or body.get("suggest"):
        return None
    if body.get("sort") is not None or body.get("search_after") is not None:
        return None
    if body.get("min_score") is not None:
        return None
    if body.get("rescore") or body.get("collapse") or body.get("slice"):
        return None
    if not (body.get("track_total_hits") is False
            or body.get("track_total_hits") == 0):
        return None
    if int(body.get("size", 10)) <= 0:
        return None
    try:
        q = dsl.parse_query(body.get("query"))
    except Exception:  # noqa: BLE001 — let the RPC path raise the real error
        return None
    if not isinstance(q, dsl.Match):
        return None
    if q.operator == "and" or q.minimum_should_match is not None:
        return None
    return q.field


class MeshDataPlane:
    """Owns the device mesh and per-index mesh-resident search structures."""

    def __init__(self, mesh=None, min_devices: int = 2):
        self._mesh = mesh
        self._min_devices = min_devices
        self._tried_default = False
        # (index, field) -> (freshness_key, ShardedTextIndex, id_map arrays)
        self._text: Dict[Tuple[str, str], Tuple[Any, Any, Any]] = {}
        self.stats: Dict[str, int] = {
            "mesh_queries": 0, "mesh_builds": 0,
            "wand_blocks_total": 0, "wand_blocks_scored": 0}

    # ------------------------------------------------------------------

    @property
    def mesh(self):
        if self._mesh is None and not self._tried_default:
            self._tried_default = True
            import jax
            from jax.sharding import Mesh
            devices = jax.devices()
            if len(devices) >= self._min_devices:
                self._mesh = Mesh(np.array(devices), ("shard",))
        return self._mesh

    @property
    def available(self) -> bool:
        return self.mesh is not None

    # ------------------------------------------------------------------
    # build / cache
    # ------------------------------------------------------------------

    @staticmethod
    def _freshness_key(readers) -> Tuple:
        # identity of the segment set + live count per segment: any refresh,
        # merge, or delete changes it and invalidates the mesh copy
        return tuple(
            (sid, tuple(seg.uid for seg in reader.segments),
             int(sum(int(np.asarray(m).sum()) for m in reader.live_masks)))
            for sid, reader in readers)

    def _text_index(self, index_name: str, field: str, readers):
        key = self._freshness_key(readers)
        got = self._text.get((index_name, field))
        if got is not None and got[0] == key:
            return got[1], got[2]
        from elasticsearch_tpu.parallel.sharded_search import ShardedTextIndex
        sources = []
        id_shard: List[int] = []
        id_segment: List[int] = []
        id_doc: List[int] = []
        for sid, reader in readers:
            for si, (seg, live) in enumerate(
                    zip(reader.segments, reader.live_masks)):
                sources.append((seg.postings.get(field), live, seg.n_docs))
                id_shard.extend([sid] * seg.n_docs)
                id_segment.extend([si] * seg.n_docs)
                id_doc.extend(range(seg.n_docs))
        tindex = ShardedTextIndex.from_postings_sources(self.mesh, sources)
        id_map = (np.asarray(id_shard, np.int32),
                  np.asarray(id_segment, np.int32),
                  np.asarray(id_doc, np.int32))
        self._text[(index_name, field)] = (key, tindex, id_map)
        self.stats["mesh_builds"] += 1
        return tindex, id_map

    # ------------------------------------------------------------------
    # search
    # ------------------------------------------------------------------

    def search_text(self, index_name: str, field: str, shards,
                    body: Dict[str, Any], mappers
                    ) -> Optional[List[Dict[str, Any]]]:
        """Run the one-program path; returns per-hit dicts
        {shard, segment, doc, score} globally sorted, or None if the field
        isn't analyzable here (caller falls back to RPC)."""
        if not self.available:
            return None
        mapper = mappers.mapper(field)
        analyzer = getattr(mapper, "search_analyzer", None)
        if analyzer is None:
            return None
        q = dsl.parse_query(body.get("query"))
        terms = analyzer.terms(q.text)
        if not terms:
            return []
        readers = [(sid, shard.engine.acquire_reader())
                   for sid, shard in sorted(shards.items())]
        tindex, id_map = self._text_index(index_name, field, readers)
        want = int(body.get("size", 10)) + int(body.get("from", 0))
        k = max(1, min(want, tindex.n_docs if tindex.n_docs else 1))
        scores, ids = tindex.search_batch([terms], k)
        t, g = tindex.last_prune_stats
        self.stats["mesh_queries"] += 1
        self.stats["wand_blocks_total"] += t
        self.stats["wand_blocks_scored"] += g
        s0 = np.asarray(scores[0])
        i0 = np.asarray(ids[0])
        out: List[Dict[str, Any]] = []
        boost = q.boost
        for sc, gid in zip(s0, i0):
            if not np.isfinite(sc) or gid < 0:
                break
            out.append({"shard": int(id_map[0][gid]),
                        "segment": int(id_map[1][gid]),
                        "doc": int(id_map[2][gid]),
                        "score": float(sc) * boost,
                        "sort": [float(sc) * boost]})
        return out
