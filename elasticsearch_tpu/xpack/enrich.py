"""Enrich: lookup policies + the enrich ingest processor.

Reference: x-pack/plugin/enrich — a policy names a source index, a
match_field, and enrich_fields; executing the policy builds a compact
system index (EnrichPolicyRunner), and the ``enrich`` ingest processor
joins documents against it at ingest time via an in-memory lookup
(MatchProcessor backed by a searcher over the enrich index). This build
executes a policy into an in-cluster-state lookup table (bounded), which
both makes the table replicate to every ingest node for free and keeps
the processor a pure dict lookup — the reference's per-node enrich index
reader collapsed to its essential form.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict

from elasticsearch_tpu.utils.errors import (
    IllegalArgumentError, ResourceNotFoundError,
)

logger = logging.getLogger(__name__)

POLICY_SECTION = "enrich_policies"
TABLE_SECTION = "enrich_tables"
MAX_TABLE_ENTRIES = 10_000


class EnrichService:
    def __init__(self, node) -> None:
        self.node = node
        # (state version, name) -> table: read-only lookups must not copy
        # a 10k-entry dict once per ingested document
        self._table_cache: Dict[str, Any] = {}

    def _policies(self) -> Dict[str, Any]:
        return dict(self.node._applied_state()
                    .metadata.custom.get(POLICY_SECTION, {}))

    def table(self, policy_name: str) -> Dict[str, Any]:
        state = self.node._applied_state()
        cached = self._table_cache.get(policy_name)
        if cached is not None and cached[0] == state.version:
            return cached[1]
        table = state.metadata.custom.get(TABLE_SECTION, {}) \
            .get(policy_name, {})
        self._table_cache[policy_name] = (state.version, table)
        return table

    # -- API --------------------------------------------------------------

    def put_policy(self, name: str, body: Dict[str, Any],
                   on_done: Callable) -> None:
        body = body or {}
        match = body.get("match") or body.get("range")
        if not match:
            on_done(None, IllegalArgumentError(
                "enrich policy requires [match]"))
            return
        for req in ("indices", "match_field", "enrich_fields"):
            if req not in match:
                on_done(None, IllegalArgumentError(
                    f"enrich policy requires [match.{req}]"))
                return
        from elasticsearch_tpu.action.admin import PUT_CUSTOM
        self.node.master_client.execute(
            PUT_CUSTOM, {"section": POLICY_SECTION, "name": name,
                         "body": body},
            lambda r, e: on_done({"acknowledged": True}
                                 if e is None else None, e))

    def delete_policy(self, name: str, on_done: Callable) -> None:
        if name not in self._policies():
            on_done(None, ResourceNotFoundError(
                f"enrich policy [{name}] not found"))
            return
        from elasticsearch_tpu.action.admin import DELETE_CUSTOM

        def table_deleted(_r, _e):
            self.node.master_client.execute(
                DELETE_CUSTOM, {"section": POLICY_SECTION, "name": name},
                lambda r, e: on_done({"acknowledged": True}
                                     if e is None else None, e))
        self.node.master_client.execute(
            DELETE_CUSTOM, {"section": TABLE_SECTION, "name": name},
            table_deleted)

    def execute_policy(self, name: str, on_done: Callable) -> None:
        """Scan the source indices and publish the match_field -> fields
        lookup table (EnrichPolicyRunner's index rebuild)."""
        policy = self._policies().get(name)
        if policy is None:
            on_done(None, ResourceNotFoundError(
                f"enrich policy [{name}] not found"))
            return
        match = policy.get("match") or policy.get("range")
        indices = match["indices"]
        # ALL source indices feed the table (the expression layer takes
        # comma-joined lists)
        index = ",".join(indices) if isinstance(indices, list) else indices
        match_field = match["match_field"]
        enrich_fields = list(match["enrich_fields"])

        def cb(resp, err):
            if err is not None:
                on_done(None, err)
                return
            table: Dict[str, Any] = {}
            for h in resp["hits"]["hits"]:
                src = h.get("_source", {})
                key = src.get(match_field)
                if key is None:
                    continue
                table[str(key)] = {f: src.get(f) for f in enrich_fields
                                   if f in src}
                if len(table) >= MAX_TABLE_ENTRIES:
                    break
            from elasticsearch_tpu.action.admin import PUT_CUSTOM
            self.node.master_client.execute(
                PUT_CUSTOM, {"section": TABLE_SECTION, "name": name,
                             "body": table},
                lambda r, e: on_done(
                    {"status": {"phase": "COMPLETE"},
                     "entries": len(table)} if e is None else None, e))
        self.node.search_action.execute(index, {
            "query": {"match_all": {}}, "size": MAX_TABLE_ENTRIES}, cb)

    def policies(self) -> Dict[str, Any]:
        return {"policies": [
            {"config": {("match" if "match" in p else "range"): {
                **(p.get("match") or p.get("range") or {}), "name": name}}}
            for name, p in sorted(self._policies().items())]}


def validate_enrich_config(config: Dict[str, Any]) -> None:
    if not config.get("policy_name") or not config.get("field") or \
            not config.get("target_field"):
        raise IllegalArgumentError(
            "enrich processor requires [policy_name], [field], "
            "[target_field]")


def make_enrich_processor(node, config: Dict[str, Any]):
    """The ``enrich`` ingest processor (MatchProcessor analog): joins the
    document's field value against the executed policy table."""
    validate_enrich_config(config)
    policy_name = config["policy_name"]
    field = config["field"]
    target = config["target_field"]
    max_matches = int(config.get("max_matches", 1))
    override = bool(config.get("override", True))

    def process(doc: Dict[str, Any]) -> Dict[str, Any]:
        """Receives the full ingest document (with _source), like every
        other processor; field paths are dotted."""
        from elasticsearch_tpu.ingest import get_field, set_field
        table = node.enrich_service.table(policy_name)
        value = get_field(doc, field)
        if value is None:
            return doc
        values = value if isinstance(value, list) else [value]
        matches = [table[str(v)] for v in values if str(v) in table]
        if not matches:
            return doc
        if not override and get_field(doc, target) is not None:
            return doc
        set_field(doc, target,
                  matches[0] if max_matches == 1 else matches[:max_matches])
        return doc
    return process
