"""Autoscaling: policies + capacity decisions from current usage.

Reference: x-pack/plugin/autoscaling — policies name roles and deciders;
GET /_autoscaling/capacity reports required vs current capacity per
policy so an orchestrator can add/remove nodes. The deciders here are
the two that matter for this build's resource model: shard density
(shards per data node) and indexing pressure headroom.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from elasticsearch_tpu.utils.errors import (
    IllegalArgumentError, ResourceNotFoundError,
)

SECTION = "autoscaling_policies"

# reference cluster.max_shards_per_node default is 1000; scaled to this
# build's event-loop nodes
MAX_SHARDS_PER_NODE = 1000


class AutoscalingService:
    def __init__(self, node) -> None:
        self.node = node

    def _policies(self) -> Dict[str, Any]:
        return dict(self.node._applied_state()
                    .metadata.custom.get(SECTION, {}))

    def put_policy(self, name: str, body: Dict[str, Any],
                   on_done: Callable) -> None:
        body = dict(body or {})
        if not body.get("roles"):
            on_done(None, IllegalArgumentError(
                "autoscaling policy requires [roles]"))
            return
        from elasticsearch_tpu.action.admin import PUT_CUSTOM
        self.node.master_client.execute(
            PUT_CUSTOM, {"section": SECTION, "name": name, "body": body},
            lambda r, e: on_done({"acknowledged": True}
                                 if e is None else None, e))

    def delete_policy(self, name: str, on_done: Callable) -> None:
        if name not in self._policies():
            on_done(None, ResourceNotFoundError(
                f"autoscaling policy [{name}] not found"))
            return
        from elasticsearch_tpu.action.admin import DELETE_CUSTOM
        self.node.master_client.execute(
            DELETE_CUSTOM, {"section": SECTION, "name": name},
            lambda r, e: on_done({"acknowledged": True}
                                 if e is None else None, e))

    def capacity(self) -> Dict[str, Any]:
        """GET /_autoscaling/capacity: per policy, current node count vs
        the count the deciders require."""
        state = self.node._applied_state()
        n_data = len(state.data_nodes())
        total_shards = sum(1 for sr in state.routing_table.all_shards()
                           if sr.assigned)
        unassigned = sum(1 for sr in state.routing_table.all_shards()
                         if not sr.assigned)
        tp = self.node.thread_pool
        pressure = (tp.write_bytes_in_flight / tp.write_bytes_limit
                    if tp.write_bytes_limit else 0.0)
        policies = {}
        for name, p in sorted(self._policies().items()):
            required = max(1, -(-(total_shards + unassigned)
                                // MAX_SHARDS_PER_NODE))
            reasons = []
            if unassigned:
                # replicas that cannot fit (same-shard) need more nodes
                required = max(required, n_data + 1)
                reasons.append(
                    f"{unassigned} unassigned shard copies need "
                    f"additional nodes")
            if pressure > 0.8:
                required = max(required, n_data + 1)
                reasons.append(
                    f"indexing pressure at {pressure:.0%} of capacity")
            policies[name] = {
                "required_capacity": {"total": {"nodes": required}},
                "current_capacity": {"total": {"nodes": n_data}},
                "current_nodes": sorted(state.data_nodes()),
                "deciders": {
                    "shard_density": {
                        "required_nodes": max(1, -(-total_shards //
                                                   MAX_SHARDS_PER_NODE)),
                        "assigned_shards": total_shards,
                        "unassigned_shards": unassigned},
                    "indexing_pressure": {
                        "utilization": round(pressure, 4)},
                },
                "reason_summary": "; ".join(reasons) or "capacity ok",
            }
        return {"policies": policies}
