"""SQL: a typed subset of the reference's x-pack SQL, compiled to the DSL.

Reference: x-pack/plugin/sql — parser -> logical plan -> QueryContainer
translated into a search request, rows streamed back with a columns
header. This build implements the high-traffic subset with a hand-rolled
tokenizer + recursive-descent parser (no ANTLR):

  SELECT */cols/aggfns FROM index [WHERE cond] [GROUP BY cols]
      [ORDER BY col [ASC|DESC], ...] [LIMIT n]

  cond: comparisons (= != <> > >= < <=), AND/OR/NOT, parentheses,
        IN (...), BETWEEN a AND b, LIKE 'pat%' (%/_ -> wildcard),
        IS [NOT] NULL
  aggs: COUNT(*), COUNT(col), SUM/AVG/MIN/MAX(col) with GROUP BY
        compiled onto the composite aggregation

POST /_sql returns {columns, rows}; POST /_sql/translate returns the
search body the query compiles to (the reference's translate API).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.utils.errors import IllegalArgumentError

MAX_ROWS = 1000
MAX_GROUPS = 10_000

_TOKEN_RX = re.compile(r"""
    \s*(?:
      (?P<num>-?\d+(?:\.\d+)?)
    | '(?P<str>(?:[^']|'')*)'
    | "(?P<qid>(?:[^"]|"")*)"
    | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|,|\*)
    | (?P<word>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""", re.VERBOSE)

_KEYWORDS = {"SELECT", "FROM", "WHERE", "GROUP", "BY", "ORDER", "LIMIT",
             "AND", "OR", "NOT", "IN", "BETWEEN", "LIKE", "IS", "NULL",
             "AS", "ASC", "DESC", "TRUE", "FALSE", "HAVING"}
_AGG_FNS = {"COUNT", "SUM", "AVG", "MIN", "MAX"}


def tokenize(text: str) -> List[Tuple[str, Any]]:
    out: List[Tuple[str, Any]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RX.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise IllegalArgumentError(
                f"SQL: cannot tokenize at [{text[pos:pos + 20]!r}]")
        pos = m.end()
        if m.group("num") is not None:
            n = float(m.group("num"))
            out.append(("num", int(n) if n.is_integer() else n))
        elif m.group("str") is not None:
            out.append(("str", m.group("str").replace("''", "'")))
        elif m.group("qid") is not None:
            out.append(("ident", m.group("qid").replace('""', '"')))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            word = m.group("word")
            if word.upper() in _KEYWORDS or word.upper() in _AGG_FNS:
                out.append(("kw", word.upper()))
            else:
                out.append(("ident", word))
    out.append(("end", None))
    return out


class _Parser:
    def __init__(self, tokens: List[Tuple[str, Any]]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> Tuple[str, Any]:
        return self.tokens[self.i]

    def next(self) -> Tuple[str, Any]:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect_kw(self, word: str) -> None:
        kind, value = self.next()
        if kind != "kw" or value != word:
            raise IllegalArgumentError(f"SQL: expected {word}, got {value!r}")

    def accept_kw(self, word: str) -> bool:
        kind, value = self.peek()
        if kind == "kw" and value == word:
            self.i += 1
            return True
        return False

    def accept_op(self, op: str) -> bool:
        kind, value = self.peek()
        if kind == "op" and value == op:
            self.i += 1
            return True
        return False

    def ident(self) -> str:
        kind, value = self.next()
        if kind != "ident":
            raise IllegalArgumentError(
                f"SQL: expected identifier, got {value!r}")
        return value

    # -- grammar ----------------------------------------------------------

    def parse(self) -> Dict[str, Any]:
        self.expect_kw("SELECT")
        select = self._select_items()
        self.expect_kw("FROM")
        index = self.ident()
        where = None
        if self.accept_kw("WHERE"):
            where = self._expr()
        group_by: List[str] = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.ident())
            while self.accept_op(","):
                group_by.append(self.ident())
        if self.accept_kw("HAVING"):
            raise IllegalArgumentError("SQL: HAVING is not supported")
        order_by: List[Tuple[str, str]] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                col = self.ident()
                direction = "asc"
                if self.accept_kw("DESC"):
                    direction = "desc"
                else:
                    self.accept_kw("ASC")
                order_by.append((col, direction))
                if not self.accept_op(","):
                    break
        limit = None
        if self.accept_kw("LIMIT"):
            kind, value = self.next()
            if kind != "num":
                raise IllegalArgumentError("SQL: LIMIT expects a number")
            limit = int(value)
        kind, value = self.next()
        if kind != "end":
            raise IllegalArgumentError(f"SQL: unexpected trailing {value!r}")
        return {"select": select, "index": index, "where": where,
                "group_by": group_by, "order_by": order_by, "limit": limit}

    def _select_items(self) -> List[Dict[str, Any]]:
        if self.accept_op("*"):
            return [{"kind": "star"}]
        items = []
        while True:
            kind, value = self.peek()
            if kind == "kw" and value in _AGG_FNS:
                self.next()
                fn = value
                if not self.accept_op("("):
                    raise IllegalArgumentError(f"SQL: {fn} expects (...)")
                if self.accept_op("*"):
                    arg = "*"
                else:
                    arg = self.ident()
                if not self.accept_op(")"):
                    raise IllegalArgumentError(f"SQL: {fn} missing )")
                name = f"{fn}({arg})"
                if self.accept_kw("AS"):
                    name = self.ident()
                items.append({"kind": "agg", "fn": fn, "arg": arg,
                              "name": name})
            else:
                col = self.ident()
                name = col
                if self.accept_kw("AS"):
                    name = self.ident()
                items.append({"kind": "col", "col": col, "name": name})
            if not self.accept_op(","):
                return items

    def _expr(self):
        node = self._and_expr()
        while self.accept_kw("OR"):
            rhs = self._and_expr()
            node = {"bool": {"should": [node, rhs],
                             "minimum_should_match": 1}}
        return node

    def _and_expr(self):
        node = self._not_expr()
        while self.accept_kw("AND"):
            rhs = self._not_expr()
            node = {"bool": {"must": [node, rhs]}}
        return node

    def _not_expr(self):
        if self.accept_kw("NOT"):
            return {"bool": {"must_not": [self._not_expr()]}}
        return self._primary()

    def _literal(self) -> Any:
        kind, value = self.next()
        if kind in ("num", "str"):
            return value
        if kind == "kw" and value in ("TRUE", "FALSE"):
            return value == "TRUE"
        raise IllegalArgumentError(f"SQL: expected literal, got {value!r}")

    def _primary(self):
        if self.accept_op("("):
            node = self._expr()
            if not self.accept_op(")"):
                raise IllegalArgumentError("SQL: missing )")
            return node
        col = self.ident()
        if self.accept_kw("IS"):
            negate = self.accept_kw("NOT")
            self.expect_kw("NULL")
            exists = {"exists": {"field": col}}
            return exists if negate else \
                {"bool": {"must_not": [exists]}}
        if self.accept_kw("IN"):
            if not self.accept_op("("):
                raise IllegalArgumentError("SQL: IN expects (...)")
            values = [self._literal()]
            while self.accept_op(","):
                values.append(self._literal())
            if not self.accept_op(")"):
                raise IllegalArgumentError("SQL: IN missing )")
            return {"terms": {col: values}}
        if self.accept_kw("BETWEEN"):
            lo = self._literal()
            self.expect_kw("AND")
            hi = self._literal()
            return {"range": {col: {"gte": lo, "lte": hi}}}
        if self.accept_kw("LIKE"):
            pat = self._literal()
            # literal wildcard metachars in the pattern must stay literal
            # (SQL LIKE has no '*'/'?'); fnmatch-class escapes via [..]
            wildcard = (str(pat)
                        .replace("[", "[[]").replace("*", "[*]")
                        .replace("?", "[?]")
                        .replace("%", "*").replace("_", "?"))
            return {"wildcard": {col: {"value": wildcard}}}
        for op, clause in (("<=", "lte"), (">=", "gte"),
                           ("<", "lt"), (">", "gt")):
            if self.accept_op(op):
                return {"range": {col: {clause: self._literal()}}}
        if self.accept_op("="):
            return {"term": {col: {"value": self._literal()}}}
        if self.accept_op("!=") or self.accept_op("<>"):
            return {"bool": {"must_not": [
                {"term": {col: {"value": self._literal()}}}]}}
        raise IllegalArgumentError(
            f"SQL: expected operator after [{col}]")


# ---------------------------------------------------------------------------
# translation + execution
# ---------------------------------------------------------------------------

def parse_sql(text: str) -> Dict[str, Any]:
    return _Parser(tokenize(text)).parse()


def _agg_body(item: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The metric-agg body for one select item; None when doc_count (or
    hit total) answers it. COUNT(col) counts docs WITH the column —
    value_count, never doc_count."""
    if item["fn"] == "COUNT" and item["arg"] == "*":
        return None
    if item["fn"] == "COUNT":
        return {"value_count": {"field": item["arg"]}}
    return {item["fn"].lower(): {"field": item["arg"]}}


def _validate_order_by(plan: Dict[str, Any]) -> None:
    """GROUP BY ordering applies host-side to SELECTed names — reject
    unknown columns BEFORE any search work runs."""
    names = [i["name"] for i in plan["select"]]
    for col, _d in plan["order_by"]:
        if col not in names:
            raise IllegalArgumentError(
                f"SQL: ORDER BY [{col}] must appear in SELECT")


def translate(plan: Dict[str, Any]) -> Dict[str, Any]:
    """The search body a parsed SQL plan compiles to (_sql/translate)."""
    body: Dict[str, Any] = {}
    if plan["where"] is not None:
        body["query"] = plan["where"]
    limit = plan["limit"] if plan["limit"] is not None else MAX_ROWS
    has_aggs = any(i["kind"] == "agg" for i in plan["select"])
    if plan["group_by"]:
        _validate_order_by(plan)
        aggs = {}
        for item in plan["select"]:
            if item["kind"] != "agg":
                continue
            agg = _agg_body(item)
            if agg is not None:
                aggs[item["name"]] = agg
        body["size"] = 0
        body["aggs"] = {"groups": {
            "composite": {
                # all groups in one page (capped) — ORDER BY/LIMIT apply
                # to the full group set host-side
                "size": MAX_GROUPS,
                "sources": [{col: {"terms": {"field": col}}}
                            for col in plan["group_by"]],
            },
            **({"aggs": aggs} if aggs else {}),
        }}
        return body
    if has_aggs:
        # implicit global group: SELECT COUNT(*), MAX(x) FROM idx is one
        # row over every match (the reference's implicit grouping)
        if any(i["kind"] != "agg" for i in plan["select"]):
            raise IllegalArgumentError(
                "SQL: mixing aggregates and columns requires GROUP BY")
        body["size"] = 0
        body["track_total_hits"] = True
        aggs = {}
        for item in plan["select"]:
            agg = _agg_body(item)
            if agg is not None:
                aggs[item["name"]] = agg
        if aggs:
            body["aggs"] = aggs
        return body
    body["size"] = min(limit, MAX_ROWS)
    cols = [item["col"] for item in plan["select"]
            if item["kind"] == "col"]
    if cols:
        body["_source"] = cols
    if plan["order_by"]:
        # ORDER BY a SELECT alias sorts the underlying field; anything
        # else passes through as a document field name
        aliases = {i["name"]: i["col"] for i in plan["select"]
                   if i["kind"] == "col"}
        body["sort"] = [{aliases.get(c, c): d}
                        for c, d in plan["order_by"]]
    return body


def _field_from(source: Dict[str, Any], path: str) -> Any:
    node: Any = source
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return None
    return node


class SqlService:
    def __init__(self, node) -> None:
        self.node = node

    def query(self, sql_text: str, on_done: Callable) -> None:
        try:
            plan = parse_sql(sql_text)
            body = translate(plan)
        except IllegalArgumentError as e:
            on_done(None, e)
            return
        if plan["group_by"]:
            self._grouped(plan, body, on_done)
        elif any(i["kind"] == "agg" for i in plan["select"]):
            self._global_aggs(plan, body, on_done)
        else:
            self._rows(plan, body, on_done)

    # -- implicit global grouping -----------------------------------------

    def _global_aggs(self, plan, body, on_done) -> None:
        def cb(resp, err):
            if err is not None:
                on_done(None, err)
                return
            aggs = resp.get("aggregations") or {}
            row = []
            for item in plan["select"]:
                if item["fn"] == "COUNT" and item["arg"] == "*":
                    row.append(resp["hits"]["total"]["value"])
                else:
                    row.append((aggs.get(item["name"]) or {}).get("value"))
            names = [i["name"] for i in plan["select"]]
            on_done({"columns": [{"name": n, "type": _col_type([row], i)}
                                 for i, n in enumerate(names)],
                     "rows": [row]}, None)
        self.node.search_action.execute(plan["index"], body, cb)

    # -- plain SELECT ------------------------------------------------------

    def _rows(self, plan, body, on_done) -> None:
        def cb(resp, err):
            if err is not None:
                on_done(None, err)
                return
            hits = resp["hits"]["hits"]
            star = any(i["kind"] == "star" for i in plan["select"])
            if star:
                names: List[str] = []
                for h in hits:
                    for k in (h.get("_source") or {}):
                        if k not in names:
                            names.append(k)
                paths = {n: n for n in names}
            else:
                names = [i["name"] for i in plan["select"]]
                paths = {i["name"]: i["col"] for i in plan["select"]
                         if i["kind"] == "col"}
            rows = []
            for h in hits:
                src = h.get("_source") or {}
                rows.append([_field_from(src, paths.get(n, n))
                             for n in names])
            on_done({"columns": [{"name": n, "type": _col_type(rows, i)}
                                 for i, n in enumerate(names)],
                     "rows": rows}, None)
        self.node.search_action.execute(plan["index"], body, cb)

    # -- GROUP BY ----------------------------------------------------------

    def _grouped(self, plan, body, on_done) -> None:
        def cb(resp, err):
            if err is not None:
                on_done(None, err)
                return
            buckets = resp["aggregations"]["groups"]["buckets"]
            names = [i["name"] for i in plan["select"]]
            rows = []
            for b in buckets:
                row = []
                for item in plan["select"]:
                    if item["kind"] == "col":
                        row.append(b["key"].get(item["col"]))
                    elif item["kind"] == "star" or (
                            item["fn"] == "COUNT" and item["arg"] == "*"):
                        row.append(b["doc_count"])
                    else:
                        # COUNT(col) rides its value_count agg, so docs
                        # missing the column are excluded, unlike doc_count
                        row.append((b.get(item["name"]) or {}).get("value"))
                rows.append(row)
            # ORDER BY on group keys or aggregate aliases, host-side
            # (validated against SELECT names before execution)
            for col, direction in reversed(plan["order_by"]):
                idx = names.index(col)
                rows.sort(key=lambda r: (r[idx] is None, r[idx]),
                          reverse=(direction == "desc"))
            if plan["limit"] is not None:
                rows = rows[: plan["limit"]]
            on_done({"columns": [{"name": n, "type": _col_type(rows, i)}
                                 for i, n in enumerate(names)],
                     "rows": rows}, None)
        self.node.search_action.execute(plan["index"], body, cb)


def _col_type(rows: List[List[Any]], i: int) -> str:
    for row in rows:
        v = row[i]
        if isinstance(v, bool):
            return "boolean"
        if isinstance(v, int):
            return "long"
        if isinstance(v, float):
            return "double"
        if isinstance(v, str):
            return "keyword"
    return "null"
