"""Anomaly detection jobs: bucketed metrics scored against an online
baseline, results queryable as records.

Reference: x-pack/plugin/ml — anomaly detection jobs run in external C++
autodetect processes fed by datafeeds (NativeAutodetectProcessFactory,
DatafeedJob), modeling per-bucket metric distributions and emitting
record/bucket anomaly scores. SURVEY singles this native boundary out
for a TPU-native re-design: here the datafeed is the node's own
date_histogram aggregation (device partial-aggs), and the model is an
exponentially-decayed Gaussian baseline per (detector, by-field value)
scored in one vectorized pass — the autodetect process collapsed into
the data plane. Supported detector functions: count, mean, sum, min,
max, high_count, low_count, high_mean, low_mean.

Results land in ``.ml-anomalies-<job>`` as record docs
(record_score 0..100, actual, typical, timestamp), the reference's
results-index shape.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.utils.errors import (
    IllegalArgumentError, ResourceNotFoundError,
)

logger = logging.getLogger(__name__)

SECTION = "ml_jobs"
TICK = 2.0
# decay of the baseline toward new data (one-sided EWMA; the reference
# decays model memory similarly per bucket)
ALPHA = 0.3
MIN_BUCKETS_TO_SCORE = 3


class _Baseline:
    """Online Gaussian with exponential decay (Welford + EWMA hybrid)."""

    __slots__ = ("n", "mean", "var")

    def __init__(self) -> None:
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def score(self, x: float, sided: str = "both") -> float:
        """Anomaly score 0..100 BEFORE updating with x."""
        if self.n < MIN_BUCKETS_TO_SCORE:
            return 0.0
        # variance floor: 0.1% of the mean, RELATIVE only. This keeps
        # float jitter on large gauges quiet (jitter scales with the
        # mean) while sub-unit-scale metrics (rates in 0..1) keep full
        # sensitivity — an absolute floor blinded them entirely. A
        # perfectly constant stream that suddenly steps DOES score
        # maximally; that is deliberate: deviation from a zero-variance
        # baseline is the strongest possible anomaly signal (the
        # reference's autodetect flags it the same way).
        floor_std = max(0.001 * abs(self.mean), 1e-9)
        std = math.sqrt(max(self.var, floor_std * floor_std))
        z = (x - self.mean) / std if std > 0 else 0.0
        if sided == "high":
            z = max(z, 0.0)
        elif sided == "low":
            z = max(-z, 0.0)
        else:
            z = abs(z)
        # squash |z| to 0..100: z=3 ~ 39, z=6 ~ 78, z>=10 ~ 97
        return 100.0 * (1.0 - math.exp(-max(z - 2.0, 0.0) / 3.0))

    def update(self, x: float) -> None:
        if self.n == 0:
            self.mean = x
            self.var = 0.0
        else:
            delta = x - self.mean
            self.mean += ALPHA * delta
            self.var = (1 - ALPHA) * (self.var + ALPHA * delta * delta)
        self.n += 1


_FUNCTIONS = {"count", "sum", "mean", "avg", "min", "max",
              "high_count", "low_count", "high_mean", "low_mean"}


def _sidedness(fn: str) -> str:
    if fn.startswith("high_"):
        return "high"
    if fn.startswith("low_"):
        return "low"
    return "both"


def _base_fn(fn: str) -> str:
    for prefix in ("high_", "low_"):
        if fn.startswith(prefix):
            fn = fn[len(prefix):]
    return {"mean": "avg"}.get(fn, fn)


class MlJobService:
    """Job registry + the master-side bucket processor (DatafeedJob +
    autodetect collapsed)."""

    def __init__(self, node) -> None:
        self.node = node
        self._running = False
        self._timer = None
        # job -> {"baselines": {(det_idx, by_value): _Baseline},
        #         "ckpt": last processed bucket ts, "busy": bool,
        #         "records": int, "buckets": int}
        self._state: Dict[str, Dict[str, Any]] = {}

    def start(self) -> None:
        self._running = True
        self._schedule()

    def stop(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()

    def _schedule(self) -> None:
        if not self._running:
            return
        self._timer = self.node.scheduler.schedule(TICK, self._tick)

    def _tick(self) -> None:
        if not self._running:
            return
        try:
            if self.node.coordinator.mode == "LEADER":
                defs = self._defs()
                # prune runtime state of deleted jobs — the DELETE may
                # have landed on another node, and a recreated job with
                # the same id must not inherit dead baselines/ckpt
                for stale in [j for j in self._state if j not in defs]:
                    self._state.pop(stale, None)
                for job_id, d in defs.items():
                    st = self._state.setdefault(job_id, {})
                    if d.get("opened") and not st.get("busy"):
                        self._process(job_id, d)
        except Exception:  # noqa: BLE001
            logger.exception("ml tick failed")
        self._schedule()

    def _defs(self) -> Dict[str, Any]:
        return dict(self.node._applied_state()
                    .metadata.custom.get(SECTION, {}))

    # -- API --------------------------------------------------------------

    def put_job(self, job_id: str, body: Dict[str, Any],
                on_done: Callable) -> None:
        if job_id in self._defs():
            err = IllegalArgumentError(
                f"The job cannot be created with the Id '{job_id}'. "
                f"The Id is already used (resource_already_exists)")
            err.status = 409
            on_done(None, err)
            return
        body = dict(body or {})
        analysis = body.get("analysis_config") or {}
        detectors = analysis.get("detectors") or []
        if not detectors:
            on_done(None, IllegalArgumentError(
                "ml job requires [analysis_config.detectors]"))
            return
        for det in detectors:
            fn = det.get("function")
            if fn not in _FUNCTIONS:
                on_done(None, IllegalArgumentError(
                    f"unsupported detector function [{fn}]; supported: "
                    f"{sorted(_FUNCTIONS)}"))
                return
            if _base_fn(fn) != "count" and not det.get("field_name"):
                on_done(None, IllegalArgumentError(
                    f"detector function [{fn}] requires [field_name]"))
                return
        datafeed = body.get("datafeed_config") or {}
        if not datafeed.get("indices"):
            on_done(None, IllegalArgumentError(
                "ml job requires [datafeed_config.indices]"))
            return
        body.setdefault("opened", False)
        from elasticsearch_tpu.action.admin import PUT_CUSTOM
        self.node.master_client.execute(
            PUT_CUSTOM, {"section": SECTION, "name": job_id, "body": body},
            lambda r, e: on_done(
                {"job_id": job_id, "acknowledged": True}
                if e is None else None, e))

    def delete_job(self, job_id: str, on_done: Callable) -> None:
        if job_id not in self._defs():
            on_done(None, ResourceNotFoundError(
                f"ml job [{job_id}] not found"))
            return
        self._state.pop(job_id, None)
        from elasticsearch_tpu.action.admin import DELETE_CUSTOM
        self.node.master_client.execute(
            DELETE_CUSTOM, {"section": SECTION, "name": job_id},
            lambda r, e: on_done({"acknowledged": True}
                                 if e is None else None, e))

    def set_opened(self, job_id: str, opened: bool,
                   on_done: Callable) -> None:
        defs = self._defs()
        if job_id not in defs:
            on_done(None, ResourceNotFoundError(
                f"ml job [{job_id}] not found"))
            return
        cfg = dict(defs[job_id])
        cfg["opened"] = opened
        from elasticsearch_tpu.action.admin import PUT_CUSTOM
        self.node.master_client.execute(
            PUT_CUSTOM, {"section": SECTION, "name": job_id, "body": cfg},
            lambda r, e: on_done({"opened" if opened else "closed": True}
                                 if e is None else None, e))

    def jobs(self, job_id: Optional[str] = None) -> Dict[str, Any]:
        out = []
        for jid, d in sorted(self._defs().items()):
            if job_id is not None and jid != job_id:
                continue
            st = self._state.get(jid, {})
            out.append({
                "job_id": jid,
                "state": "opened" if d.get("opened") else "closed",
                "analysis_config": d.get("analysis_config", {}),
                "data_counts": {
                    "processed_bucket_count": st.get("buckets", 0),
                    "record_count": st.get("records", 0)}})
        if job_id is not None and not out:
            raise ResourceNotFoundError(f"ml job [{job_id}] not found")
        return {"count": len(out), "jobs": out}

    def records(self, job_id: str, on_done: Callable,
                min_score: float = 0.0, from_: int = 0,
                size: int = 100, desc: bool = False) -> None:
        def cb(resp, err):
            if err is not None:
                from elasticsearch_tpu.utils.errors import (
                    IndexNotFoundError,
                )
                if isinstance(err, IndexNotFoundError):
                    # no anomalies recorded yet: empty result set
                    on_done({"count": 0, "records": []}, None)
                else:
                    # overload/outage must NOT read as "no anomalies"
                    on_done(None, err)
                return
            records = [h["_source"] for h in resp["hits"]["hits"]]
            on_done({"count": resp["hits"]["total"]["value"],
                     "records": records}, None)
        self.node.search_action.execute(
            f".ml-anomalies-{job_id}",
            {"query": {"range": {"record_score": {"gte": min_score}}},
             "from": int(from_), "size": min(int(size), 1000),
             "track_total_hits": True,
             "sort": [{"timestamp": "desc" if desc else "asc"}]}, cb)

    # -- bucket processing -------------------------------------------------

    def _process(self, job_id: str, d: Dict[str, Any]) -> None:
        st = self._state.setdefault(job_id, {})
        st["busy"] = True
        analysis = d.get("analysis_config") or {}
        datafeed = d.get("datafeed_config") or {}
        span = str(analysis.get("bucket_span", "5m"))
        time_field = (d.get("data_description") or {}).get(
            "time_field", "@timestamp")
        detectors = analysis.get("detectors") or []
        indices = datafeed["indices"]
        index = ",".join(indices) if isinstance(indices, list) else indices

        aggs: Dict[str, Any] = {}
        for i, det in enumerate(detectors):
            fn = _base_fn(det.get("function", "count"))
            by = det.get("by_field_name")
            metric = ({"value_count": {"field": time_field}}
                      if fn == "count" and not det.get("field_name")
                      else {fn if fn != "count" else "value_count":
                            {"field": det.get("field_name", time_field)}})
            node: Dict[str, Any] = {f"m{i}": metric}
            if by:
                aggs[f"d{i}"] = {"terms": {"field": by, "size": 100},
                                 "aggs": node}
            else:
                aggs[f"d{i}"] = {"filter": {"match_all": {}},
                                 "aggs": node}
        body: Dict[str, Any] = {
            "size": 0,
            "query": datafeed.get("query", {"match_all": {}}),
            "aggs": {"buckets": {
                "date_histogram": {"field": time_field,
                                   "fixed_interval": span},
                "aggs": aggs}}}
        ckpt = st.get("ckpt")
        if ckpt is not None:
            # ckpt is the START of the first UNPROCESSED bucket (the one
            # held back as still-filling), so gte re-forms exactly it and
            # later data — never a bucket whose baseline update already
            # happened (baseline updates are not idempotent)
            body["query"] = {"bool": {"filter": [
                body["query"],
                {"range": {time_field: {"gte": ckpt}}}]}}

        def cb(resp, err):
            if err is not None:
                logger.warning("ml job [%s] datafeed failed: %s",
                               job_id, err)
                st["busy"] = False
                return
            all_buckets = ((resp.get("aggregations") or {})
                           .get("buckets") or {}).get("buckets", [])
            # the LAST bucket may still be filling: hold it back; its
            # start key becomes the next run's resume point
            buckets = all_buckets[:-1]
            records = self._score_buckets(job_id, d, st, detectors,
                                          buckets)
            if buckets:
                st["ckpt"] = all_buckets[-1]["key"]
                st["buckets"] = st.get("buckets", 0) + len(buckets)

            def written(_r=None):
                st["records"] = st.get("records", 0) + len(records)
                st["busy"] = False
            if records:
                self.node.bulk_action.execute(records, written)
            else:
                written()
        try:
            self.node.search_action.execute(index, body, cb)
        except Exception as e:  # noqa: BLE001
            logger.warning("ml job [%s] failed: %s", job_id, e)
            st["busy"] = False

    def _score_buckets(self, job_id, d, st, detectors, buckets
                       ) -> List[Dict[str, Any]]:
        baselines = st.setdefault("baselines", {})
        records: List[Dict[str, Any]] = []
        for b in buckets:
            ts = b["key"]
            for i, det in enumerate(detectors):
                fn = det.get("function", "count")
                sided = _sidedness(fn)
                node = b.get(f"d{i}") or {}
                if "buckets" in node:        # by-field split
                    entries = [(e["key"],
                                self._metric_value(e, i, det, e))
                               for e in node["buckets"]]
                else:
                    entries = [(None, self._metric_value(node, i, det, b))]
                for by_value, actual in entries:
                    if actual is None:
                        continue
                    key = (i, by_value)
                    base = baselines.get(key)
                    if base is None:
                        base = baselines[key] = _Baseline()
                    score = base.score(actual, sided)
                    typical = base.mean
                    base.update(actual)
                    if score >= float(
                            d.get("min_record_score", 30.0)):
                        rec = {
                            "job_id": job_id, "result_type": "record",
                            "timestamp": ts, "detector_index": i,
                            "function": fn,
                            "field_name": det.get("field_name"),
                            "record_score": round(score, 2),
                            "actual": actual,
                            "typical": round(typical, 4),
                        }
                        if by_value is not None:
                            rec["by_field_value"] = by_value
                        records.append({
                            "action": "index",
                            "index": f".ml-anomalies-{job_id}",
                            "id": f"{job_id}-{ts}-{i}-{by_value}",
                            "source": rec})
        return records

    def _metric_value(self, node, i, det, bucket) -> Optional[float]:
        fn = _base_fn(det.get("function", "count"))
        if fn == "count" and not det.get("field_name"):
            v = bucket.get("doc_count")
            return float(v) if v is not None else None
        m = node.get(f"m{i}") or {}
        v = m.get("value")
        return float(v) if v is not None else None
