"""EQL: event query language over timestamped event indices.

Reference: x-pack/plugin/eql — an ANTLR grammar compiling to the shared
ql planner, executed as search requests plus a sequence state machine
(x-pack/plugin/eql/src/main/java/org/elasticsearch/xpack/eql/execution/
sequence/TumblingWindow.java). This build hand-rolls the recursive-descent
parser and compiles conditions straight onto the query DSL; sequences run
as one filtered, time-ordered sweep joined host-side by key — the
TumblingWindow's job collapsed into a single pass, practical because the
per-stage candidate sets come back from the device top-k already sorted.

Supported surface:
  <category> where <condition>
  sequence [by f1, f2] [with maxspan=<N><unit>]
      [cat1 where c1] [cat2 where c2] ...
  condition: comparisons (== != < <= > >=), and/or/not, parentheses,
      field in ("a", "b"), like~ / like "wild*card", field regex~ "...",
      true/false/null literals, function calls length(f), wildcard(f, p)
Pipes: | head N, | tail N.

POST /{index}/_eql/search with {"query": "..."}; events responses carry
hits.events, sequence responses hits.sequences with join_keys.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.utils.errors import IllegalArgumentError

DEFAULT_SIZE = 10
SWEEP_SIZE = 10_000          # events fetched per sequence sweep

_TOKEN_RX = re.compile(r"""
    \s*(?:
      (?P<num>-?\d+(?:\.\d+)?)
    | "(?P<dstr>(?:[^"\\]|\\.)*)"
    | '(?P<sstr>(?:[^'\\]|\\.)*)'
    | (?P<op>==|!=|<=|>=|=|<|>|\(|\)|\[|\]|,|\|)
    | (?P<word>[A-Za-z_][A-Za-z0-9_.]*~?)
    )""", re.VERBOSE)

_UNITS_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000}


def tokenize(text: str) -> List[Tuple[str, Any]]:
    out: List[Tuple[str, Any]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RX.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise IllegalArgumentError(
                f"EQL: cannot tokenize at [{text[pos:pos + 20]!r}]")
        pos = m.end()
        if m.group("num") is not None:
            n = float(m.group("num"))
            out.append(("num", int(n) if n.is_integer() else n))
        elif m.group("dstr") is not None:
            out.append(("str", re.sub(r"\\(.)", r"\1", m.group("dstr"))))
        elif m.group("sstr") is not None:
            out.append(("str", re.sub(r"\\(.)", r"\1", m.group("sstr"))))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            out.append(("word", m.group("word")))
    return out


class _P:
    def __init__(self, toks: List[Tuple[str, Any]]):
        self.toks = toks
        self.i = 0

    def peek(self) -> Optional[Tuple[str, Any]]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Tuple[str, Any]:
        t = self.peek()
        if t is None:
            raise IllegalArgumentError("EQL: unexpected end of query")
        self.i += 1
        return t

    def eat_word(self, *words: str) -> bool:
        t = self.peek()
        if t is not None and t[0] == "word" and t[1].lower() in words:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        t = self.next()
        if t != ("op", op):
            raise IllegalArgumentError(f"EQL: expected [{op}], got {t}")


# ---------------------------------------------------------------------------
# condition -> DSL body
# ---------------------------------------------------------------------------

def _cond_or(p: _P) -> Dict[str, Any]:
    left = _cond_and(p)
    clauses = [left]
    while p.eat_word("or"):
        clauses.append(_cond_and(p))
    if len(clauses) == 1:
        return left
    return {"bool": {"should": clauses, "minimum_should_match": 1}}


def _cond_and(p: _P) -> Dict[str, Any]:
    left = _cond_not(p)
    clauses = [left]
    while p.eat_word("and"):
        clauses.append(_cond_not(p))
    if len(clauses) == 1:
        return left
    return {"bool": {"filter": clauses}}


def _cond_not(p: _P) -> Dict[str, Any]:
    if p.eat_word("not"):
        inner = _cond_not(p)
        return {"bool": {"must_not": [inner]}}
    return _cond_cmp(p)


def _literal(p: _P) -> Any:
    t = p.next()
    if t[0] in ("num", "str"):
        return t[1]
    if t[0] == "word":
        w = t[1].lower()
        if w == "true":
            return True
        if w == "false":
            return False
        if w == "null":
            return None
    raise IllegalArgumentError(f"EQL: expected a literal, got {t}")


def _cond_cmp(p: _P) -> Dict[str, Any]:
    t = p.peek()
    if t == ("op", "("):
        p.next()
        inner = _cond_or(p)
        p.expect_op(")")
        return inner
    t = p.next()
    if t[0] != "word":
        raise IllegalArgumentError(f"EQL: expected a field, got {t}")
    field = t[1]
    nxt = p.peek()
    # bare boolean condition: 'where true' / 'where false'
    if field.lower() in ("true", "false") and (
            nxt is None or nxt[0] != "op" or nxt[1] in (")", "]", "|")):
        if field.lower() == "true":
            return {"match_all": {}}
        return {"bool": {"must_not": [{"match_all": {}}]}}
    if nxt is None:
        raise IllegalArgumentError(
            f"EQL: dangling field [{field}] without an operator")
    if nxt[0] == "op":
        op = p.next()[1]
        value = _literal(p)
        if op == "=":
            op = "=="
        if op == "==":
            if value is None:
                return {"bool": {"must_not": [{"exists": {"field": field}}]}}
            return {"term": {field: value}}
        if op == "!=":
            if value is None:
                return {"exists": {"field": field}}
            return {"bool": {"must_not": [{"term": {field: value}}]}}
        rng = {">": "gt", ">=": "gte", "<": "lt", "<=": "lte"}[op]
        return {"range": {field: {rng: value}}}
    if nxt[0] == "word" and nxt[1].lower() == "in":
        p.next()
        p.expect_op("(")
        values = [_literal(p)]
        while p.peek() == ("op", ","):
            p.next()
            values.append(_literal(p))
        p.expect_op(")")
        return {"terms": {field: values}}
    if nxt[0] == "word" and nxt[1].lower() in ("like", "like~"):
        p.next()
        pat = _literal(p)
        return {"wildcard": {field: {"value": str(pat)}}}
    if nxt[0] == "word" and nxt[1].lower() in ("regex", "regex~"):
        p.next()
        pat = _literal(p)
        return {"regexp": {field: {"value": str(pat)}}}
    raise IllegalArgumentError(
        f"EQL: unsupported operator after [{field}]: {nxt}")


# ---------------------------------------------------------------------------
# query parsing
# ---------------------------------------------------------------------------

def _parse_stage(p: _P, category_field: str) -> Dict[str, Any]:
    """'<category> where <cond>' -> filter body."""
    t = p.next()
    if t[0] != "word":
        raise IllegalArgumentError(f"EQL: expected event category, got {t}")
    category = t[1]
    if not p.eat_word("where"):
        raise IllegalArgumentError("EQL: expected [where]")
    cond = _cond_or(p)
    clauses: List[Dict[str, Any]] = []
    if category != "any":
        clauses.append({"term": {category_field: category}})
    clauses.append(cond)
    return {"bool": {"filter": clauses}}


def parse_eql(text: str, category_field: str = "event.category"
              ) -> Dict[str, Any]:
    p = _P(tokenize(text))
    out: Dict[str, Any] = {"pipes": []}
    if p.eat_word("sequence"):
        by: List[str] = []
        maxspan: Optional[float] = None
        if p.eat_word("by"):
            t = p.next()
            by.append(t[1])
            while p.peek() == ("op", ","):
                p.next()
                by.append(p.next()[1])
        if p.eat_word("with"):
            t = p.next()
            if t[0] != "word" or t[1].lower() != "maxspan":
                raise IllegalArgumentError("EQL: expected maxspan=<span>")
            if p.peek() in (("op", "="), ("op", "==")):
                p.next()
            span_t = p.next()
            if span_t[0] == "num":
                # "10s" tokenizes as num 10 + unit word
                unit = p.peek()
                if unit is not None and unit[0] == "word" and \
                        unit[1].lower() in _UNITS_MS:
                    maxspan = float(span_t[1]) * \
                        _UNITS_MS[p.next()[1].lower()]
                else:
                    maxspan = float(span_t[1])
            else:
                maxspan = _span_ms(span_t)
        stages = []
        stage_by: List[List[str]] = []
        while p.peek() == ("op", "["):
            p.next()
            stages.append(_parse_stage(p, category_field))
            p.expect_op("]")
            # per-stage "by" keys JOIN POSITIONALLY across stages
            # ([a] by src [b] by dest joins a.src == b.dest); the global
            # "sequence by" keys prefix every stage's list
            sb: List[str] = []
            if p.eat_word("by"):
                sb.append(p.next()[1])
                while p.peek() == ("op", ","):
                    p.next()
                    sb.append(p.next()[1])
            stage_by.append(sb)
        if len(stages) < 2:
            raise IllegalArgumentError(
                "EQL: sequence requires at least 2 stages")
        arities = {len(sb) for sb in stage_by}
        if len(arities) > 1:
            raise IllegalArgumentError(
                "EQL: every sequence stage must declare the same number "
                "of [by] keys")
        out.update({"kind": "sequence", "stages": stages, "by": by,
                    "stage_by": stage_by, "maxspan_ms": maxspan})
    else:
        out.update({"kind": "event",
                    "filter": _parse_stage(p, category_field)})
    while p.peek() == ("op", "|"):
        p.next()
        t = p.next()
        if t[0] != "word" or t[1].lower() not in ("head", "tail"):
            raise IllegalArgumentError(f"EQL: unsupported pipe {t}")
        n = p.next()
        if n[0] != "num":
            raise IllegalArgumentError("EQL: pipe requires a count")
        out["pipes"].append((t[1].lower(), int(n[1])))
    if p.peek() is not None:
        raise IllegalArgumentError(
            f"EQL: trailing input at {p.peek()}")
    return out


def _span_ms(tok: Tuple[str, Any]) -> float:
    if tok[0] == "num":
        return float(tok[1])
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)", str(tok[1]))
    if not m:
        raise IllegalArgumentError(f"EQL: bad maxspan [{tok[1]}]")
    return float(m.group(1)) * _UNITS_MS[m.group(2)]


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

class EqlService:
    """Compiles and runs EQL searches against the node's search action
    (TransportEqlSearchAction analog)."""

    def __init__(self, node) -> None:
        self.node = node

    def search(self, index: str, body: Dict[str, Any],
               on_done: Callable) -> None:
        text = (body or {}).get("query")
        if not text:
            on_done(None, IllegalArgumentError(
                "EQL search requires [query]"))
            return
        ts_field = (body or {}).get("timestamp_field", "@timestamp")
        cat_field = (body or {}).get("event_category_field",
                                     "event.category")
        size = int((body or {}).get("size", DEFAULT_SIZE))
        try:
            plan = parse_eql(text, category_field=cat_field)
        except IllegalArgumentError as e:
            on_done(None, e)
            return
        if plan["kind"] == "event":
            self._event_search(index, plan, ts_field, size, on_done)
        else:
            self._sequence_search(index, plan, ts_field, size, on_done)

    def _apply_pipes(self, rows: List[Any], pipes) -> List[Any]:
        for kind, n in pipes:
            rows = rows[:n] if kind == "head" else rows[-n:]
        return rows

    def _event_search(self, index, plan, ts_field, size, on_done) -> None:
        want = size
        for kind, n in plan["pipes"]:
            want = max(want, n)
            if kind == "tail":
                # tail needs the LAST events overall, not the last of a
                # truncated ascending window
                want = SWEEP_SIZE

        def cb(resp, err):
            if err is not None:
                on_done(None, err)
                return
            hits = resp["hits"]["hits"]
            # a tail over a window that filled up may be missing the true
            # latest events — report partiality like the sequence path
            truncated = any(k == "tail" for k, _n in plan["pipes"]) \
                and len(hits) >= SWEEP_SIZE
            hits = self._apply_pipes(hits, plan["pipes"])[:size]
            on_done({
                "is_partial": truncated, "timed_out": False,
                "hits": {"total": resp["hits"]["total"],
                         "events": [self._event(h) for h in hits]}}, None)
        self.node.search_action.execute(index, {
            "query": plan["filter"], "size": max(want, size),
            "sort": [{ts_field: "asc"}]}, cb)

    def _event(self, hit) -> Dict[str, Any]:
        return {"_index": hit.get("_index"), "_id": hit["_id"],
                "_source": hit.get("_source", {})}

    def _sequence_search(self, index, plan, ts_field, size,
                         on_done) -> None:
        """One time-ordered sweep per stage, then a host-side ordered join
        keyed by the by-fields (TumblingWindow collapsed — sound for
        result sets within SWEEP_SIZE, reported via is_partial)."""
        stages = plan["stages"]
        results: List[Optional[List[Dict[str, Any]]]] = [None] * len(stages)
        pending = {"n": len(stages), "err": None}

        def stage_cb(idx):
            def cb(resp, err):
                if err is not None:
                    pending["err"] = pending["err"] or err
                else:
                    results[idx] = resp["hits"]["hits"]
                pending["n"] -= 1
                if pending["n"] == 0:
                    if pending["err"] is not None:
                        on_done(None, pending["err"])
                        return
                    self._join(plan, results, ts_field, size, on_done)
            return cb

        for i, stage in enumerate(stages):
            self.node.search_action.execute(index, {
                "query": stage, "size": SWEEP_SIZE,
                "sort": [{ts_field: "asc"}]}, stage_cb(i))

    def _join(self, plan, results, ts_field, size, on_done) -> None:
        by = plan["by"]
        stage_by = plan.get("stage_by") or [[] for _ in results]
        maxspan = plan["maxspan_ms"]
        from elasticsearch_tpu.mapping.mappers import parse_date_millis

        def key_of(hit, stage_idx: int):
            src = hit.get("_source", {})
            fields = list(by) + list(stage_by[stage_idx])
            return tuple(_dotted(src, f) for f in fields)

        def ts_of(hit):
            src = hit.get("_source", {})
            v = _dotted(src, ts_field)
            try:
                return parse_date_millis(v)
            except Exception:  # noqa: BLE001 — unparseable ts sorts first
                return 0.0

        # per stage: key -> time-ordered events
        staged: List[Dict[Any, List[Tuple[float, Dict]]]] = []
        for si, hits in enumerate(results):
            d: Dict[Any, List[Tuple[float, Dict]]] = {}
            for h in hits:
                d.setdefault(key_of(h, si), []).append((ts_of(h), h))
            for lst in d.values():
                lst.sort(key=lambda x: x[0])
            staged.append(d)

        sequences = []
        for key in staged[0]:
            if any(key not in d for d in staged[1:]):
                continue
            # greedy earliest-completion matching per key, non-reusing
            used = [set() for _ in staged]
            while True:
                seq = self._match_one(staged, key, used, maxspan)
                if seq is None:
                    break
                sequences.append((key, seq))
        sequences.sort(key=lambda s: s[1][-1][0])   # by completion time
        sequences = self._apply_pipes(sequences, plan["pipes"])[:size]
        on_done({
            "is_partial": any(len(r) >= SWEEP_SIZE for r in results),
            "timed_out": False,
            "hits": {"total": {"value": len(sequences),
                               "relation": "eq"},
                     "sequences": [{
                         "join_keys": list(k),
                         "events": [self._event(h) for _t, h in seq]}
                         for k, seq in sequences]}}, None)

    def _match_one(self, staged, key, used, maxspan):
        """Earliest sequence of one event per stage, strictly ordered in
        time, within maxspan of the first event; events are consumed."""
        first_list = staged[0][key]
        for i0, (t0, h0) in enumerate(first_list):
            if i0 in used[0]:
                continue
            chosen = [(t0, h0)]
            idxs = [i0]
            ok = True
            t_prev = t0
            for s in range(1, len(staged)):
                found = False
                for j, (t, h) in enumerate(staged[s][key]):
                    if j in used[s] or t < t_prev:
                        continue
                    if maxspan is not None and t - t0 > maxspan:
                        break
                    chosen.append((t, h))
                    idxs.append(j)
                    t_prev = t
                    found = True
                    break
                if not found:
                    ok = False
                    break
            if ok:
                for s, j in enumerate(idxs):
                    used[s].add(j)
                return chosen
        return None


def _dotted(src: Dict[str, Any], path: str) -> Any:
    node: Any = src
    for part in path.split("."):
        if isinstance(node, dict):
            node = node.get(part)
        else:
            return None
    return node
