"""Monitoring: periodic collection of node/cluster stats into a
monitoring index.

Reference: x-pack/plugin/monitoring — Collector subclasses snapshot
cluster/node/index stats on an interval and an Exporter bulk-writes them
to ``.monitoring-es-*`` (LocalExporter). This build keeps the local
exporter shape: every collection interval the elected master writes one
``cluster_stats``-type doc and one ``node_stats`` doc per node into the
monitoring index, queryable through the ordinary search path.
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Dict

logger = logging.getLogger(__name__)

MONITORING_INDEX = ".monitoring-es"
INTERVAL = 5.0


class MonitoringService:
    def __init__(self, node) -> None:
        self.node = node
        self._running = False
        self._timer = None
        self._seq = itertools.count()
        self.collections = 0
        # the reference gates collection on the dynamic cluster setting
        # xpack.monitoring.collection.enabled; read it live each tick
        self.enabled = False

    def start(self) -> None:
        self._running = True
        self._schedule()

    def stop(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def _schedule(self) -> None:
        if not self._running:
            return
        self._timer = self.node.scheduler.schedule(INTERVAL, self._tick)

    def _collection_enabled(self) -> bool:
        if self.enabled:
            return True
        try:
            settings = self.node._applied_state() \
                .metadata.persistent_settings
            return bool(settings.get(
                "xpack.monitoring.collection.enabled"))
        except Exception:  # noqa: BLE001
            return False

    def _tick(self) -> None:
        if not self._running:
            return
        try:
            if self._collection_enabled() and \
                    self.node.coordinator.mode == "LEADER":
                self.collect_now()
        except Exception:  # noqa: BLE001
            logger.exception("monitoring collection failed")
        self._schedule()

    def collect_now(self) -> None:
        """One collection: cluster doc + per-node docs (Collector +
        LocalExporter, collapsed)."""
        state = self.node._applied_state()
        ts = self.node.scheduler.now()
        seq = next(self._seq)
        items = [{
            "action": "index", "index": MONITORING_INDEX,
            "id": f"cluster-{seq}",
            "source": {
                "type": "cluster_stats", "timestamp": ts,
                "cluster_uuid": getattr(state, "cluster_uuid", "local"),
                "version": state.version,
                "nodes": len(state.nodes),
                "indices": len(state.metadata.indices),
                "status": self._health(state),
            }}]
        self.collections += 1

        def with_stats(resp, _err=None):
            for nid, stats in sorted(
                    ((resp or {}).get("nodes") or {}).items()):
                items.append({
                    "action": "index", "index": MONITORING_INDEX,
                    "id": f"node-{nid}-{seq}",
                    "source": {"type": "node_stats", "timestamp": ts,
                               "node_id": nid,
                               "node_stats": _shallow(stats)}})
            self.node.bulk_action.execute(items, lambda _r=None: None)
        # one node_stats doc PER CLUSTER NODE via the transport fan-out
        self.node.client.nodes_stats_all(with_stats)

    def _health(self, state) -> str:
        try:
            from elasticsearch_tpu.action.admin import cluster_health
            return cluster_health(state)["status"]
        except Exception:  # noqa: BLE001
            return "unknown"

    def stats(self) -> Dict[str, Any]:
        return {"enabled": self.enabled,
                "collections": self.collections,
                "interval_s": INTERVAL}


def _shallow(stats: Dict[str, Any]) -> Dict[str, Any]:
    """Keep the doc bounded: top-level scalars + one nesting level."""
    out: Dict[str, Any] = {}
    for k, v in (stats or {}).items():
        if isinstance(v, (int, float, str, bool)):
            out[k] = v
        elif isinstance(v, dict):
            out[k] = {k2: v2 for k2, v2 in v.items()
                      if isinstance(v2, (int, float, str, bool))}
    return out
