"""Graph explore: significant-term vertices and co-occurrence edges.

Reference: x-pack/plugin/graph TransportGraphExploreAction — hops of
sampled significant-terms frontiers, connections scored by shared-doc
overlap. This build runs each hop as a sampler+significant_terms
aggregation through the node's own search path and derives edges from
per-pair doc co-occurrence counts (adjacency-style filters), keeping the
response shape (vertices[], connections[] with weight/doc_count).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

from elasticsearch_tpu.utils.errors import IllegalArgumentError

DEFAULT_SIZE = 5
SAMPLE_SIZE = 1000


class GraphService:
    def __init__(self, node) -> None:
        self.node = node

    def explore(self, index: str, body: Dict[str, Any],
                on_done: Callable) -> None:
        body = body or {}
        query = body.get("query", {"match_all": {}})
        vertices_spec = (body.get("vertices")
                         or (body.get("controls") or {}).get("vertices"))
        if not vertices_spec:
            on_done(None, IllegalArgumentError(
                "graph explore requires [vertices]"))
            return
        fields: List[Tuple[str, int]] = []
        for v in vertices_spec:
            fields.append((v["field"], int(v.get("size", DEFAULT_SIZE))))
        use_sig = bool((body.get("controls") or {})
                       .get("use_significance", True))

        aggs: Dict[str, Any] = {}
        for fname, size in fields:
            agg_kind = "significant_terms" if use_sig else "terms"
            aggs[f"v_{fname}"] = {agg_kind: {"field": fname, "size": size}}
        req = {"size": 0, "query": query, "aggs": {
            "sample": {"sampler": {"shard_size": SAMPLE_SIZE},
                       "aggs": aggs}}}

        def cb(resp, err):
            if err is not None:
                on_done(None, err)
                return
            sample = (resp.get("aggregations") or {}).get("sample") or {}
            vertices = []
            for fname, _size in fields:
                node_out = sample.get(f"v_{fname}") or {}
                for b in node_out.get("buckets", []):
                    vertices.append({
                        "field": fname, "term": b["key"],
                        "weight": float(b.get("score", b["doc_count"])),
                        "depth": 0})
            if len(vertices) < 2:
                on_done({"took": resp.get("took", 0), "timed_out": False,
                         "vertices": vertices, "connections": []}, None)
                return
            self._connections(index, query, vertices, resp, on_done)
        self.node.search_action.execute(index, req, cb)

    def _connections(self, index, query, vertices, first_resp,
                     on_done) -> None:
        """Pairwise co-occurrence via one adjacency_matrix request."""
        filters = {}
        for i, v in enumerate(vertices):
            filters[str(i)] = {"term": {v["field"]: v["term"]}}
        req = {"size": 0, "query": query, "aggs": {
            "adj": {"adjacency_matrix": {"filters": filters}}}}

        def cb(resp, err):
            if err is not None:
                on_done(None, err)
                return
            connections = []
            adj = (resp.get("aggregations") or {}).get("adj") or {}
            for b in adj.get("buckets", []):
                key = b["key"]
                if "&" not in key:
                    continue
                a, c = key.split("&", 1)
                connections.append({
                    "source": int(a), "target": int(c),
                    "weight": float(b["doc_count"]),
                    "doc_count": b["doc_count"]})
            on_done({"took": first_resp.get("took", 0),
                     "timed_out": False,
                     "vertices": vertices,
                     "connections": connections}, None)
        self.node.search_action.execute(index, req, cb)
