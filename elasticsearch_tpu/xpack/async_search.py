"""Async search: submit now, fetch the (partial) response later.

Reference: x-pack/plugin/async-search — TransportSubmitAsyncSearchAction
keeps a mutable search task whose response can be polled by id, with a
wait_for_completion_timeout fast path and keep-alive-based expiry. Here
the search runs through the ordinary TransportSearchAction (as a
cancellable task) and the coordinator keeps the async state in memory;
ids are node-local like the reference's pre-index-persistence behavior.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, Optional

from elasticsearch_tpu.utils.errors import ResourceNotFoundError
from elasticsearch_tpu.utils.settings import parse_time_to_seconds

DEFAULT_WAIT = 1.0
DEFAULT_KEEP_ALIVE = 5 * 60.0


class AsyncSearchService:
    def __init__(self, node) -> None:
        self.node = node
        self._searches: Dict[str, Dict[str, Any]] = {}

    # -- lifecycle --------------------------------------------------------

    def _reap(self) -> None:
        now = self.node.scheduler.now()
        for sid in [s for s, e in self._searches.items()
                    if e["expiration"] < now]:
            del self._searches[sid]

    def _status(self, entry: Dict[str, Any]) -> Dict[str, Any]:
        out = {
            "id": entry["id"],
            "is_running": entry["running"],
            "is_partial": entry["running"] or entry["error"] is not None,
            "start_time_in_millis": int(entry["start"] * 1000),
            "expiration_time_in_millis": int(entry["expiration"] * 1000),
        }
        if entry["response"] is not None:
            out["response"] = entry["response"]
        if entry["error"] is not None:
            err = entry["error"]
            out["error"] = {"type": type(err).__name__, "reason": str(err)}
        return out

    # -- API --------------------------------------------------------------

    def submit(self, index_expression: str, body: Dict[str, Any],
               on_done, wait_for_completion: Any = None,
               keep_alive: Any = None, owner: Optional[str] = None) -> None:
        self._reap()
        wait_s = (parse_time_to_seconds(wait_for_completion)
                  if wait_for_completion is not None else DEFAULT_WAIT)
        keep_s = (parse_time_to_seconds(keep_alive)
                  if keep_alive is not None else DEFAULT_KEEP_ALIVE)
        sid = uuid.uuid4().hex
        now = self.node.scheduler.now()
        entry: Dict[str, Any] = {
            "id": sid, "running": True, "response": None, "error": None,
            "start": now, "expiration": now + keep_s, "owner": owner,
        }
        self._searches[sid] = entry
        responded = {"flag": False}

        def respond() -> None:
            if responded["flag"]:
                return
            responded["flag"] = True
            on_done(self._status(entry), None)

        def search_done(resp: Optional[Dict[str, Any]],
                        err: Optional[Exception]) -> None:
            entry["running"] = False
            entry["response"] = resp
            entry["error"] = err
            respond()

        self.node.search_action.execute(index_expression, body, search_done)
        # fast path: if the search beats the wait timeout, the submit call
        # returns the COMPLETE response; otherwise it returns the running
        # id and the client polls (SubmitAsyncSearchRequest semantics)
        self.node.scheduler.schedule(max(wait_s, 0.0), respond)

    def _owned(self, sid: str, owner: Optional[str]) -> Dict[str, Any]:
        entry = self._searches.get(sid)
        # a stored response is the OWNER's data: another principal gets
        # the same 404 as a nonexistent id (no existence oracle)
        if entry is None or entry.get("owner") != owner:
            raise ResourceNotFoundError(f"async search [{sid}] not found")
        return entry

    def get(self, sid: str, owner: Optional[str] = None) -> Dict[str, Any]:
        self._reap()
        return self._status(self._owned(sid, owner))

    def delete(self, sid: str, owner: Optional[str] = None
               ) -> Dict[str, Any]:
        self._reap()
        self._owned(sid, owner)
        del self._searches[sid]
        return {"acknowledged": True}
